"""Topology/placement tests with fake in-process clusters — mirrors the
reference's topology_test.go / volume_growth_test.go approach."""

import random

import pytest

from seaweedfs_tpu.storage.store import VolumeInfo
from seaweedfs_tpu.topology.node import DataNode
from seaweedfs_tpu.topology.sequence import MemorySequencer
from seaweedfs_tpu.topology.topology import Topology, VolumeGrowOption
from seaweedfs_tpu.topology.volume_growth import (VolumeGrowth,
                                                  target_count_per_grow)


def _vinfo(vid, collection="", size=0, read_only=False, rp=0, ttl=0,
           max_file_key=0):
    return VolumeInfo(id=vid, collection=collection, size=size,
                      file_count=0, delete_count=0, deleted_byte_count=0,
                      read_only=read_only, replica_placement=rp, ttl=ttl,
                      compact_revision=0, max_file_key=max_file_key)


def _cluster(topo, dcs=2, racks=2, nodes=2, max_volumes=10):
    """Build dc{i}/rack{j}/node ip 10.i.j.k:8080."""
    out = []
    for i in range(dcs):
        for j in range(racks):
            for k in range(nodes):
                dn = topo.register_data_node(
                    f"dc{i}", f"rack{j}", f"10.{i}.{j}.{k}", 8080,
                    max_volume_count=max_volumes)
                out.append(dn)
    return out


def test_register_and_counters():
    topo = Topology()
    nodes = _cluster(topo, dcs=1, racks=1, nodes=2, max_volumes=5)
    assert topo.max_volume_count == 10
    topo.register_volume(_vinfo(1), nodes[0])
    topo.register_volume(_vinfo(2), nodes[0])
    assert topo.volume_count == 2
    assert nodes[0].free_space() == 3
    assert topo.free_space() == 8


def test_full_sync_add_remove():
    topo = Topology()
    (dn,) = _cluster(topo, dcs=1, racks=1, nodes=1)
    new, deleted = topo.sync_data_node_registration(
        [_vinfo(1), _vinfo(2)], dn)
    assert [v.id for v in new] == [1, 2]
    new, deleted = topo.sync_data_node_registration([_vinfo(2)], dn)
    assert [v.id for v in deleted] == [1]
    assert topo.volume_count == 1
    assert topo.lookup("", 2) == [dn]
    assert topo.lookup("", 1) == []


def test_writable_requires_enough_replicas():
    topo = Topology()
    nodes = _cluster(topo, dcs=1, racks=1, nodes=2)
    v = _vinfo(5, rp=1)  # 001 -> 2 copies
    topo.register_volume(v, nodes[0])
    with pytest.raises(ValueError, match="no more writable"):
        topo.pick_for_write(1, VolumeGrowOption(replica_placement="001"))
    topo.register_volume(v, nodes[1])
    fid, count, locs = topo.pick_for_write(
        1, VolumeGrowOption(replica_placement="001"))
    assert count == 1 and len(locs) == 2
    vid = int(fid.split(",")[0])
    assert vid == 5


def test_oversized_not_writable():
    topo = Topology(volume_size_limit=1000)
    (dn,) = _cluster(topo, dcs=1, racks=1, nodes=1)
    topo.register_volume(_vinfo(1, size=2000), dn)
    with pytest.raises(ValueError):
        topo.pick_for_write(1, VolumeGrowOption())
    topo.register_volume(_vinfo(2, size=10), dn)
    fid, _, _ = topo.pick_for_write(1, VolumeGrowOption())
    assert fid.startswith("2,")


def test_dead_node_unregisters_volumes():
    topo = Topology()
    nodes = _cluster(topo, dcs=1, racks=1, nodes=2)
    v = _vinfo(1)
    topo.register_volume(v, nodes[0])
    assert topo.lookup("", 1) == [nodes[0]]
    topo.unregister_data_node(nodes[0])
    assert topo.lookup("", 1) == []
    # Counter hygiene: only node 1's capacity (10 slots) remains.
    assert topo.max_volume_count == 10
    assert topo.volume_count == 0


def test_sequencer_monotonic_and_restart(tmp_path):
    meta = str(tmp_path / "seq.dat")
    s = MemorySequencer(meta)
    a = s.next_file_id(10)
    b = s.next_file_id(1)
    assert b == a + 10
    s.set_max(5000)
    assert s.next_file_id() == 5001
    # Restart never reissues.
    s2 = MemorySequencer(meta)
    assert s2.next_file_id() > b


def test_heartbeat_raises_sequencer():
    topo = Topology()
    (dn,) = _cluster(topo, dcs=1, racks=1, nodes=1)
    topo.register_volume(_vinfo(1, max_file_key=999), dn)
    assert topo.next_file_key() >= 1000


def test_ec_shard_registration():
    from seaweedfs_tpu.ec.shard_bits import ShardBits
    topo = Topology()
    nodes = _cluster(topo, dcs=1, racks=1, nodes=2)
    bits_a = int(ShardBits(0).add_shard_id(0).add_shard_id(1))
    bits_b = int(ShardBits(0).add_shard_id(2))
    topo.register_ec_shards(7, "c", bits_a, nodes[0])
    topo.register_ec_shards(7, "c", bits_b, nodes[1])
    locs = topo.lookup_ec_shards(7)
    assert locs.locations[0] == [nodes[0]]
    assert locs.locations[2] == [nodes[1]]
    assert topo.ec_shard_count == 3
    # Shrink node 0 to shard 1 only.
    topo.register_ec_shards(7, "c", int(ShardBits(0).add_shard_id(1)),
                            nodes[0])
    assert topo.lookup_ec_shards(7).locations.get(0, []) == []
    assert topo.ec_shard_count == 2
    topo.unregister_ec_shards(7, nodes[0])
    topo.unregister_ec_shards(7, nodes[1])
    assert topo.lookup_ec_shards(7) is None
    assert topo.ec_shard_count == 0


def test_growth_placement_respects_rp():
    """Placement honoring 'one other DC, one other rack, one same rack'."""
    rng = random.Random(42)
    topo = Topology()
    _cluster(topo, dcs=2, racks=3, nodes=3, max_volumes=10)
    vg = VolumeGrowth(rng)
    for trial in range(10):
        servers = vg.find_empty_slots_for_one_volume(
            topo, VolumeGrowOption(replica_placement="111"))
        assert len(servers) == 4  # main + same-rack + other-rack + other-DC
        assert len({s.id for s in servers}) == 4
        dcs = {s.get_data_center().id for s in servers}
        racks = {(s.get_data_center().id, s.get_rack().id) for s in servers}
        assert len(dcs) == 2      # main DC + 1 other DC
        assert len(racks) == 3    # main rack (x2 servers) + other + other-DC


def test_growth_same_rack_placement():
    rng = random.Random(7)
    topo = Topology()
    _cluster(topo, dcs=1, racks=1, nodes=4)
    vg = VolumeGrowth(rng)
    servers = vg.find_empty_slots_for_one_volume(
        topo, VolumeGrowOption(replica_placement="002"))
    assert len(servers) == 3
    assert len({s.id for s in servers}) == 3  # distinct nodes


def test_growth_insufficient_topology():
    topo = Topology()
    _cluster(topo, dcs=1, racks=1, nodes=1)
    vg = VolumeGrowth(random.Random(1))
    with pytest.raises(ValueError):
        vg.find_empty_slots_for_one_volume(
            topo, VolumeGrowOption(replica_placement="010"))


def test_grow_by_type_allocates_on_servers():
    topo = Topology()
    nodes = _cluster(topo, dcs=1, racks=1, nodes=3)
    vg = VolumeGrowth(random.Random(3))
    allocated = []

    def allocate(vid, option, server):
        allocated.append((vid, server.id))
        # Simulate the heartbeat that follows a real allocation.
        topo.register_volume(
            _vinfo(vid, rp=int(option.replica_placement)), server)

    grown = vg.grow_by_type(
        topo, VolumeGrowOption(replica_placement="001"), allocate)
    assert grown == target_count_per_grow(2) == 6
    assert len(allocated) == 12  # 6 volumes x 2 replicas
    fid, _, locs = topo.pick_for_write(
        1, VolumeGrowOption(replica_placement="001"))
    assert len(locs) == 2


def test_pick_for_write_dc_preference():
    topo = Topology()
    nodes = _cluster(topo, dcs=2, racks=1, nodes=1)
    v = _vinfo(1)
    topo.register_volume(v, nodes[0])   # dc0
    v2 = _vinfo(2)
    topo.register_volume(v2, nodes[1])  # dc1
    for _ in range(5):
        fid, _, locs = topo.pick_for_write(
            1, VolumeGrowOption(data_center="dc1"))
        assert fid.startswith("2,")
        assert locs[0].get_data_center().id == "dc1"
