"""SeaweedFiler gRPC service against a live filer stack."""

import threading

import grpc
import pytest

from seaweedfs_tpu.cluster import rpc
from seaweedfs_tpu.cluster.master import MasterServer
from seaweedfs_tpu.cluster.volume_server import VolumeServer
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.pb import filer_pb2 as pb
from seaweedfs_tpu.pb.filer_grpc import FilerGrpcServer

SVC = "/filer_pb.SeaweedFiler/"


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("filer-grpc")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")],
                      max_volume_counts=[64], pulse_seconds=60)
    vs.start()
    fs = FilerServer(master.url(), chunk_size=1024)
    fs.start()
    g = FilerGrpcServer(fs, port=0)
    g.start()
    chan = grpc.insecure_channel(g.addr())
    yield master, vs, fs, g, chan
    chan.close()
    g.stop()
    fs.stop()
    vs.stop()
    master.stop()


def _unary(chan, name, req, resp_cls):
    return chan.unary_unary(
        SVC + name,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString)(req, timeout=10)


def test_grpc_full_write_read_cycle(stack):
    """The reference client's upload sequence, entirely over gRPC +
    HTTP data plane: AssignVolume -> POST bytes -> CreateEntry ->
    LookupDirectoryEntry -> LookupVolume -> GET bytes."""
    _m, _vs, fs, _g, chan = stack
    av = _unary(chan, "AssignVolume",
                pb.AssignVolumeRequest(count=1), pb.AssignVolumeResponse)
    assert av.file_id and not av.error
    body = b"written by a grpc filer client"
    rpc.call(f"http://{av.url}/{av.file_id}", "POST", body)
    entry = pb.Entry(
        name="grpc.txt",
        attributes=pb.FuseAttributes(mtime=1234, file_mode=0o644,
                                     mime="text/plain"),
        chunks=[pb.FileChunk(file_id=av.file_id, offset=0,
                             size=len(body), mtime=1)])
    out = _unary(chan, "CreateEntry",
                 pb.CreateEntryRequest(directory="/grpcdir",
                                       entry=entry),
                 pb.CreateEntryResponse)
    assert not out.error
    lk = _unary(chan, "LookupDirectoryEntry",
                pb.LookupDirectoryEntryRequest(directory="/grpcdir",
                                               name="grpc.txt"),
                pb.LookupDirectoryEntryResponse)
    assert lk.entry.name == "grpc.txt"
    assert lk.entry.attributes.file_size == len(body)
    assert lk.entry.chunks[0].file_id == av.file_id
    vids = [av.file_id.split(",")[0]]
    lv = _unary(chan, "LookupVolume",
                pb.LookupVolumeRequest(volume_ids=vids),
                pb.LookupVolumeResponse)
    locs = lv.locations_map[vids[0]].locations
    assert locs and rpc.call(
        f"http://{locs[0].url}/{av.file_id}") == body
    # the entry also reads through the filer HTTP plane
    assert rpc.call(f"{fs.url()}/grpcdir/grpc.txt") == body


def test_grpc_list_rename_delete(stack):
    _m, _vs, fs, _g, chan = stack
    for i in range(5):
        rpc.call(f"{fs.url()}/lst/f{i}.txt", "POST", b"x")
    listed = list(chan.unary_stream(
        SVC + "ListEntries",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.ListEntriesResponse.FromString)(
        pb.ListEntriesRequest(directory="/lst"), timeout=10))
    assert [r.entry.name for r in listed] == \
        [f"f{i}.txt" for i in range(5)]
    # prefix filter + pagination limit
    limited = list(chan.unary_stream(
        SVC + "ListEntries",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.ListEntriesResponse.FromString)(
        pb.ListEntriesRequest(directory="/lst", prefix="f1",
                              limit=10), timeout=10))
    assert [r.entry.name for r in limited] == ["f1.txt"]
    _unary(chan, "AtomicRenameEntry",
           pb.AtomicRenameEntryRequest(
               old_directory="/lst", old_name="f0.txt",
               new_directory="/lst", new_name="renamed.txt"),
           pb.AtomicRenameEntryResponse)
    assert rpc.call(f"{fs.url()}/lst/renamed.txt") == b"x"
    out = _unary(chan, "DeleteEntry",
                 pb.DeleteEntryRequest(directory="/lst",
                                       name="renamed.txt",
                                       is_delete_data=True),
                 pb.DeleteEntryResponse)
    assert not out.error
    with pytest.raises(rpc.RpcError):
        rpc.call(f"{fs.url()}/lst/renamed.txt")


def test_grpc_configuration_and_kv(stack):
    master, _vs, fs, _g, chan = stack
    cfg = _unary(chan, "GetFilerConfiguration",
                 pb.GetFilerConfigurationRequest(),
                 pb.GetFilerConfigurationResponse)
    assert cfg.masters == [master.url()]
    assert cfg.signature == fs.filer.signature
    assert cfg.dir_buckets == "/buckets"
    _unary(chan, "KvPut",
           pb.KvPutRequest(key=b"grpc.k", value=b"grpc.v"),
           pb.KvPutResponse)
    got = _unary(chan, "KvGet", pb.KvGetRequest(key=b"grpc.k"),
                 pb.KvGetResponse)
    assert got.value == b"grpc.v"
    miss = _unary(chan, "KvGet", pb.KvGetRequest(key=b"absent"),
                  pb.KvGetResponse)
    assert miss.error


def test_grpc_subscribe_metadata_replay_and_tail(stack):
    _m, _vs, fs, _g, chan = stack
    rpc.call(f"{fs.url()}/sub/before.txt", "POST", b"1")
    stream = chan.unary_stream(
        SVC + "SubscribeMetadata",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.SubscribeMetadataResponse.FromString)
    got = []
    seen_live = threading.Event()

    def consume():
        try:
            for r in stream(pb.SubscribeMetadataRequest(
                    client_name="t", path_prefix="/sub",
                    since_ns=0), timeout=15):
                got.append(r)
                if r.event_notification.new_entry.name == "live.txt":
                    seen_live.set()
                    return
        except grpc.RpcError:
            pass

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time
    time.sleep(0.3)  # let the replay attach to the live tail
    rpc.call(f"{fs.url()}/sub/live.txt", "POST", b"2")
    assert seen_live.wait(10), "live event never arrived"
    names = [r.event_notification.new_entry.name for r in got
             if r.event_notification.HasField("new_entry")]
    assert "before.txt" in names and "live.txt" in names
    assert all(r.ts_ns for r in got)


def test_grpc_binary_hardlink_id_and_kv_keys(stack):
    """Reference clients send RANDOM BYTES as hard_link_id and may use
    binary KV keys — both must round-trip, never UnicodeDecodeError."""
    import os as _os
    _m, _vs, fs, _g, chan = stack
    raw_id = bytes(range(240, 256)) + b"\x01"  # non-UTF-8
    out = _unary(chan, "CreateEntry",
                 pb.CreateEntryRequest(
                     directory="/hl",
                     entry=pb.Entry(
                         name="linked.txt",
                         attributes=pb.FuseAttributes(mtime=1,
                                                      file_mode=0o644),
                         hard_link_id=raw_id, hard_link_counter=2)),
                 pb.CreateEntryResponse)
    assert not out.error
    lk = _unary(chan, "LookupDirectoryEntry",
                pb.LookupDirectoryEntryRequest(directory="/hl",
                                               name="linked.txt"),
                pb.LookupDirectoryEntryResponse)
    assert lk.entry.hard_link_id == raw_id
    assert lk.entry.hard_link_counter == 2
    bkey = b"\xff\xfe binary key"
    _unary(chan, "KvPut", pb.KvPutRequest(key=bkey, value=b"v1"),
           pb.KvPutResponse)
    got = _unary(chan, "KvGet", pb.KvGetRequest(key=bkey),
                 pb.KvGetResponse)
    assert got.value == b"v1"


def test_grpc_append_creates_and_assign_ttl(stack):
    _m, _vs, fs, _g, chan = stack
    av = _unary(chan, "AssignVolume",
                pb.AssignVolumeRequest(count=1, ttl_sec=90),
                pb.AssignVolumeResponse)
    assert av.file_id and not av.error  # 90s -> "2m", a valid TTL
    body = b"appended"
    rpc.call(f"http://{av.url}/{av.file_id}", "POST", body)
    # first AppendToEntry on a missing path creates it
    _unary(chan, "AppendToEntry",
           pb.AppendToEntryRequest(
               directory="/app", entry_name="log.txt",
               chunks=[pb.FileChunk(file_id=av.file_id, size=len(body),
                                    mtime=1)]),
           pb.AppendToEntryResponse)
    assert rpc.call(f"{fs.url()}/app/log.txt") == body


def test_grpc_filer_statistics_real_numbers(stack):
    _m, vs, fs, _g, chan = stack
    rpc.call(f"{fs.url()}/statdir/s.bin", "POST", b"z" * 4096)
    for loc in vs.store.locations:
        for v in loc.volumes.values():
            v.sync()
    vs._send_heartbeat(full=True)  # counters ride heartbeats
    st = _unary(chan, "Statistics", pb.StatisticsRequest(),
                pb.StatisticsResponse)
    assert st.file_count >= 1 and st.used_size > 0 and st.total_size > 0
