"""Persistent filer meta log, signatures, KV, and MetaAggregator.

Reference behaviors covered: filer_notify.go (persisted meta log with
replay), filer.proto EventNotification.signatures (sync loop-breaker),
filer.proto KvGet/KvPut, meta_aggregator.go (peer stream merging).
"""

import time

import pytest

from seaweedfs_tpu.filer import (Filer, MemoryStore, MetaAggregator,
                                 MetaLog)
from seaweedfs_tpu.filer.entry import Attributes, Entry


def _touch(filer, path, **kw):
    filer.create_entry(Entry(path=path,
                             attributes=Attributes(mtime=time.time())),
                       **kw)


# -- MetaLog ---------------------------------------------------------------

def test_meta_log_memory_ring():
    log = MetaLog(None, capacity=4)
    for i in range(10):
        log.append({"ts_ns": i + 1, "n": i})
    evs = log.read_since(0)
    assert [e["n"] for e in evs] == [6, 7, 8, 9]  # capped at capacity
    assert log.read_since(8) == [{"ts_ns": 9, "n": 8},
                                 {"ts_ns": 10, "n": 9}]


def test_meta_log_forces_strictly_increasing_ts():
    """Events sharing a boundary ts_ns would be skipped by the strict
    `> since_ns` paging cursor; MetaLog bumps duplicates (topic_log's
    max(now, last+1) rule) and reports the final ts to the caller."""
    log = MetaLog(None)
    assert log.append({"ts_ns": 100, "n": 0}) == 100
    assert log.append({"ts_ns": 100, "n": 1}) == 101
    assert log.append({"ts_ns": 50, "n": 2}) == 102
    # Paging with the strict cursor sees every event exactly once.
    seen = []
    since = 0
    while True:
        page = log.read_since(since, limit=1)
        if not page:
            break
        seen.extend(e["n"] for e in page)
        since = page[-1]["ts_ns"]
    assert seen == [0, 1, 2]


def test_meta_log_persists_and_replays(tmp_path):
    d = str(tmp_path / "log")
    log = MetaLog(d, capacity=2)  # tiny ring: force disk replay
    for i in range(20):
        log.append({"ts_ns": (i + 1) * 10, "n": i})
    log.close()
    # Reopen: ring is empty, everything must come from segments.
    log2 = MetaLog(d, capacity=2)
    evs = log2.read_since(0)
    assert [e["n"] for e in evs] == list(range(20))
    assert [e["n"] for e in log2.read_since(150)] == list(range(15, 20))
    assert log2.last_ts_ns() == 200
    # Appends after reopen land in a new segment and stay ordered.
    log2.append({"ts_ns": 500, "n": 99})
    assert log2.read_since(190)[-1]["n"] == 99
    log2.close()


def test_meta_log_segment_rotation(tmp_path):
    d = str(tmp_path / "rot")
    log = MetaLog(d, segment_max_bytes=64)  # a couple events per file
    for i in range(12):
        log.append({"ts_ns": i + 1, "n": i})
    assert len(log._segments()) > 2
    assert [e["n"] for e in log.read_since(0)] == list(range(12))
    log.close()


def test_meta_log_no_duplicates_between_disk_and_ring(tmp_path):
    log = MetaLog(str(tmp_path / "dup"), capacity=100)
    for i in range(5):
        log.append({"ts_ns": i + 1, "n": i})
    # All 5 are both on disk and in the ring; reader must not repeat.
    assert [e["n"] for e in log.read_since(0)] == [0, 1, 2, 3, 4]


def test_meta_log_truncates_torn_tail_once_at_open(tmp_path):
    """A crash mid-append leaves a torn final line; reopen must
    physically truncate it (once, at open — not re-skip it on every
    read) and every intact event must survive."""
    d = str(tmp_path / "torn")
    log = MetaLog(d, capacity=2)
    for i in range(10):
        log.append({"ts_ns": i + 1, "n": i})
    log.close()
    seg = sorted((tmp_path / "torn").glob("*.meta.jsonl"))[-1]
    good_size = seg.stat().st_size
    with open(seg, "ab") as f:
        f.write(b'{"ts_ns": 999, "n":')  # torn: no newline, bad JSON
    log2 = MetaLog(d, capacity=2)
    assert seg.stat().st_size == good_size  # tail physically gone
    assert [e["n"] for e in log2.read_since(0)] == list(range(10))
    # Appends after the repair extend the truncated file cleanly.
    log2.append({"ts_ns": 100, "n": 10})
    log2.close()
    log3 = MetaLog(d, capacity=2)
    assert [e["n"] for e in log3.read_since(0)] == list(range(11))
    log3.close()


def test_meta_log_mid_segment_tear_skips_only_bad_line(tmp_path):
    """Bit rot in the middle of a segment must drop ONLY the damaged
    line — the old per-segment exception handler ate every event after
    it (and the file must NOT be truncated at the damage: the good
    suffix is still valid history)."""
    d = str(tmp_path / "midtear")
    log = MetaLog(d, capacity=2)
    for i in range(10):
        log.append({"ts_ns": i + 1, "n": i})
    log.close()
    seg = sorted((tmp_path / "midtear").glob("*.meta.jsonl"))[-1]
    lines = seg.read_bytes().splitlines(keepends=True)
    assert len(lines) >= 3
    lines[1] = b'{"ts_ns": corrupted!!\n'
    seg.write_bytes(b"".join(lines))
    size = seg.stat().st_size
    log2 = MetaLog(d, capacity=2)
    assert seg.stat().st_size == size  # mid-segment: no truncation
    got = [e["n"] for e in log2.read_since(0)]
    assert len(got) == 9 and got == sorted(got)  # one event lost, rest
    log2.close()                                 # intact and ordered


# -- Filer integration -----------------------------------------------------

def test_filer_meta_log_survives_restart(tmp_path):
    d = str(tmp_path / "filer-log")
    f = Filer(store=MemoryStore(), meta_log_dir=d)
    _touch(f, "/a/x.txt")
    _touch(f, "/a/y.txt")
    f.delete_entry("/a/x.txt")
    evs = f.read_meta_events(0)
    f.close()
    assert len(evs) >= 4  # mkdir /a + 2 creates + delete
    f2 = Filer(store=MemoryStore(), meta_log_dir=d)
    replay = f2.read_meta_events(0)
    assert [e.ts_ns for e in replay] == [e.ts_ns for e in evs]
    deletes = [e for e in replay
               if e.old_entry and not e.new_entry]
    assert deletes[-1].old_entry.path == "/a/x.txt"
    f2.close()


def test_event_signatures_and_loop_filter():
    f = Filer(store=MemoryStore(), signature=111)
    _touch(f, "/plain.txt")
    with f.with_signatures([222, 333]):
        _touch(f, "/synced.txt")
    evs = f.read_meta_events(0)
    by_path = {e.new_entry.path: e for e in evs if e.new_entry}
    assert by_path["/plain.txt"].signatures == [111]
    assert set(by_path["/synced.txt"].signatures) == {111, 222, 333}
    f.close()


def test_subscribe_replays_from_persistent_log(tmp_path):
    d = str(tmp_path / "sub")
    f = Filer(store=MemoryStore(), meta_log_dir=d)
    _touch(f, "/one.txt")
    f.close()
    f2 = Filer(store=MemoryStore(), meta_log_dir=d)
    seen = []
    f2.subscribe(lambda ev: seen.append(ev))
    assert any(ev.new_entry and ev.new_entry.path == "/one.txt"
               for ev in seen)
    _touch(f2, "/two.txt")
    assert seen[-1].new_entry.path == "/two.txt"
    f2.close()


# -- FilerServer HTTP surface ---------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.filer.server import FilerServer
    tmp = tmp_path_factory.mktemp("metalog-stack")
    master = MasterServer(volume_size_limit_mb=64, meta_dir=str(tmp))
    master.start()
    vs = VolumeServer(master.url(), [str(tmp / "vs")], pulse_seconds=60)
    vs.start()
    f1 = FilerServer(master.url())
    f1.start()
    f2 = FilerServer(master.url())
    f2.start()
    yield f1, f2
    f2.stop()
    f1.stop()
    vs.stop()
    master.stop()


def test_http_meta_subscribe_and_kv(stack):
    from seaweedfs_tpu.filer.client import FilerProxy
    f1, _ = stack
    p = FilerProxy(f1.url())
    info = p.meta_info()
    assert info["signature"] == f1.filer.signature
    p.put("/mlog/a.txt", b"hello")
    out = p.meta_events(0, prefix="/mlog")
    paths = [e["new_entry"]["path"] for e in out["events"]
             if e.get("new_entry")]
    assert "/mlog/a.txt" in paths
    # exclude_signature filters this filer's own events out entirely
    out2 = p.meta_events(0, exclude_signature=f1.filer.signature)
    assert out2["events"] == []
    # KV round trip
    assert p.kv_get("sync.offset") is None
    p.kv_put("sync.offset", b"12345")
    assert p.kv_get("sync.offset") == b"12345"


def test_meta_aggregator_merges_peers(stack):
    from seaweedfs_tpu.filer.client import FilerProxy
    f1, f2 = stack
    agg = MetaAggregator([f1.url(), f2.url()], poll_interval=0.05)
    got = []
    agg.subscribe(lambda peer, ev: got.append((peer, ev)))
    agg.start()
    FilerProxy(f1.url()).put("/agg/p1.txt", b"one")
    FilerProxy(f2.url()).put("/agg/p2.txt", b"two")
    agg.drain()
    agg.stop()
    paths = {ev.new_entry.path for _, ev in got if ev.new_entry}
    assert {"/agg/p1.txt", "/agg/p2.txt"} <= paths
    peers = {peer for peer, _ in got}
    assert peers == {f1.url(), f2.url()}
