"""Edge cases surfaced in code review: empty wanted, invalid shard ids."""

import numpy as np
import pytest

from seaweedfs_tpu.ops.coder_numpy import NumpyCoder


def test_reconstruct_empty_wanted_returns_empty():
    c = NumpyCoder(10, 4)
    # Even with too few survivors, nothing wanted -> nothing to do.
    have = {i: np.zeros(10, np.uint8) for i in range(5)}
    assert c.reconstruct(have, wanted=[]) == {}


def test_reconstruct_out_of_range_wanted_raises_valueerror():
    c = NumpyCoder(10, 4)
    data = np.random.default_rng(0).integers(0, 256, (10, 20), dtype=np.uint8)
    shards = c.encode_all(data)
    have = {i: shards[i] for i in range(10)}
    with pytest.raises(ValueError, match="out of range"):
        c.reconstruct(have, wanted=[14])
    with pytest.raises(ValueError, match="out of range"):
        c.reconstruct(have, wanted=[-1])


def test_parity_only_reconstruction_skips_data_solve():
    c = NumpyCoder(10, 4)
    data = np.random.default_rng(1).integers(0, 256, (10, 64), dtype=np.uint8)
    shards = c.encode_all(data)
    have = {i: shards[i] for i in range(10)}  # all data, no parity
    rec = c.reconstruct(have)
    assert set(rec) == {10, 11, 12, 13}
    for i in rec:
        assert np.array_equal(rec[i], shards[i])
