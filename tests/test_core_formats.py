"""L0 format tests: scalar codecs, CRC, TTL, needle round-trips, superblock.

The golden test at the bottom cross-validates against the reference's
committed binary fixture (weed/storage/erasure_coding/1.dat + 1.idx): every
needle is parsed and re-serialized and must be byte-identical — this pins
header layout, optional sections, checksum masking, AND the padding quirk.
"""

import os
import zlib

import pytest

from seaweedfs_tpu.core import crc, idx, types as t
from seaweedfs_tpu.core.needle import (CURRENT_VERSION, VERSION1, VERSION2,
                                       VERSION3, Needle, get_actual_size,
                                       padding_length)
from seaweedfs_tpu.core.replica_placement import ReplicaPlacement
from seaweedfs_tpu.core.super_block import SuperBlock
from seaweedfs_tpu.core.ttl import TTL

REF_FIXTURE = "/root/reference/weed/storage/erasure_coding"


def test_scalar_codecs_big_endian():
    assert t.put_uint32(0x01020304) == b"\x01\x02\x03\x04"
    assert t.get_uint32(b"\x01\x02\x03\x04") == 0x01020304
    assert t.put_uint64(1) == b"\x00" * 7 + b"\x01"
    assert t.put_uint16(0xBEEF) == b"\xbe\xef"
    assert t.size_from_bytes(t.size_to_bytes(-1)) == -1


def test_offset_units_of_8():
    assert t.offset_to_bytes(800) == t.put_uint32(100)
    assert t.offset_from_bytes(t.offset_to_bytes(12345678 * 8)) == 12345678 * 8


def test_needle_map_entry_roundtrip():
    e = t.NeedleMapEntry(key=0xDEADBEEF01, offset=4096, size=1234)
    b = e.to_bytes()
    assert len(b) == 16
    assert t.NeedleMapEntry.from_bytes(b) == e


def test_tombstone():
    e = t.NeedleMapEntry(key=1, offset=0, size=t.TOMBSTONE_FILE_SIZE)
    assert t.size_is_deleted(t.NeedleMapEntry.from_bytes(e.to_bytes()).size)


def test_file_id_format_parse():
    fid = t.format_file_id(3, 0x01637037, 0xD6000000)
    vid, key, cookie = t.parse_file_id(fid)
    assert (vid, key, cookie) == (3, 0x01637037, 0xD6000000)
    assert t.format_file_id(1, 0, 0x12345678) == "1,012345678"
    with pytest.raises(ValueError):
        t.parse_file_id("nocomma")


def test_crc32c_against_zlib_crc32_distinct():
    # CRC32-C != zlib CRC32 (different polynomial); sanity that we're not
    # accidentally using the stdlib one.
    data = b"hello seaweedfs"
    assert crc.crc32c(data) != zlib.crc32(data)


def test_crc32c_known_vectors():
    # RFC 3720 / Castagnoli test vectors.
    assert crc.crc32c(b"") == 0
    assert crc.crc32c(b"123456789") == 0xE3069283
    assert crc.crc32c(bytes(32)) == 0x8A9136AA
    assert crc.crc32c(bytes([0xFF] * 32)) == 0x62A8AB43


def test_crc32c_incremental_update():
    data = os.urandom(1000)
    whole = crc.crc32c(data)
    part = crc.crc32c(data[373:], crc.crc32c(data[:373]))
    assert whole == part


def test_masked_value():
    # Value() = rot17(c) + 0xa282ead8 mod 2^32
    assert crc.masked_value(0) == 0xA282EAD8
    c = 0x12345678
    rot = ((c >> 15) | (c << 17)) & 0xFFFFFFFF
    assert crc.masked_value(c) == (rot + 0xA282EAD8) & 0xFFFFFFFF


def test_ttl_roundtrip():
    for s, mins in (("3m", 3), ("4h", 240), ("5d", 7200), ("6w", 60480),
                    ("100", 100)):
        ttl = TTL.parse(s)
        assert ttl.minutes() == mins
        assert TTL.from_bytes(ttl.to_bytes()) == ttl
        assert TTL.from_uint32(ttl.to_uint32()) == ttl
    assert str(TTL.parse("7M")) == "7M"
    assert str(TTL.parse("")) == ""
    assert TTL.parse("").to_uint32() == 0


def test_replica_placement():
    rp = ReplicaPlacement.parse("012")
    assert rp.diff_data_center_count == 0
    assert rp.diff_rack_count == 1
    assert rp.same_rack_count == 2
    assert rp.copy_count() == 4
    assert ReplicaPlacement.from_byte(rp.to_byte()) == rp
    assert str(rp) == "012"
    with pytest.raises(ValueError):
        ReplicaPlacement.parse("900")


def test_padding_always_1_to_8():
    for version in (VERSION1, VERSION2, VERSION3):
        for size in range(0, 64):
            pad = padding_length(size, version)
            assert 1 <= pad <= 8
            total = get_actual_size(size, version)
            assert total % 8 == 0


def test_needle_roundtrip_minimal():
    for version in (VERSION1, VERSION2, VERSION3):
        n = Needle(cookie=0x11223344, id=42, data=b"hello world")
        blob = n.to_bytes(version)
        assert len(blob) == n.disk_size(version)
        assert len(blob) % 8 == 0
        m = Needle.from_bytes(blob, version)
        assert m.id == 42 and m.cookie == 0x11223344
        assert m.data == b"hello world"


def test_needle_roundtrip_all_options():
    n = Needle(cookie=7, id=0xABCDEF, data=b"payload-bytes")
    n.set_name(b"file.txt")
    n.set_mime(b"text/plain")
    n.set_last_modified(1_600_000_000)
    n.set_ttl(TTL.parse("3d"))
    n.set_pairs(b'{"k":"v"}')
    n.append_at_ns = 123456789012345678
    blob = n.to_bytes(VERSION3)
    m = Needle.from_bytes(blob, VERSION3)
    assert m.data == n.data
    assert m.name == b"file.txt"
    assert m.mime == b"text/plain"
    assert m.last_modified == 1_600_000_000
    assert str(m.ttl) == "3d"
    assert m.pairs == b'{"k":"v"}'
    assert m.append_at_ns == 123456789012345678


def test_needle_empty_data():
    n = Needle(cookie=1, id=2, data=b"")
    blob = n.to_bytes(VERSION3)
    assert n.size == 0
    m = Needle.from_bytes(blob, VERSION3)
    assert m.data == b""


def test_needle_crc_corruption_detected():
    n = Needle(cookie=1, id=2, data=b"some data here")
    blob = bytearray(n.to_bytes(VERSION3))
    blob[t.NEEDLE_HEADER_SIZE + 5] ^= 0xFF  # flip a data byte
    with pytest.raises(ValueError, match="CRC"):
        Needle.from_bytes(bytes(blob), VERSION3)


def test_superblock_roundtrip():
    sb = SuperBlock(version=VERSION3,
                    replica_placement=ReplicaPlacement.parse("001"),
                    ttl=TTL.parse("3w"), compaction_revision=7)
    b = sb.to_bytes()
    assert len(b) == 8
    got = SuperBlock.from_bytes(b)
    assert got.version == 3
    assert str(got.replica_placement) == "001"
    assert str(got.ttl) == "3w"
    assert got.compaction_revision == 7
    sb.extra = b"\x01\x02\x03"
    got2 = SuperBlock.from_bytes(sb.to_bytes())
    assert got2.extra == b"\x01\x02\x03"


def test_idx_walk_and_append(tmp_path):
    p = tmp_path / "v.idx"
    with open(p, "wb") as f:
        for i in range(2500):  # > ROWS_TO_READ to hit the chunking path
            idx.append_entry(f, key=i, actual_offset=i * 8, size=i % 100)
    with open(p, "rb") as f:
        entries = list(idx.iter_index(f))
    assert len(entries) == 2500
    assert entries[7] == t.NeedleMapEntry(7, 56, 7)


# ---------------------------------------------------------------------------
# Golden cross-validation vs the reference's committed binary fixture
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not os.path.exists(os.path.join(REF_FIXTURE, "1.dat")),
                    reason="reference fixture not available")
def test_reference_fixture_byte_identical_reserialization():
    """Parse every needle in the reference 1.dat and re-serialize: bytes
    must match exactly (validates layout, checksum, and padding quirks)."""
    with open(os.path.join(REF_FIXTURE, "1.dat"), "rb") as f:
        dat = f.read()
    with open(os.path.join(REF_FIXTURE, "1.idx"), "rb") as f:
        entries = list(idx.iter_index(f))
    sb = SuperBlock.from_bytes(dat[:8])
    version = sb.version
    assert entries, "fixture idx is empty?"
    checked = 0
    for e in entries:
        if not t.size_is_valid(e.size):
            continue
        total = get_actual_size(e.size, version)
        blob = dat[e.offset:e.offset + total]
        n = Needle.from_bytes(blob, version)
        assert n.id == e.key
        re_blob = n.to_bytes(version)
        assert re_blob == blob, (
            f"re-serialization mismatch for needle {e.key:x} at {e.offset}")
        checked += 1
    assert checked > 0
