// Host-side native kernels for seaweedfs_tpu.
//
// The reference leans on two Go-assembly SIMD libraries
// (klauspost/crc32, klauspost/reedsolomon — SURVEY.md §2.2 ⚡ rows).
// This file provides the equivalent native host paths for our build:
//
//   sw_crc32c    — CRC32-C: SSE4.2 hardware instruction when available,
//                  slice-by-8 tables otherwise.
//   sw_gf_mul_add/sw_gf_mix — GF(2^8) region multiply-accumulate with the
//                  AVX2 PSHUFB split-nibble technique (the same scheme
//                  klauspost/ISA-L use), scalar table fallback.
//
// The TPU Pallas kernel is the hot path for bulk EC; these serve the host
// daemon (checksums on ingest) and the CPU-baseline benchmark.
//
// Build: make -C native   ->  libseaweed_native.so

#include <cstddef>
#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define SW_X86 1
#endif

extern "C" {

// ---------------------------------------------------------------------------
// CRC32-C
// ---------------------------------------------------------------------------

static uint32_t crc_tables[8][256];
static bool crc_tables_ready = false;

static void init_crc_tables() {
    if (crc_tables_ready) return;
    const uint32_t poly = 0x82F63B78u;  // reversed Castagnoli
    for (int i = 0; i < 256; i++) {
        uint32_t crc = (uint32_t)i;
        for (int k = 0; k < 8; k++)
            crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
        crc_tables[0][i] = crc;
    }
    for (int t = 1; t < 8; t++)
        for (int i = 0; i < 256; i++)
            crc_tables[t][i] = (crc_tables[t - 1][i] >> 8) ^
                               crc_tables[0][crc_tables[t - 1][i] & 0xFF];
    crc_tables_ready = true;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t* buf, size_t len) {
    init_crc_tables();
    crc = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, buf, 8);
        crc ^= (uint32_t)word;
        uint32_t hi = (uint32_t)(word >> 32);
        crc = crc_tables[7][crc & 0xFF] ^ crc_tables[6][(crc >> 8) & 0xFF] ^
              crc_tables[5][(crc >> 16) & 0xFF] ^ crc_tables[4][crc >> 24] ^
              crc_tables[3][hi & 0xFF] ^ crc_tables[2][(hi >> 8) & 0xFF] ^
              crc_tables[1][(hi >> 16) & 0xFF] ^ crc_tables[0][hi >> 24];
        buf += 8;
        len -= 8;
    }
    while (len--) crc = (crc >> 8) ^ crc_tables[0][(crc ^ *buf++) & 0xFF];
    return ~crc;
}

#ifdef SW_X86
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t* buf, size_t len) {
    uint64_t c = ~crc;
    while (len >= 8) {
        uint64_t word;
        memcpy(&word, buf, 8);
        c = _mm_crc32_u64(c, word);
        buf += 8;
        len -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (len--) c32 = _mm_crc32_u8(c32, *buf++);
    return ~c32;
}
#endif

uint32_t sw_crc32c(uint32_t crc, const uint8_t* buf, size_t len) {
#ifdef SW_X86
    if (__builtin_cpu_supports("sse4.2")) return crc32c_hw(crc, buf, len);
#endif
    return crc32c_sw(crc, buf, len);
}

// ---------------------------------------------------------------------------
// GF(2^8) region ops (poly 0x11D)
// ---------------------------------------------------------------------------

static uint8_t gf_mul_table[256][256];
static uint8_t gf_nib_lo[256][16];  // c * low-nibble values
static uint8_t gf_nib_hi[256][16];  // c * (high-nibble << 4) values
static bool gf_ready = false;

static uint8_t gf_mul_slow(uint8_t a, uint8_t b) {
    uint16_t r = 0, aa = a;
    while (b) {
        if (b & 1) r ^= aa;
        aa <<= 1;
        if (aa & 0x100) aa ^= 0x11D;
        b >>= 1;
    }
    return (uint8_t)r;
}

static void init_gf_tables() {
    if (gf_ready) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            gf_mul_table[a][b] = gf_mul_slow((uint8_t)a, (uint8_t)b);
    for (int c = 0; c < 256; c++) {
        for (int n = 0; n < 16; n++) {
            gf_nib_lo[c][n] = gf_mul_table[c][n];
            gf_nib_hi[c][n] = gf_mul_table[c][n << 4];
        }
    }
    gf_ready = true;
}

static void gf_mul_add_scalar(uint8_t c, const uint8_t* src, uint8_t* dst,
                              size_t n) {
    const uint8_t* row = gf_mul_table[c];
    for (size_t i = 0; i < n; i++) dst[i] ^= row[src[i]];
}

#ifdef SW_X86
__attribute__((target("avx2")))
static void gf_mul_add_avx2(uint8_t c, const uint8_t* src, uint8_t* dst,
                            size_t n) {
    __m256i lo_tbl = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i*)gf_nib_lo[c]));
    __m256i hi_tbl = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i*)gf_nib_hi[c]));
    __m256i mask = _mm256_set1_epi8(0x0F);
    size_t i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i*)(src + i));
        __m256i lo = _mm256_and_si256(v, mask);
        __m256i hi = _mm256_and_si256(_mm256_srli_epi16(v, 4), mask);
        __m256i prod = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tbl, lo),
                                        _mm256_shuffle_epi8(hi_tbl, hi));
        __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
        _mm256_storeu_si256((__m256i*)(dst + i),
                            _mm256_xor_si256(d, prod));
    }
    if (i < n) gf_mul_add_scalar(c, src + i, dst + i, n - i);
}
#endif

// dst ^= c * src over GF(2^8)
void sw_gf_mul_add(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n) {
    init_gf_tables();
    if (c == 0) return;
#ifdef SW_X86
    if (__builtin_cpu_supports("avx2")) {
        gf_mul_add_avx2(c, src, dst, n);
        return;
    }
#endif
    gf_mul_add_scalar(c, src, dst, n);
}

// outs[r] = XOR_c mat[r*cols + c] * ins[c], each region n bytes.
void sw_gf_mix(const uint8_t* mat, int rows, int cols,
               const uint8_t* const* ins, uint8_t* const* outs, size_t n) {
    init_gf_tables();
    for (int r = 0; r < rows; r++) {
        memset(outs[r], 0, n);
        for (int c = 0; c < cols; c++) {
            uint8_t coef = mat[r * cols + c];
            if (coef) sw_gf_mul_add(coef, ins[c], outs[r], n);
        }
    }
}

}  // extern "C"
