// Standalone sanitizer harness for the native host kernels.
//
// SURVEY §5 prescribes sanitizer builds for the C++ runtime; Python's
// ctypes loading can't carry ASan, so this mirror-exercises the
// exported surface (sw_crc32c, sw_gf_mul_add, sw_gf_mix) directly,
// with odd/unaligned sizes that stress the AVX2 tail paths.  Built and
// run by `make asan-test` under -fsanitize=address,undefined.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
uint32_t sw_crc32c(uint32_t crc, const uint8_t* buf, size_t len);
void sw_gf_mul_add(uint8_t c, const uint8_t* src, uint8_t* dst, size_t n);
void sw_gf_mix(const uint8_t* mat, int rows, int cols,
               const uint8_t* const* srcs, uint8_t* const* dsts, size_t n);
}

static uint8_t gf_mul_ref(uint8_t a, uint8_t b) {
    uint16_t r = 0, aa = a;
    while (b) {
        if (b & 1) r ^= aa;
        aa <<= 1;
        if (aa & 0x100) aa ^= 0x11D;
        b >>= 1;
    }
    return (uint8_t)r;
}

static void fail(const char* what) {
    fprintf(stderr, "FAIL: %s\n", what);
    exit(1);
}

int main() {
    // CRC32-C check vector (RFC 3720) + incremental equivalence across
    // arbitrary split points.
    const uint8_t nine[] = "123456789";
    if (sw_crc32c(0, nine, 9) != 0xE3069283u) fail("crc vector");
    std::vector<uint8_t> data(100003);
    for (size_t i = 0; i < data.size(); i++)
        data[i] = (uint8_t)(i * 131 + 17);
    uint32_t whole = sw_crc32c(0, data.data(), data.size());
    for (size_t split : {size_t(1), size_t(7), size_t(63),
                         size_t(4096), data.size() - 3}) {
        uint32_t a = sw_crc32c(0, data.data(), split);
        uint32_t b = sw_crc32c(a, data.data() + split,
                               data.size() - split);
        if (b != whole) fail("crc incremental");
    }

    // gf_mul_add against the scalar reference at awkward lengths
    // (tails shorter than one AVX2 lane, lane+tail, unaligned starts).
    for (size_t n : {size_t(1), size_t(15), size_t(31), size_t(32),
                     size_t(33), size_t(1000), size_t(4097)}) {
        std::vector<uint8_t> src(n), dst(n), ref(n);
        for (size_t i = 0; i < n; i++) {
            src[i] = (uint8_t)(i * 7 + 3);
            dst[i] = ref[i] = (uint8_t)(i * 13 + 1);
        }
        uint8_t c = (uint8_t)(n * 37 + 5);
        sw_gf_mul_add(c, src.data(), dst.data(), n);
        for (size_t i = 0; i < n; i++)
            ref[i] ^= gf_mul_ref(c, src[i]);
        if (memcmp(dst.data(), ref.data(), n) != 0) fail("gf_mul_add");
    }

    // gf_mix: full RS(10,4)-shaped matrix multiply vs reference.
    const int rows = 4, cols = 10;
    const size_t n = 2049;  // odd: exercises the vector tail
    std::vector<uint8_t> mat(rows * cols);
    for (int i = 0; i < rows * cols; i++)
        mat[i] = (uint8_t)(i * 29 + 11);
    std::vector<std::vector<uint8_t>> srcs(cols,
                                           std::vector<uint8_t>(n));
    std::vector<std::vector<uint8_t>> dsts(rows,
                                           std::vector<uint8_t>(n, 0));
    std::vector<const uint8_t*> sp(cols);
    std::vector<uint8_t*> dp(rows);
    for (int j = 0; j < cols; j++) {
        for (size_t i = 0; i < n; i++)
            srcs[j][i] = (uint8_t)(i + j * 101 + 5);
        sp[j] = srcs[j].data();
    }
    for (int r = 0; r < rows; r++) dp[r] = dsts[r].data();
    sw_gf_mix(mat.data(), rows, cols, sp.data(), dp.data(), n);
    for (int r = 0; r < rows; r++) {
        for (size_t i = 0; i < n; i++) {
            uint8_t want = 0;
            for (int j = 0; j < cols; j++)
                want ^= gf_mul_ref(mat[r * cols + j], srcs[j][i]);
            if (dsts[r][i] != want) fail("gf_mix");
        }
    }

    printf("native sanitizer harness OK\n");
    return 0;
}
