"""Per-scheme kernel throughput: RS(10,4) / RS(16,4) / RS(8,3), int8+bf16.

Produces the measurement table in BASELINE.md's "Kernel roofline
analysis" (execution-fenced, same harness as bench.py).  The column
rate it prints is the model quantity: throughput = k bytes/column x
column rate, column rate <= 6.0e9/s on v5e whatever fraction of the
128x128 MXU weight tile the (8r, 8k) bit-matrix fills.

Run on a real chip: python bench_schemes.py
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from seaweedfs_tpu.ops import rs_bitmatrix
from seaweedfs_tpu.ops.coder_jax import plane_major
from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder

N = 64 * 1024 * 1024
ITERS = 10
BLOCK = 65536


def log(*a):
    print(*a, file=sys.stderr, flush=True)


@jax.jit
def _chain(acc, out):
    return acc ^ out[:, :256].astype(jnp.uint32).sum()


def timed(fn, *args, **kw):
    out = fn(*args, **kw)
    acc = _chain(jnp.uint32(0), out)
    int(acc)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args, **kw)
        acc = _chain(acc, out)
    int(acc)
    return (time.perf_counter() - t0) / ITERS


def main():
    log(f"device: {jax.devices()[0]}")
    key = jax.random.PRNGKey(0)
    results = {}
    for k, r in ((10, 4), (16, 4), (8, 3)):
        total = k + r
        pm = jnp.asarray(plane_major(
            rs_bitmatrix.parity_bitmatrix(k, total), r, k), jnp.float32)
        data = jax.random.randint(key, (k, N), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        # correctness gate per scheme
        got = np.asarray(apply_bitmatrix_pallas(
            pm, data[:, :BLOCK], r, k, block_n=BLOCK, mm="int8"))
        ok = np.array_equal(got, NumpyCoder(k, r).encode(
            np.asarray(data[:, :BLOCK])))
        assert ok, f"RS({k},{r}) wrong"
        for mm in ("int8", "bf16"):
            dt = timed(apply_bitmatrix_pallas, pm, data, r, k,
                       block_n=BLOCK, mm=mm)
            mbps = data.nbytes / dt / 1e6
            cols = (N / dt) / 1e9
            log(f"RS({k:2d},{r}) {mm}: {mbps:8.0f} MB/s "
                f"({cols:.2f}e9 cols/s, {k}B/col)")
            results[f"rs{k}_{r}_{mm}"] = round(mbps, 1)
        del data
    print(json.dumps(results))


if __name__ == "__main__":
    main()
