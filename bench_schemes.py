"""Per-scheme kernel throughput: RS(10,4) / RS(16,4) / RS(8,3) /
LRC(10,2,2), int8+bf16.

Produces the measurement table in BASELINE.md's "Kernel roofline
analysis" (execution-fenced via bench.py's shared harness).  The column
rate it prints is the model quantity: throughput = k bytes/column x
column rate, column rate <= 6.0e9/s on v5e whatever fraction of the
128x128 MXU weight tile the (8r, 8k) bit-matrix fills.  The LRC row
runs the SAME kernel with the lrc codec's generator — encode cost is
identical by construction (same (8*4, 8*10) matrix shape as RS(10,4));
what LRC buys is 2x cheaper repair (bench_repair_traffic.py).

Run on a real chip: python bench_schemes.py
"""
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bench import _make_timed, roofline_limit_mbps
from seaweedfs_tpu.codecs import get_codec, rs_codec
from seaweedfs_tpu.ops.coder_jax import plane_major
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas

N = 64 * 1024 * 1024
BLOCK = 65536


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    dev = jax.devices()[0]
    log(f"device: {dev}")
    timed = _make_timed()
    key = jax.random.PRNGKey(0)
    results = {}
    schemes = [
        ("RS(10,4)", "rs10_4", rs_codec(10, 4)),
        ("RS(16,4)", "rs16_4", rs_codec(16, 4)),
        ("RS( 8,3)", "rs8_3", rs_codec(8, 3)),
        ("LRC(10,2,2)", "lrc10_2_2", get_codec("lrc")),
    ]
    for label, keybase, cd in schemes:
        k, r = cd.data_shards, cd.parity_shards
        pm = jnp.asarray(plane_major(
            cd.parity_bitmatrix(), r, k), jnp.float32)
        data = jax.random.randint(key, (k, N), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        want = NumpyCoder(codec=cd).encode(np.asarray(data[:, :BLOCK]))
        limit = roofline_limit_mbps(r, k)
        for mm in ("int8", "bf16"):
            # correctness gate per scheme AND dtype: an untested
            # lowering must never publish a number.
            got = np.asarray(apply_bitmatrix_pallas(
                pm, data[:, :BLOCK], r, k, block_n=BLOCK, mm=mm))
            assert np.array_equal(got, want), f"{label} {mm} wrong"
            dt = timed(apply_bitmatrix_pallas, pm, data, r, k,
                       block_n=BLOCK, mm=mm)
            mbps = data.nbytes / dt / 1e6
            if dev.platform == "tpu" and mbps > 1.05 * limit:
                log(f"{label} {mm}: REJECT {mbps:.0f} MB/s — "
                    f"exceeds the physical roofline {limit:.0f} MB/s "
                    f"(harness bug, not a result)")
                continue
            cols = (N / dt) / 1e9
            log(f"{label:>11s} {mm}: {mbps:8.0f} MB/s "
                f"({cols:.2f}e9 cols/s, {k}B/col)")
            results[f"{keybase}_{mm}"] = round(mbps, 1)
        del data
    print(json.dumps(results))


if __name__ == "__main__":
    main()
