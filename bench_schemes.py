"""Per-scheme kernel throughput: RS(10,4) / RS(16,4) / RS(8,3) /
LRC(10,2,2), int8+bf16.

Produces the measurement table in BASELINE.md's "Kernel roofline
analysis" (execution-fenced via bench.py's shared harness).  The column
rate it prints is the model quantity: throughput = k bytes/column x
column rate, column rate <= 6.0e9/s on v5e whatever fraction of the
128x128 MXU weight tile the (8r, 8k) bit-matrix fills.  The LRC row
runs the SAME kernel with the lrc codec's generator — encode cost is
identical by construction (same (8*4, 8*10) matrix shape as RS(10,4));
what LRC buys is 2x cheaper repair (bench_repair_traffic.py).

Run on a real chip: python bench_schemes.py

`python bench_schemes.py --roofline [out.json]` runs the device
roofline pass instead: small-N end-to-end PallasCoder encodes per
(codec, mm dtype) through the REAL call sites (so the achieved
fractions, conservation verdict, and armed-vs-disarmed overhead all
come from stats/roofline.py's production ledger, not a parallel
harness), published as BENCH_roofline_r01.json.  Small-N on purpose:
it completes in interpret mode on a CPU-only box; on a real chip the
same command gives honest achieved fractions against the probed peaks.
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from bench import _make_timed, roofline_limit_mbps
from seaweedfs_tpu.codecs import get_codec, rs_codec
from seaweedfs_tpu.ops.coder_jax import plane_major
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas
from seaweedfs_tpu.stats import roofline as rl

N = 64 * 1024 * 1024
BLOCK = 65536


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    dev = jax.devices()[0]
    log(f"device: {dev}")
    timed = _make_timed()
    key = jax.random.PRNGKey(0)
    results = {}
    schemes = [
        ("RS(10,4)", "rs10_4", rs_codec(10, 4)),
        ("RS(16,4)", "rs16_4", rs_codec(16, 4)),
        ("RS( 8,3)", "rs8_3", rs_codec(8, 3)),
        ("LRC(10,2,2)", "lrc10_2_2", get_codec("lrc")),
    ]
    for label, keybase, cd in schemes:
        k, r = cd.data_shards, cd.parity_shards
        pm = jnp.asarray(plane_major(
            cd.parity_bitmatrix(), r, k), jnp.float32)
        # GF(2) work columns: naive XOR count beside the
        # post-elimination schedule (Paar greedy) — the baseline pair
        # matrix-scheduling work (arxiv 2108.02692) lands against.
        bm = np.asarray(cd.parity_bitmatrix())
        dense = rl.dense_gf2_work(bm)
        eff = rl.effective_gf2_work(bm)
        log(f"{label:>11s} GF(2) work: dense {dense} XORs, "
            f"effective {eff} ({eff / dense:.0%} after elimination)")
        results[f"{keybase}_gf2_dense_xors"] = dense
        results[f"{keybase}_gf2_effective_xors"] = eff
        data = jax.random.randint(key, (k, N), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        want = NumpyCoder(codec=cd).encode(np.asarray(data[:, :BLOCK]))
        limit = roofline_limit_mbps(r, k)
        peaks = rl.probe_peaks()
        for mm in ("int8", "bf16"):
            # correctness gate per scheme AND dtype: an untested
            # lowering must never publish a number.
            got = np.asarray(apply_bitmatrix_pallas(
                pm, data[:, :BLOCK], r, k, block_n=BLOCK, mm=mm))
            assert np.array_equal(got, want), f"{label} {mm} wrong"
            dt = timed(apply_bitmatrix_pallas, pm, data, r, k,
                       block_n=BLOCK, mm=mm)
            mbps = data.nbytes / dt / 1e6
            if dev.platform == "tpu" and mbps > 1.05 * limit:
                log(f"{label} {mm}: REJECT {mbps:.0f} MB/s — "
                    f"exceeds the physical roofline {limit:.0f} MB/s "
                    f"(harness bug, not a result)")
                continue
            cols = (N / dt) / 1e9
            # Achieved fraction of the MEASURED roofline (probed
            # matmul peak / membw), beside the analytic MB/s limit —
            # the same floor the production ledger applies.
            cost = rl.cost_model(r, k, N)
            floor = rl.roofline_floor_seconds(
                cost["flops"], cost["bytes"], peaks, mm)
            ach = None if floor is None else min(floor / dt, 1.0)
            log(f"{label:>11s} {mm}: {mbps:8.0f} MB/s "
                f"({cols:.2f}e9 cols/s, {k}B/col"
                + (f", {ach:.1%} of probed roofline" if ach is not None
                   else "") + ")")
            results[f"{keybase}_{mm}"] = round(mbps, 1)
            if ach is not None:
                results[f"{keybase}_{mm}_achieved"] = round(ach, 4)
        del data
    print(json.dumps(results))


def bench_roofline(out: str = "BENCH_roofline_r01.json") -> None:
    """Per-kernel achieved-fraction rows for rs(10,4) and lrc(10,2,2)
    x int8/bf16 through the production ledger: real PallasCoder
    encodes (plain + fused-CRC) fill stats/roofline.LEDGER, whose
    kernel table, conservation verdict, and peaks are what this
    publishes — plus the armed-vs-disarmed overhead of the plane
    itself."""
    n = int(os.environ.get("BENCH_ROOFLINE_N", str(256 * 1024)))
    reps = int(os.environ.get("BENCH_ROOFLINE_REPS", "3"))
    dev = jax.devices()[0]
    log(f"device: {dev}  n={n} bytes/shard  reps={reps}")
    rl.LEDGER.reset()
    rl.set_armed(True)
    peaks = rl.probe_peaks()
    key = jax.random.PRNGKey(0)

    from seaweedfs_tpu.ops.coder_pallas import PallasCoder
    gf2 = {}
    coders = []
    for codec_name in ("rs", "lrc"):
        for mm in ("int8", "bf16"):
            coders.append((codec_name, mm,
                           PallasCoder(codec=codec_name, mm=mm)))
    for codec_name, mm, pc in coders:
        bm = np.asarray(pc.codec.parity_bitmatrix())
        gf2[pc.codec.name] = {
            "dense_xors": rl.dense_gf2_work(bm),
            "effective_xors": rl.effective_gf2_work(bm)}
        k = pc.data_shards
        data = jax.random.randint(key, (k, n), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        for _ in range(reps):
            pc.encode(data)          # records encode_kernel
        if pc.fused_crc_ok:
            for _ in range(reps):
                pc.encode_with_crc(data)   # records encode_crc_kernel
        log(f"{pc.codec.name} {mm}: {2 * reps} fenced encodes recorded")

    # Plane overhead: the same encode with the ledger disarmed — the
    # difference is what always-on roofline accounting costs; the
    # disarmed path itself is one flag check (tests assert that).
    codec_name, mm, pc = coders[0]
    data = jax.random.randint(key, (pc.data_shards, n), 0, 256,
                              dtype=jnp.int32).astype(jnp.uint8)
    jax.block_until_ready(data)

    def wall(reps_=5):
        t0 = time.perf_counter()
        for _ in range(reps_):
            jax.block_until_ready(pc.encode(data))
        return (time.perf_counter() - t0) / reps_

    wall(2)  # warm
    armed_s = wall()
    rl.set_armed(False)
    disarmed_s = wall()
    rl.set_armed(True)
    overhead = {"armed_seconds_per_encode": round(armed_s, 6),
                "disarmed_seconds_per_encode": round(disarmed_s, 6),
                "overhead_fraction": round(
                    max(armed_s - disarmed_s, 0.0)
                    / max(disarmed_s, 1e-12), 6)}
    log(f"plane overhead: armed {armed_s * 1e3:.2f}ms vs disarmed "
        f"{disarmed_s * 1e3:.2f}ms per encode "
        f"({overhead['overhead_fraction']:.2%})")

    cons = rl.LEDGER.conservation()
    assert cons["ok"], f"conservation violated: {cons['violations']}"
    doc = {"round": 1, "platform": dev.platform, "n_bytes": n,
           "reps": reps, "peaks": peaks,
           "kernels": rl.LEDGER.kernel_table(),
           "gf2_work": gf2, "conservation": cons,
           "overhead": overhead}
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    log(f"wrote {out} ({len(doc['kernels'])} kernel rows, "
        f"conservation {'OK' if cons['ok'] else 'VIOLATED'})")


if __name__ == "__main__":
    if "--roofline" in sys.argv:
        args = [a for a in sys.argv[1:] if not a.startswith("--")]
        bench_roofline(*args[:1])
    else:
        main()
