"""Per-scheme kernel throughput: RS(10,4) / RS(16,4) / RS(8,3), int8+bf16.

Produces the measurement table in BASELINE.md's "Kernel roofline
analysis" (execution-fenced via bench.py's shared harness).  The column
rate it prints is the model quantity: throughput = k bytes/column x
column rate, column rate <= 6.0e9/s on v5e whatever fraction of the
128x128 MXU weight tile the (8r, 8k) bit-matrix fills.

Run on a real chip: python bench_schemes.py
"""
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bench import _make_timed, roofline_limit_mbps
from seaweedfs_tpu.ops import rs_bitmatrix
from seaweedfs_tpu.ops.coder_jax import plane_major
from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas

N = 64 * 1024 * 1024
BLOCK = 65536


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    dev = jax.devices()[0]
    log(f"device: {dev}")
    timed = _make_timed()
    key = jax.random.PRNGKey(0)
    results = {}
    for k, r in ((10, 4), (16, 4), (8, 3)):
        total = k + r
        pm = jnp.asarray(plane_major(
            rs_bitmatrix.parity_bitmatrix(k, total), r, k), jnp.float32)
        data = jax.random.randint(key, (k, N), 0, 256,
                                  dtype=jnp.int32).astype(jnp.uint8)
        jax.block_until_ready(data)
        want = NumpyCoder(k, r).encode(np.asarray(data[:, :BLOCK]))
        limit = roofline_limit_mbps(r, k)
        for mm in ("int8", "bf16"):
            # correctness gate per scheme AND dtype: an untested
            # lowering must never publish a number.
            got = np.asarray(apply_bitmatrix_pallas(
                pm, data[:, :BLOCK], r, k, block_n=BLOCK, mm=mm))
            assert np.array_equal(got, want), f"RS({k},{r}) {mm} wrong"
            dt = timed(apply_bitmatrix_pallas, pm, data, r, k,
                       block_n=BLOCK, mm=mm)
            mbps = data.nbytes / dt / 1e6
            if dev.platform == "tpu" and mbps > 1.05 * limit:
                log(f"RS({k:2d},{r}) {mm}: REJECT {mbps:.0f} MB/s — "
                    f"exceeds the physical roofline {limit:.0f} MB/s "
                    f"(harness bug, not a result)")
                continue
            cols = (N / dt) / 1e9
            log(f"RS({k:2d},{r}) {mm}: {mbps:8.0f} MB/s "
                f"({cols:.2f}e9 cols/s, {k}B/col)")
            results[f"rs{k}_{r}_{mm}"] = round(mbps, 1)
        del data
    print(json.dumps(results))


if __name__ == "__main__":
    main()
