#!/usr/bin/env python3
"""EC RS(10,4) throughput benchmark — prints ONE JSON line to stdout.

Metric: MB/s of volume data through an encode+reconstruct round trip on one
chip (the BASELINE.json north-star metric).  vs_baseline is the ratio to
the same round trip on the CPU via the native AVX2 PSHUFB coder
(klauspost-class, the reference's CPU path).

Design notes:
- Benchmark data is generated ON DEVICE (host->device over this
  environment's tunnel is orders of magnitude slower than HBM and would
  measure the tunnel, not the kernel).
- The timed loop is EXECUTION-FENCED: each iteration's output is folded
  into an on-device scalar accumulator, and the accumulator is
  host-fetched inside the timed region.  `jax.block_until_ready` alone
  has been observed not to fence dispatched work on the axon tunnel
  platform (round-1 numbers were 26x over the chip's compute roofline);
  a host fetch of a value that transitively depends on every iteration
  cannot return early.
- A roofline guard rejects any measurement that implies more FLOPs or
  HBM bytes than the chip can physically deliver — a too-good number is
  a harness bug, not a result.
- The whole TPU section runs with a watchdog: if the TPU runtime can't
  initialize (busy tunnel), we report the CPU numbers with a note instead
  of hanging the driver.

`python bench.py --e2e` additionally measures the real pipelines (see
bench_e2e) — CPU `ec.encode` of a generated volume, device
`write_ec_files` end-to-end including disk + transfer, and the `weed
benchmark` HTTP write/read path — and prints one JSON line per result.

All diagnostics go to stderr; stdout carries exactly one JSON line per
metric.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

SHARD_MB = int(os.environ.get("BENCH_SHARD_MB", "64"))
N = SHARD_MB * 1024 * 1024  # bytes per shard per call
ITERS = int(os.environ.get("BENCH_ITERS", "10"))
LOST = (2, 7, 11, 13)  # worst case: 4 shards lost

# Physical ceilings for one v5e-class chip.  Used to REJECT impossible
# measurements (VERDICT round 1: claimed 9.9e6 MB/s encode = 26x over
# peak).  The kernel does a (8*out_rows, 8*in_rows) @ (8*in_rows, n)
# matmul per n bytes/shard: 512 flops and 1.4 HBM bytes per data byte
# for RS(10,4) encode.
PEAK_FLOPS = 197e12   # bf16 MXU peak
PEAK_HBM_BPS = 0.82e12  # HBM bytes/s


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def roofline_limit_mbps(out_rows: int = 4, in_rows: int = 10) -> float:
    """Max physically possible data-MB/s for the bitmatrix kernel —
    the REJECT threshold (a measurement above this is a harness bug)."""
    flops_per_byte = 2.0 * (8 * out_rows) * (8 * in_rows) / in_rows
    hbm_per_byte = (in_rows + out_rows) / in_rows
    return min(PEAK_FLOPS / flops_per_byte, PEAK_HBM_BPS / hbm_per_byte) / 1e6


def shape_ceiling_mbps(in_rows: int = 10) -> float:
    """The ATTAINABLE ceiling for an (8r, 8k) matrix: the MXU streams
    one K-vector (= one byte-column = k data bytes) per column-slot at
    197e12/(2*128*128) = 6.0e9 columns/s whatever fraction of the
    128x128 weight tile the matrix fills — padding is structurally
    forfeit flops.  See BASELINE.md 'Kernel roofline analysis'."""
    cols_per_sec = PEAK_FLOPS / (2.0 * 128 * 128)
    return in_rows * cols_per_sec / 1e6


def bench_cpu() -> tuple[float, str]:
    """CPU round-trip MB/s + the coder actually used (single thread)."""
    from seaweedfs_tpu.ops.erasure import new_coder
    try:
        coder = new_coder(backend="native")
    except Exception as e:  # noqa: BLE001
        log(f"native coder unavailable ({e}); numpy fallback baseline")
        coder = new_coder(backend="numpy")
    n = min(N, 4 * 1024 * 1024)  # CPU pass is slow; 40MB per iter is ample
    data = np.random.default_rng(0).integers(
        0, 256, (10, n)).astype(np.uint8)
    shards = coder.encode_all(data)
    present = [i for i in range(14) if i not in LOST]
    have = {i: shards[i] for i in present}
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        coder.encode(data)
        coder.reconstruct(have, wanted=list(LOST))
    dt = (time.perf_counter() - t0) / iters
    mbps = data.nbytes / dt / 1e6
    name = type(coder).__name__
    log(f"cpu round-trip: {mbps:.0f} MB/s ({name})")
    return mbps, name


def _make_timed():
    """Build an execution-fenced timer (see module docstring)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _chain(acc, out):
        # Folding any slice of `out` into the accumulator makes the
        # final host fetch wait on the whole kernel that produced it
        # (kernels complete atomically); the slice keeps the fence's
        # own HBM traffic negligible.
        return acc ^ out[:, :256].astype(jnp.uint32).sum()

    def timed(fn, *args, iters=ITERS, **kw):
        out = fn(*args, **kw)
        acc = _chain(jnp.uint32(0), out)
        int(acc)  # warm: compile both, drain the pipe
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args, **kw)
            acc = _chain(acc, out)
        sink = int(acc)  # host fetch INSIDE the timed region: the fence
        dt = (time.perf_counter() - t0) / iters
        del sink
        return dt

    return timed


def bench_tpu() -> dict | None:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")
    on_tpu = dev.platform == "tpu"

    from seaweedfs_tpu.ops import rs_bitmatrix
    from seaweedfs_tpu.ops.coder_jax import plane_major
    from seaweedfs_tpu.ops.coder_pallas import apply_bitmatrix_pallas

    enc_pm = jnp.asarray(plane_major(
        rs_bitmatrix.parity_bitmatrix(10, 14), 4, 10), jnp.float32)
    present = tuple(i for i in range(14) if i not in LOST)
    dec_b, _used = rs_bitmatrix.decode_bitmatrix(10, 14, present, LOST)
    dec_pm = jnp.asarray(plane_major(np.asarray(dec_b), 4, 10), jnp.float32)

    # On-device data (bytes as uint8).
    key = jax.random.PRNGKey(0)
    data = jax.random.randint(key, (10, N), 0, 256, dtype=jnp.int32
                              ).astype(jnp.uint8)
    jax.block_until_ready(data)
    timed = _make_timed()
    limit = roofline_limit_mbps()

    def checked_mbps(dt: float, what: str) -> float | None:
        mbps = data.nbytes / dt / 1e6
        if on_tpu and mbps > 1.05 * limit:
            log(f"  REJECT {what}: {mbps:.0f} MB/s exceeds the physical "
                f"roofline ({limit:.0f} MB/s) — harness bug, not a result")
            return None
        return mbps

    # Self-tune the kernel.
    best = None
    for block_n in (8192, 16384, 32768, 65536):
        for mm in ("bf16", "int8"):
            try:
                dt = timed(apply_bitmatrix_pallas, enc_pm, data, 4, 10,
                           block_n=block_n, mm=mm, iters=3)
                mbps = checked_mbps(dt, f"tune {block_n}/{mm}")
                if mbps is None:
                    continue
                log(f"  tune block_n={block_n:6d} mm={mm}: {mbps:8.0f} MB/s")
                if best is None or mbps > best[0]:
                    best = (mbps, block_n, mm)
            except Exception as e:  # noqa: BLE001
                log(f"  tune block_n={block_n} mm={mm}: FAIL "
                    f"{type(e).__name__}: {str(e)[:80]}")
    if best is None:
        return None
    _, block_n, mm = best
    log(f"selected block_n={block_n} mm={mm} "
        f"(roofline {limit:.0f} MB/s)")

    t_enc = timed(apply_bitmatrix_pallas, enc_pm, data, 4, 10,
                  block_n=block_n, mm=mm)
    # Reconstruction: same kernel, decode matrix over the 10 survivors.
    t_dec = timed(apply_bitmatrix_pallas, dec_pm, data, 4, 10,
                  block_n=block_n, mm=mm)
    enc_mbps = checked_mbps(t_enc, "encode")
    dec_mbps = checked_mbps(t_dec, "reconstruct")
    if enc_mbps is None or dec_mbps is None:
        return None
    rt_mbps = data.nbytes / (t_enc + t_dec) / 1e6
    # Correctness spot check against the oracle on a slice.
    from seaweedfs_tpu.ops.coder_numpy import NumpyCoder
    sl = np.asarray(data[:, :65536])
    got = np.asarray(apply_bitmatrix_pallas(
        enc_pm, jnp.asarray(sl), 4, 10, block_n=block_n, mm=mm))
    ok = np.array_equal(got, NumpyCoder(10, 4).encode(sl))
    log(f"encode {enc_mbps:.0f} MB/s, reconstruct {dec_mbps:.0f} MB/s, "
        f"round-trip {rt_mbps:.0f} MB/s, correct={ok}")
    if not ok:
        return None
    return {"enc": enc_mbps, "dec": dec_mbps, "rt": rt_mbps,
            "platform": dev.platform, "on_tpu": on_tpu,
            "block_n": block_n, "mm": mm,
            "roofline_mbps": limit,
            "shape_ceiling_mbps": shape_ceiling_mbps()}


def main() -> None:
    if "--e2e" in sys.argv:
        import bench_e2e
        bench_e2e.main()
        return
    if os.environ.get("BENCH_CHILD") == "1":
        # Child mode: run the TPU section, emit JSON on fd 1.
        res = bench_tpu()
        print(json.dumps(res))
        return

    cpu_mbps, cpu_coder = bench_cpu()
    cpu_desc = ("cpu native avx2" if cpu_coder == "NativeCoder"
                else f"cpu {cpu_coder} (native lib NOT built)")

    # Run the device benchmark in a child with a watchdog so a wedged TPU
    # tunnel can't hang the driver.
    import subprocess
    env = dict(os.environ, BENCH_CHILD="1")
    res = None
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_TIMEOUT", "900")))
        sys.stderr.write(proc.stderr)
        for line in proc.stdout.strip().splitlines():
            try:
                res = json.loads(line)
            except json.JSONDecodeError:
                continue
    except subprocess.TimeoutExpired:
        log("TPU benchmark timed out (tunnel busy?); reporting CPU numbers")

    if res:
        value = res["rt"]
        ceiling = res.get("shape_ceiling_mbps") or 0
        note = (f"pallas mxu kernel on {res['platform']}, "
                f"block_n={res['block_n']} mm={res['mm']}; "
                f"encode {res['enc']:.0f} MB/s "
                f"({100 * res['enc'] / ceiling:.0f}% of the 60 GB/s "
                f"shape ceiling - see BASELINE.md roofline analysis), "
                f"reconstruct {res['dec']:.0f} MB/s; execution-fenced; "
                f"{cpu_desc} baseline {cpu_mbps:.0f} MB/s")
    else:
        value = cpu_mbps
        note = (f"TPU unavailable - {cpu_desc} round-trip reported; "
                "baseline == itself")
    print(json.dumps({
        "metric": "EC RS(10,4) encode+reconstruct(4 lost) MB/s per chip",
        "value": round(value, 1),
        "unit": "MB/s",
        "vs_baseline": round(value / cpu_mbps, 3) if cpu_mbps else None,
        "note": note,
    }))


if __name__ == "__main__":
    main()
