#!/usr/bin/env python3
"""BASELINE config #3: 256-volume batched EC rebuild wall-clock.

Measures the mesh-batched decode machinery (`batched_reconstruct`
grouped exactly as `ec.rebuild -batch` groups volumes) over 256
synthetic volumes that all lost the same 3 shards — the compiled-step
pipeline without the HTTP gather/scatter, which on this 1-core box
would measure the loopback stack, not the codec.

Runs on the 8-device virtual CPU mesh by default (real multi-chip
hardware is not reachable from this environment); on a real v5e-8 the
same script measures the production path.  Prints ONE JSON line.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python bench_batch_rebuild.py
"""

from __future__ import annotations

import json
import os
import sys
import time

# Default to the virtual CPU mesh unless the caller explicitly asks
# for the real chip: force_cpu() also unregisters the axon TPU plugin
# that sitecustomize installs BEFORE this script runs (env vars alone
# are too late).
if os.environ.get("BENCH_REBUILD_TPU") != "1":
    from seaweedfs_tpu.utils.jaxenv import force_cpu
    force_cpu(device_count=int(os.environ.get("BENCH_REBUILD_DEVICES",
                                              "8")))

import numpy as np  # noqa: E402

VOLUMES = int(os.environ.get("BENCH_REBUILD_VOLUMES", "256"))
SHARD_BYTES = int(os.environ.get("BENCH_REBUILD_SHARD_BYTES",
                                 str(1024 * 1024)))
LOST = (2, 7, 11)  # 3 shards lost (BASELINE config #3)
MAX_BATCH = 1 << 28


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from seaweedfs_tpu.parallel.cluster_rebuild import make_mesh
    from seaweedfs_tpu.parallel.sharded_codec import batched_reconstruct

    mesh = make_mesh()
    log(f"mesh: {mesh.shape} over {jax.devices()[0].platform}")
    present = tuple(s for s in range(14) if s not in LOST)
    used = present[:10]

    rng = np.random.default_rng(0)
    per_vol = SHARD_BYTES * (10 + len(LOST))
    vol_axis = mesh.shape["vol"]
    chunk_v = max(1, min(VOLUMES, MAX_BATCH // per_vol))
    chunk_v = max(vol_axis, chunk_v - chunk_v % vol_axis)
    log(f"{VOLUMES} volumes x {SHARD_BYTES >> 10}KB shards, "
        f"{chunk_v} volumes/step")

    # One representative stacked batch, reused for every step — the
    # gather is not what's being measured, and jit dispatch does not
    # cache across identical calls (each step executes fully; the
    # fenced block_until_ready proves it).
    stacked = rng.integers(0, 256, (chunk_v, 10, SHARD_BYTES),
                           dtype=np.uint8)

    # Warm: compile the step once.
    out = batched_reconstruct(stacked, present, LOST, mesh)
    jax.block_until_ready(out)

    # Every step runs a full chunk (the production path pads the tail
    # batch to the vol axis the same way).
    steps = -(-VOLUMES // chunk_v)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = batched_reconstruct(stacked, present, LOST, mesh)
        jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    data_bytes = VOLUMES * 10 * SHARD_BYTES
    print(json.dumps({
        "metric": f"batched ec.rebuild decode wall-clock, "
                  f"{VOLUMES} volumes x {SHARD_BYTES >> 10}KB shards, "
                  f"3 lost",
        "value": round(dt, 2),
        "unit": "s",
        "vs_baseline": None,
        "note": f"{steps} compiled steps on a "
                f"{dict(mesh.shape)} mesh "
                f"({jax.devices()[0].platform}); "
                f"{data_bytes / dt / 1e6:.0f} MB/s of volume data; "
                f"decode only — HTTP gather/scatter excluded "
                f"(loopback-bound on this 1-core box)",
    }))


if __name__ == "__main__":
    main()
