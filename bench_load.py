#!/usr/bin/env python3
"""Sustained mixed-workload load harness — the gating BENCH series for
the million-user front-door arc (ROADMAP 3).

Drives an OPEN-LOOP (fixed arrival rate — the load does not slow down
because the server did, which is what exposes tail collapse) mixed
read/write workload with a skewed (Zipf) key distribution against a
REAL subprocess cluster (master + 2 volume servers), then:

1. reports achieved throughput and client-measured p50/p95/p99 (both
   service latency — send to last byte — and open-loop latency from
   the scheduled arrival, which includes queueing);
2. CROSS-CHECKS the client-side tail against the server-side sliding
   quantile sketch (/debug/slo): the client feeds its own read
   latencies into an identical sketch (|sketch - exact| <= alpha*exact,
   the documented bound, gates hard), and the server quantiles must
   agree with the client's within alpha on both sides plus the
   measured per-request framing overhead (3x the p50 client-server gap
   + 2ms) — a self-calibrating tolerance recorded in the JSON;
3. runs the FAULT PHASE of the acceptance criteria: a deliberately
   injected slow fault (volume.read delay via /debug/faults) must
   produce a /debug/slow exemplar whose trace id resolves in
   /debug/traces, flip /cluster/healthz to degraded via the latency
   burn rate, and emit `slo.burn`;
4. (round 2) exercises the TIME-ATTRIBUTION plane: slow-exemplar
   phase budgets must sum to >= 90% of each exemplar's wall, the p99
   phase breakdown (where the tail's time goes) is published from the
   live phase sketches, `cluster.profile` merges collapsed stacks
   from every node of the subprocess cluster, and a second
   plane-DISARMED cluster measured in the same run prices the whole
   plane (always-on sampler + phase ledger + lock metering) as a
   closed-loop throughput ratio — the r02 overhead row and "before"
   baseline the ROADMAP-3 front-door refactor diffs against;
5. (round 3) the CONNECTION-SCALING phase: one single-volume cluster
   per transport holds a fleet of idle keep-alive connections
   (threads: CONN_BASE, aio: CONN_MULT x that), reads thread count and
   RSS from /proc plus /debug/conns from the server, probes p99 at the
   r02 rate THROUGH the held fleet, and profile-diffs the two
   transports' hottest frames — the front-door claim (10x the parked
   connections at flat threads/RSS and an unharmed tail) as a gate.
6. (round 4) the TENANCY / QoS noisy-neighbor phase: a flood tenant
   offers 10x its rps quota against the same volume server a victim
   tenant reads from; with -tenant.rules armed the flood's excess must
   shed as 429 + Retry-After, the flood's admitted rate must hold near
   its quota, and the victim's p99 under flood must stay within 3x its
   solo baseline with zero errors and zero 429s for in-quota traffic.
   A ruleless cluster publishes the QoS-off comparison.  Standalone:
   `python bench_load.py --tenant` writes only BENCH_tenant_r01.json.
7. (round 5) the GEO active/active phase: two cross-wired regions
   (epoch-fenced leases, zlib-compressed bidirectional shipping);
   region A's read p99 must stay within 1.5x its solo baseline while
   region B absorbs a local write storm that ships back over the WAN,
   and the storm's compressed-vs-raw ship bytes are published from
   both the shipper's ack accounting and the rlog.ship flow ledger
   row.  Standalone: `python bench_load.py --geo` writes only
   BENCH_geo_r01.json.
8. (round 6) the METADATA-PLANE HA phase: a sharded filer fleet
   (master -filer.shards=N + N filer processes with per-shard
   crash-safe journals) absorbs a closed-loop mkdir/rename storm
   through the shard-map-aware client, first with 1 shard (every
   commit serialized behind one primary's fsync + semi-sync fan-out)
   and then with the N primaries spread via filer.shards.move; the
   sharded fleet must beat the single-shard fleet by >= META_SCALE_X
   with zero errors and every shard's journal advanced.  Standalone:
   `python bench_load.py --meta` writes only BENCH_meta_r01.json.

Output: one JSON document (default BENCH_load_r03.json) — the BENCH
series beside the EC kernel numbers — plus BENCH_tenant_r01.json from
the round-4 tenant phase, BENCH_geo_r01.json from the round-5 geo
phase, and BENCH_meta_r01.json from the round-6 metadata-HA phase.

Knobs (env): BENCH_LOAD_QUICK=1 (seconds-scale smoke: the `slow`
pytest path), BENCH_LOAD_RATE, BENCH_LOAD_DURATION, BENCH_LOAD_WARMUP,
BENCH_LOAD_KEYS, BENCH_LOAD_SIZE, BENCH_LOAD_WORKERS, BENCH_LOAD_ZIPF,
BENCH_LOAD_WRITE_FRACTION; the meta phase reads BENCH_META_SHARDS,
BENCH_META_FILERS, BENCH_META_SECONDS, BENCH_META_WORKERS,
BENCH_META_SCALE_X.  CPU-only; no accelerator involved.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUICK = os.environ.get("BENCH_LOAD_QUICK", "") in ("1", "true")


def _env(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


RATE = _env("BENCH_LOAD_RATE", 150.0 if QUICK else 400.0)
DURATION = _env("BENCH_LOAD_DURATION", 5.0 if QUICK else 30.0)
WARMUP = _env("BENCH_LOAD_WARMUP", 1.0 if QUICK else 5.0)
KEYS = int(_env("BENCH_LOAD_KEYS", 80 if QUICK else 400))
SIZE = int(_env("BENCH_LOAD_SIZE", 4096 if QUICK else 8192))
# Enough for the offered concurrency (rate x ~2ms service time << 8)
# with headroom for tail stalls; hundreds of idle threads would convoy
# the CLIENT's own tail on the GIL and corrupt the measurement.
WORKERS = int(_env("BENCH_LOAD_WORKERS", 16 if QUICK else 24))
ZIPF_S = _env("BENCH_LOAD_ZIPF", 1.2)
WRITE_FRACTION = _env("BENCH_LOAD_WRITE_FRACTION", 0.2)
# Burn windows for the fault phase: short enough that the post-load
# cool-down (both windows must shed the healthy main-run traffic
# before the all-slow phase can dominate them) fits a bench run.
SHORT_WINDOW = 6.0 if QUICK else 15.0
LONG_WINDOW = 12.0 if QUICK else 30.0
SLO_READ_P99 = 0.25          # generous: the main run must NOT burn
FAULT_DELAY = 0.4            # >> objective: every faulted read burns
ALPHA = 0.01                 # sketch bound (stats/sketch.py)

REPO = os.path.dirname(os.path.abspath(__file__))


def log(*args):
    print(*args, file=sys.stderr, flush=True)


class Cluster:
    """Subprocess master + 2 volume servers.

    attribution=True runs the full time-attribution plane (always-on
    continuous profiler + /debug/pprof, phase ledger, lock metering);
    attribution=False disarms all three — the overhead comparison's
    control group, measured in the same bench run."""

    def __init__(self, tmp: str, attribution: bool = True,
                 traces: bool = True, transport: str | None = None,
                 volumes: int = 2, tenant_rules: str | None = None):
        from seaweedfs_tpu.cluster import rpc
        self.tmp = tmp
        self.n_volumes = volumes
        self.procs: list[subprocess.Popen] = []
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   SEAWEEDFS_TPU_TRACES="1" if traces else "0",
                   SEAWEEDFS_TPU_FAULTS_DEBUG="1",
                   # Deterministic string hashing: without this, each
                   # server process draws a random dict-collision
                   # profile and cluster instances differ by a few %
                   # throughput from SEED LUCK — fatal for an A/B
                   # that prices a 3% plane.
                   PYTHONHASHSEED="0",
                   SEAWEEDFS_TPU_SLO_SHORT_WINDOW=str(SHORT_WINDOW),
                   SEAWEEDFS_TPU_SLO_LONG_WINDOW=str(LONG_WINDOW))
        if attribution:
            env.update(SEAWEEDFS_TPU_PPROF="1",
                       # short ring windows so ?window= has data
                       # within bench timescales
                       SEAWEEDFS_TPU_PPROF_WINDOW="5")
        else:
            env.update(SEAWEEDFS_TPU_PPROF="0",
                       SEAWEEDFS_TPU_LOCK_METER="0",
                       SEAWEEDFS_TPU_PHASES="0")
        mport = rpc.free_port()
        self.master_url = f"http://127.0.0.1:{mport}"
        margs = ["master", f"-port={mport}", f"-mdir={tmp}/meta"]
        if tenant_rules:
            margs.append(f"-tenant.rules={tenant_rules}")
        self._spawn(margs, env)
        self.volume_urls = []
        for i in range(volumes):
            vport = rpc.free_port()
            d = f"{tmp}/vs{i}"
            os.makedirs(d)
            args = ["volume", f"-port={vport}", f"-dir={d}",
                    "-max=50", f"-mserver=127.0.0.1:{mport}",
                    f"-slo.read.p99={SLO_READ_P99}",
                    "-slo.availability=99.9"]
            if tenant_rules:
                args.append(f"-tenant.rules={tenant_rules}")
            if transport:
                args.append(f"-transport={transport}")
            self._spawn(args, env)
            self.volume_urls.append(f"127.0.0.1:{vport}")

    def _spawn(self, args: list[str], env: dict) -> None:
        p = subprocess.Popen(
            [sys.executable, "-m", "seaweedfs_tpu"] + args,
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        self.procs.append(p)

    def wait_ready(self, timeout: float = 60.0) -> None:
        from seaweedfs_tpu.cluster import rpc
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                st, doc = rpc.call_status(
                    f"{self.master_url}/cluster/healthz", timeout=2.0)
                if st == 200 and \
                        len(doc.get("nodes", [])) == self.n_volumes:
                    return
            except Exception:  # noqa: BLE001 — still starting
                pass
            time.sleep(0.2)
        raise TimeoutError("subprocess cluster never became healthy")

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def populate(client, n: int, size: int, rng) -> list[str]:
    payload = rng.integers(0, 256, size, dtype="uint8").tobytes()
    fids = []
    for _ in range(n):
        fids.append(client.upload_data(payload))
    return fids


def percentiles(vals: list[float]) -> dict:
    import math

    import numpy as np
    if not vals:
        return {"count": 0}
    arr = np.sort(np.asarray(vals))

    def nearest(q):
        # Nearest-rank with ceil — the SAME rank convention
        # QuantileSketch.quantile uses.  A round-half-up here would
        # compare adjacent order statistics against the sketch and
        # fail the alpha gate on tails where neighbors differ > alpha.
        return float(arr[max(0, math.ceil(q * len(arr)) - 1)])
    return {"count": len(arr), "p50": nearest(0.5),
            "p95": nearest(0.95), "p99": nearest(0.99)}


def run_load(cluster: Cluster) -> dict:
    """Open-loop mixed workload; returns client-side results + the
    op log for the window-matched server comparison."""
    import numpy as np

    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    rng = np.random.default_rng(1)
    client = WeedClient(cluster.master_url)
    log(f"populating {KEYS} keys of {SIZE}B ...")
    fids = populate(client, KEYS, SIZE, rng)

    # Zipf-ranked key popularity: rank r drawn with p ~ 1/r^s.
    ranks = np.arange(1, KEYS + 1)
    probs = 1.0 / ranks ** ZIPF_S
    probs /= probs.sum()
    total_ops = int(RATE * (WARMUP + DURATION))
    key_choice = rng.choice(KEYS, size=total_ops, p=probs)
    is_write = rng.random(total_ops) < WRITE_FRACTION
    payload = rng.integers(0, 256, SIZE, dtype="uint8").tobytes()

    ops: list[tuple] = []   # (kind, sched, start, end, status)
    ops_lock = threading.Lock()
    pool = ThreadPoolExecutor(max_workers=WORKERS)
    t0 = time.perf_counter()

    def one(i: int, sched: float) -> None:
        kind = "write" if is_write[i] else "read"
        start = time.perf_counter()
        status = 200
        try:
            if kind == "write":
                client.upload_data(payload)
            else:
                client.download(fids[key_choice[i]])
        except rpc.RpcError as e:
            status = e.status
        except Exception:  # noqa: BLE001 — connection-level failure
            status = 599
        end = time.perf_counter()
        with ops_lock:
            ops.append((kind, sched, start, end, status))

    log(f"open loop: {RATE:g} req/s for {WARMUP + DURATION:g}s "
        f"({WRITE_FRACTION:.0%} writes, zipf s={ZIPF_S:g}) ...")
    futures = []
    for i in range(total_ops):
        sched = t0 + i / RATE
        now = time.perf_counter()
        if sched > now:
            time.sleep(sched - now)
        futures.append(pool.submit(one, i, sched))
    for f in futures:
        f.result()
    pool.shutdown(wait=True)
    t_end = time.perf_counter()
    elapsed = t_end - t0

    warm_cut = t0 + WARMUP
    recorded = [op for op in ops if op[1] >= warm_cut]
    reads = [op for op in recorded if op[0] == "read"]
    writes = [op for op in recorded if op[0] == "write"]
    errors = sum(1 for op in recorded if op[4] >= 500)
    shed = sum(1 for op in recorded if op[4] == 429)

    def svc(rows):
        return [r[3] - r[2] for r in rows]

    def sched_lat(rows):
        return [r[3] - r[1] for r in rows]

    # The client's own sketch over the same read latencies: the
    # documented |sketch - exact| <= alpha*exact bound, checked hard.
    from seaweedfs_tpu.stats.sketch import QuantileSketch
    csk = QuantileSketch(alpha=ALPHA)
    for v in svc(reads):
        csk.observe(v)
    exact = percentiles(svc(reads))
    sketch_err = {}
    within_alpha = True
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        est = csk.quantile(q)
        rel = abs(est - exact[key]) / exact[key] if exact[key] else 0.0
        sketch_err[key] = round(rel, 6)
        if rel > ALPHA + 1e-9:
            within_alpha = False

    # Window-matched subset for the server comparison: the server's
    # sliding sketch only covers its short window, so compare against
    # the client reads that finished inside it.
    cut = t_end - SHORT_WINDOW * (1.0 - 1.0 / 6.0)
    recent_reads = [r for r in reads if r[3] >= cut] or reads
    return {
        "client": client,
        "fids": fids,
        "elapsed": elapsed,
        "achieved_rps": len(recorded) / max(elapsed - WARMUP, 1e-9),
        "totals": {"ops": len(recorded), "reads": len(reads),
                   "writes": len(writes), "errors": errors,
                   "shed": shed,
                   "shed_rate": round(shed / max(len(recorded), 1), 6)},
        "read": {**exact,
                 "sched": percentiles(sched_lat(reads))},
        "write": {**percentiles(svc(writes)),
                  "sched": percentiles(sched_lat(writes))},
        "recent_read": percentiles(svc(recent_reads)),
        "sketch_vs_exact": {"rel_err": sketch_err,
                            "alpha": ALPHA,
                            "within_alpha": within_alpha},
    }


def server_read_quantiles(cluster: Cluster) -> dict:
    """Merge both volume servers' live read sketches (the same
    mergeable wire format /cluster/healthz folds)."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.stats.slo import merge_sketch_dicts
    dicts, per_node = [], []
    for url in cluster.volume_urls:
        snap = rpc.call(f"http://{url}/debug/slo")
        dicts.append(snap["read"]["sketch"])
        per_node.append({"node": url,
                         **snap["read"]["quantiles"]})
    merged = merge_sketch_dicts(dicts)
    if merged is None or merged.count == 0:
        return {"count": 0, "per_node": per_node}
    return {"count": merged.count,
            "p50": merged.quantile(0.5),
            "p95": merged.quantile(0.95),
            "p99": merged.quantile(0.99),
            "per_node": per_node}


def agreement(client_q: dict, server_q: dict) -> dict:
    """Client-vs-server tail agreement.  The server sketch measures
    handler time; the client adds framing + loopback overhead, which
    the p50 gap measures directly — the tolerance is alpha on both
    sides plus 3x that constant plus 2ms, all recorded."""
    overhead = max(0.0, client_q.get("p50", 0.0)
                   - server_q.get("p50", 0.0))
    out = {"overhead_p50": round(overhead, 6), "alpha": ALPHA,
           "per_quantile": {}, "within_bound": True}
    for key in ("p95", "p99"):
        c, s = client_q.get(key), server_q.get(key)
        if not c or not s:
            out["within_bound"] = False
            continue
        tol = ALPHA * (c + s) + 3.0 * overhead + 0.002
        diff = abs(c - s)
        out["per_quantile"][key] = {
            "client": round(c, 6), "server": round(s, 6),
            "diff": round(diff, 6), "tolerance": round(tol, 6),
            "ok": diff <= tol}
        if diff > tol:
            out["within_bound"] = False
    return out


def fault_phase(cluster: Cluster, client, fids: list[str]) -> dict:
    """Acceptance: slow fault -> /debug/slow exemplar -> trace resolves
    -> healthz degraded via burn -> slo.burn emitted."""
    from seaweedfs_tpu.cluster import rpc
    vs0 = cluster.volume_urls[0]
    checks = {"exemplar_recorded": False, "trace_resolved": False,
              "healthz_degraded": False, "slo_burn_emitted": False}

    # Cool down: both burn windows must forget the healthy main run,
    # or the fast-read majority would dilute the slow fraction below
    # the fast-burn threshold.
    cool = LONG_WINDOW * (1.0 + 1.0 / 6.0) + 1.0
    log(f"fault phase: cooling {cool:.0f}s so the burn windows forget "
        f"the healthy run ...")
    time.sleep(cool)

    # Find fids actually hosted on vs0 so every faulted read hits it.
    local = []
    for fid in fids[:50]:
        vid = int(fid.split(",")[0])
        try:
            locs = client.lookup(vid)
        except Exception:  # noqa: BLE001
            continue
        if any(loc.get("url") == vs0 for loc in locs):
            local.append(fid)
        if len(local) >= 4:
            break
    if not local:
        log("no fid hosted on vs0 — cannot run fault phase")
        return checks

    log(f"arming volume.read delay:{FAULT_DELAY}s on {vs0} ...")
    rpc.call(f"http://{vs0}/debug/faults?point=volume.read"
             f"&spec=delay:{FAULT_DELAY}", "POST")
    stop = time.time() + (4.0 if QUICK else 10.0)

    def slow_reader():
        i = 0
        while time.time() < stop:
            try:
                rpc.call(f"http://{vs0}/{local[i % len(local)]}",
                         timeout=30.0)
            except Exception:  # noqa: BLE001
                pass
            i += 1

    threads = [threading.Thread(target=slow_reader) for _ in range(6)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    rpc.call(f"http://{vs0}/debug/faults?point=volume.read&spec=off",
             "POST")

    slow = rpc.call(f"http://{vs0}/debug/slow")
    exemplars = [e for e in slow.get("exemplars", [])
                 if e.get("family") == "/needle"
                 and e.get("seconds", 0) >= FAULT_DELAY]
    if exemplars:
        checks["exemplar_recorded"] = True
        tid = exemplars[0].get("trace_id", "")
        if tid:
            try:
                trace = rpc.call(
                    f"http://{vs0}/debug/traces?trace={tid}")
                checks["trace_resolved"] = bool(trace.get("spans"))
            except Exception:  # noqa: BLE001
                pass

    # Burn rides the heartbeat (2s pulse) to the master.
    deadline = time.time() + 25.0
    while time.time() < deadline:
        st, doc = rpc.call_status(
            f"{cluster.master_url}/cluster/healthz", timeout=5.0)
        if st == 503 and any("SLO fast burn" in p
                             for p in doc.get("problems", [])):
            checks["healthz_degraded"] = True
            break
        time.sleep(0.5)
    try:
        evs = rpc.call(f"http://{vs0}/debug/events?type=slo.burn")
        checks["slo_burn_emitted"] = bool(evs.get("events"))
        if evs.get("events"):
            checks["slo_burn_trace_id"] = \
                evs["events"][-1].get("trace_id", "")
    except Exception:  # noqa: BLE001
        pass
    return checks


# Overhead rounds are deliberately SHORT: shared boxes oscillate
# ±10-15% in available CPU on 20-40s periods, so an ABBA block must
# complete well inside one period for its drift-cancelling algebra to
# hold — many short blocks beat few long ones for a 2-3% effect.
SAT_SECONDS = _env("BENCH_LOAD_SAT_SECONDS", 2.0 if QUICK else 2.5)
SAT_WORKERS = int(_env("BENCH_LOAD_SAT_WORKERS", 6))
SAT_ROUNDS = int(_env("BENCH_LOAD_SAT_ROUNDS", 3))
# Overhead blocks: the on/off comparison runs ABBA round blocks
# (on, off, off, on) — a linear machine drift inside a block hits
# both sides symmetrically and cancels in the block's ratio
# (sum(A) / sum(B)); the median across blocks then discards whole
# blocks hit by a noisy-neighbor burst.
SAT_BLOCKS = int(_env("BENCH_LOAD_SAT_BLOCKS", 3 if QUICK else 8))
# Fresh-cluster warmup before timed rounds: a just-spawned server
# climbs for several seconds (thread creation, allocator, page cache,
# the scrub daemon's initial pass) — measured rounds must start past
# that knee on BOTH sides of the overhead pair or the comparison
# prices warmup, not the plane.
SAT_WARMUP = _env("BENCH_LOAD_SAT_WARMUP", 6.0 if QUICK else 12.0)


def _resolve_read_urls(cluster: Cluster, fids: list[str]) -> list[str]:
    """Direct volume-server URLs for the fids: the saturation rounds
    must price the SERVER plane, not client lookups."""
    from seaweedfs_tpu.cluster.client import WeedClient
    client = WeedClient(cluster.master_url)
    urls = []
    for fid in fids:
        vid = int(fid.split(",")[0])
        try:
            locs = client.lookup(vid)
        except Exception:  # noqa: BLE001
            continue
        if locs:
            urls.append(f"http://{locs[0]['url']}/{fid}")
    assert urls, "no readable fid for the saturation round"
    return urls


def _sat_round(urls: list[str],
               seconds: float) -> tuple[float, int]:
    """One closed-loop read round: SAT_WORKERS hammering random fids
    as fast as they go; returns (achieved req/s, request count)."""
    import random as _random

    from seaweedfs_tpu.cluster import rpc
    stop = time.perf_counter() + seconds
    counts = [0] * SAT_WORKERS

    def worker(wi: int) -> None:
        rng = _random.Random(wi)
        n = 0
        while time.perf_counter() < stop:
            try:
                rpc.call(rng.choice(urls), timeout=10.0)
                n += 1
            except Exception:  # noqa: BLE001
                pass
        counts[wi] = n

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(SAT_WORKERS)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    total = sum(counts)
    return total / (time.perf_counter() - t0), total


def _server_cpu_seconds(cluster: Cluster) -> float:
    """Summed cpu_seconds of every server process (the /admin/status
    and /cluster/status counters) — the denominator-side of the
    CPU-per-request overhead measurand."""
    from seaweedfs_tpu.cluster import rpc
    total = rpc.call(
        f"{cluster.master_url}/cluster/status")["cpu_seconds"]
    for u in cluster.volume_urls:
        total += rpc.call(
            f"http://{u}/admin/status")["cpu_seconds"]
    return total


def saturation_rps(cluster: Cluster, fids: list[str],
                   warmup: float = 0.0) -> dict:
    """Closed-loop read throughput: median of SAT_ROUNDS rounds (the
    overhead comparison's measurand — open-loop rates are pinned by
    the arrival schedule and can't price a 1-3% tax).  `warmup`
    seconds of identical untimed traffic run first."""
    urls = _resolve_read_urls(cluster, fids)
    if warmup > 0:
        _sat_round(urls, warmup)
    rounds = [_sat_round(urls, SAT_SECONDS)[0]
              for _ in range(SAT_ROUNDS)]
    ordered = sorted(rounds)
    return {"rounds_rps": [round(r, 1) for r in rounds],
            "median_rps": round(ordered[len(ordered) // 2], 1),
            "workers": SAT_WORKERS, "seconds": SAT_SECONDS,
            "warmup": warmup}


def phase_budget(cluster: Cluster) -> dict:
    """Pull every slow exemplar carrying a phase budget from both
    volume servers and check the budget-sums-to-wall invariant, plus
    the p99 phase breakdown from the live phase sketches."""
    from seaweedfs_tpu.cluster import rpc
    fractions, sample = [], None
    shares: dict[str, float] = {}
    for url in cluster.volume_urls:
        slow = rpc.call(f"http://{url}/debug/slow")
        for e in slow.get("exemplars", []):
            ph = e.get("phases")
            if not ph or not e.get("seconds"):
                continue
            covered = sum(v for k, v in ph.items() if k != "queue")
            fractions.append(covered / e["seconds"])
            if sample is None:
                sample = e
            for k, v in ph.items():
                shares[k] = shares.get(k, 0.0) + v
    total_share = sum(shares.values()) or 1.0
    out = {
        "exemplars_with_phases": len(fractions),
        "mean_fraction": round(sum(fractions) / len(fractions), 4)
        if fractions else 0.0,
        "min_fraction": round(min(fractions), 4) if fractions else 0.0,
        "slow_wall_share": {k: round(v / total_share, 4)
                            for k, v in sorted(shares.items())},
        "sample_exemplar": sample,
        "budget_ok": bool(fractions) and
        (sum(fractions) / len(fractions)) >= 0.9,
    }
    # p99 phase breakdown of the data plane from the live sketches
    # (SeaweedFS_request_phase_seconds source) on the first node that
    # has one.
    for url in cluster.volume_urls:
        snap = rpc.call(f"http://{url}/debug/slo")
        needle = snap.get("phases", {}).get("/needle")
        if needle:
            out["p99_breakdown"] = {
                phase: round(d.get("p99", 0.0), 6)
                for phase, d in sorted(needle.items())}
            break
    return out


def cluster_profile_merge(cluster: Cluster) -> dict:
    """Acceptance: cluster.profile across the 3-node subprocess
    cluster merges collapsed stacks carrying frames from >= 2 distinct
    nodes.  A live concurrent sample runs while a short read burst
    keeps every role busy."""
    from seaweedfs_tpu.shell.command_profile import (
        NODE_FRAME_PREFIX, merge_cluster_profile)
    urls = [cluster.master_url] + \
        [f"http://{u}" for u in cluster.volume_urls]
    merged, nodes = merge_cluster_profile(urls, seconds=1.5)
    distinct = {stack.split(";", 1)[0] for stack in merged}
    distinct = {f for f in distinct
                if f.startswith(NODE_FRAME_PREFIX)}
    return {"nodes_answering": len(nodes),
            "nodes_in_merged_stacks": len(distinct),
            "total_samples": sum(merged.values()),
            "distinct_stacks": len(merged),
            "merged_ok": len(distinct) >= 2}


# -- round 3: connection scaling (ROADMAP 3, the front-door claim) ----------
#
# The threaded transport pins one OS thread per keep-alive connection;
# the aio loop parks idle sockets in a selector and only borrows a
# worker while a request is in flight.  The phase holds a big fleet of
# idle keep-alive connections against a single volume server per
# transport (aio holds CONN_MULT x the threaded fleet), reads
# thread-count/RSS from /proc, then probes p99 at the r02 rate THROUGH
# the held load — the million-user front door priced in numbers.
CONN_BASE = int(_env("BENCH_LOAD_CONNS", 40 if QUICK else 200))
CONN_MULT = int(_env("BENCH_LOAD_CONNS_MULT", 10))
PROBE_SECONDS = _env("BENCH_LOAD_PROBE_SECONDS", 3.0 if QUICK else 10.0)
PROBE_WORKERS = int(_env("BENCH_LOAD_PROBE_WORKERS", 12))


def _proc_stat(pid: int) -> dict:
    threads = rss_kb = 0
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("Threads:"):
                threads = int(line.split()[1])
            elif line.startswith("VmRSS:"):
                rss_kb = int(line.split()[1])
    return {"threads": threads, "rss_kb": rss_kb}


def _hold_keepalive(host: str, port: int, n: int) -> list:
    """Open n keep-alive connections, each completing ONE request and
    then going idle — the parked-fleet shape of a million-user front
    door (mostly-idle persistent clients)."""
    import socket as _socket
    req = (b"GET /admin/status HTTP/1.1\r\nHost: bench\r\n"
           b"Connection: keep-alive\r\n\r\n")
    conns = []
    for _ in range(n):
        s = _socket.create_connection((host, port), timeout=10.0)
        s.sendall(req)
        # Read status line + headers + body (Content-Length framed).
        buf = b""
        while b"\r\n\r\n" not in buf:
            buf += s.recv(65536)
        head, rest = buf.split(b"\r\n\r\n", 1)
        clen = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                clen = int(line.split(b":", 1)[1])
        while len(rest) < clen:
            rest += s.recv(65536)
        conns.append(s)
    return conns


def _probe_open_loop(urls: list[str], rate: float,
                     seconds: float) -> dict:
    """Fixed-arrival-rate read probe (open loop — the arrival schedule
    does not slow down with the server, so tail collapse shows)."""
    import random as _random

    from seaweedfs_tpu.cluster import rpc
    total = int(rate * seconds)
    lat: list[float] = []
    errs = [0]
    lock = threading.Lock()
    idx = [0]
    t0 = time.perf_counter() + 0.2

    def worker(wi: int) -> None:
        rng = _random.Random(wi)
        while True:
            with lock:
                i = idx[0]
                if i >= total:
                    return
                idx[0] += 1
            now = time.perf_counter()
            due = t0 + i / rate
            if due > now:
                time.sleep(due - now)
            t1 = time.perf_counter()
            try:
                rpc.call(rng.choice(urls), timeout=10.0)
                d = time.perf_counter() - t1
                with lock:
                    lat.append(d)
            except Exception:  # noqa: BLE001
                with lock:
                    errs[0] += 1

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(PROBE_WORKERS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    out = percentiles(lat)
    out["errors"] = errs[0]
    out["achieved_rps"] = round(len(lat) / max(elapsed, 1e-9), 1)
    return out


def _top_frames(stacks: dict, n: int = 8) -> list:
    """Collapse a {stack: samples} profile to its hottest leaf frames
    — the transport diff reads straight off this list."""
    leaves: dict[str, int] = {}
    for stack, count in stacks.items():
        leaf = stack.rsplit(";", 1)[-1]
        leaves[leaf] = leaves.get(leaf, 0) + count
    ranked = sorted(leaves.items(), key=lambda kv: -kv[1])[:n]
    total = sum(leaves.values()) or 1
    return [{"frame": f, "share": round(c / total, 3)}
            for f, c in ranked]


def connection_scaling() -> dict:
    """One single-volume cluster per transport: hold the idle fleet,
    read /proc + /debug/conns, probe p99 through it, and profile the
    server under probe for the transport diff."""
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.shell.command_profile import merge_cluster_profile
    out: dict = {"conns": {"threads": CONN_BASE,
                           "aio": CONN_BASE * CONN_MULT}}
    for transport in ("threads", "aio"):
        n_conns = out["conns"][transport]
        tmp = tempfile.mkdtemp(prefix=f"bench_conn_{transport}_")
        cluster = Cluster(tmp, attribution=True, traces=False,
                          transport=transport, volumes=1)
        conns: list = []
        try:
            cluster.wait_ready()
            import numpy as np
            rng = np.random.default_rng(1)
            client = WeedClient(cluster.master_url)
            urls = _resolve_read_urls(
                cluster, populate(client, min(KEYS, 100), SIZE, rng))
            vs_pid = cluster.procs[1].pid
            host, port = cluster.volume_urls[0].split(":")
            before = _proc_stat(vs_pid)
            t_open = time.perf_counter()
            conns = _hold_keepalive(host, int(port), n_conns)
            open_s = time.perf_counter() - t_open
            time.sleep(1.0)  # let per-conn threads/buffers settle
            after = _proc_stat(vs_pid)
            snap = rpc.call(
                f"http://{cluster.volume_urls[0]}/debug/conns?limit=1")
            prof_box: dict = {}

            def sample_profile() -> None:
                merged, _nodes = merge_cluster_profile(
                    [f"http://{cluster.volume_urls[0]}"],
                    seconds=min(PROBE_SECONDS - 1.0, 5.0))
                prof_box.update(merged)

            prof_thread = threading.Thread(target=sample_profile)
            prof_thread.start()
            probe = _probe_open_loop(urls, RATE, PROBE_SECONDS)
            prof_thread.join()
            out[transport] = {
                "held_conns": len(conns),
                "server_open_conns": snap["open"],
                "transport_reported": snap["transport"],
                "open_all_s": round(open_s, 2),
                "threads_before": before["threads"],
                "threads_held": after["threads"],
                "rss_before_kb": before["rss_kb"],
                "rss_held_kb": after["rss_kb"],
                "rss_delta_kb": after["rss_kb"] - before["rss_kb"],
                "probe_p99_s": probe.get("p99"),
                "probe": probe,
                "top_frames": _top_frames(prof_box),
            }
            log(f"  {transport}: {len(conns)} conns held, "
                f"{after['threads']} threads "
                f"(+{after['threads'] - before['threads']}), "
                f"rss +{out[transport]['rss_delta_kb']} kB, "
                f"probe p99 {probe.get('p99', 0):.4f}s "
                f"@ {probe['achieved_rps']} rps")
        finally:
            for s in conns:
                try:
                    s.close()
                except OSError:
                    pass
            cluster.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    th, ai = out["threads"], out["aio"]
    # The claim: 10x the parked fleet at no worse RSS, flat thread
    # count, and a tail that doesn't pay for the idle crowd.
    out["conn_ratio"] = round(ai["held_conns"] /
                              max(th["held_conns"], 1), 1)
    out["aio_threads_flat"] = \
        ai["threads_held"] - ai["threads_before"] <= \
        (th["threads_held"] - th["threads_before"]) // 4
    out["rss_ok"] = ai["rss_delta_kb"] <= \
        max(th["rss_delta_kb"] * 1.25, 16 * 1024)
    out["frames_diff"] = {
        "threads_only": [f["frame"] for f in th["top_frames"]
                         if f["frame"] not in
                         {g["frame"] for g in ai["top_frames"]}],
        "aio_only": [f["frame"] for f in ai["top_frames"]
                     if f["frame"] not in
                     {g["frame"] for g in th["top_frames"]}],
    }
    out["scaling_ok"] = (out["conn_ratio"] >= CONN_MULT
                         and ai["server_open_conns"] >=
                         out["conns"]["aio"]
                         and out["aio_threads_flat"]
                         and out["rss_ok"])
    return out


# -- round 4: the tenancy / QoS noisy-neighbor phase -------------------------
#
# One flood tenant offers 10x its rps quota while a victim tenant runs
# an in-quota read load against the same volume server.  With the QoS
# plane armed (-tenant.rules) the flood's excess is shed as cheap 429s
# and the victim's tail must hold: p99 under flood within 3x the solo
# baseline measured on the SAME cluster, zero errors and zero 429s for
# the in-quota victim.  A second ruleless cluster publishes the
# QoS-off comparison (what the victim pays when nobody is throttled).

TEN_QUOTA_RPS = _env("BENCH_TENANT_QUOTA_RPS", 20.0)
TEN_FLOOD_X = _env("BENCH_TENANT_FLOOD_X", 10.0)
TEN_VICTIM_RATE = _env("BENCH_TENANT_VICTIM_RATE", 50.0)
TEN_SECONDS = _env("BENCH_TENANT_SECONDS", 4.0 if QUICK else 10.0)
TEN_WORKERS = int(_env("BENCH_TENANT_WORKERS", 8))


def _tenant_probe(urls: list[str], tenant: str, rate: float,
                  seconds: float) -> dict:
    """Open-loop reads AS a tenant (X-Weed-Tenant on the wire),
    classifying admitted / 429-shed / errored per request; the
    percentiles cover the admitted requests only (shed requests get
    Retry-After, they are not latency samples)."""
    import random as _random

    from seaweedfs_tpu.cluster import rpc
    hdr = {"X-Weed-Tenant": tenant}
    total = int(rate * seconds)
    lat: list[float] = []
    shed = [0]
    errs = [0]
    retry_after = [0.0]
    lock = threading.Lock()
    idx = [0]
    t0 = time.perf_counter() + 0.1

    def worker(wi: int) -> None:
        rng = _random.Random(wi)
        while True:
            with lock:
                i = idx[0]
                if i >= total:
                    return
                idx[0] += 1
            due = t0 + i / rate
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            t1 = time.perf_counter()
            try:
                rpc.call(rng.choice(urls), timeout=10.0, headers=hdr)
                d = time.perf_counter() - t1
                with lock:
                    lat.append(d)
            except rpc.RpcError as e:
                with lock:
                    if e.status == 429:
                        shed[0] += 1
                        if e.retry_after:
                            retry_after[0] = max(retry_after[0],
                                                 float(e.retry_after))
                    else:
                        errs[0] += 1
            except Exception:  # noqa: BLE001 — connection-level failure
                with lock:
                    errs[0] += 1

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(TEN_WORKERS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    elapsed = time.perf_counter() - t0
    out = percentiles(lat)
    out["offered"] = total
    out["admitted"] = len(lat)
    out["shed_429"] = shed[0]
    out["errors"] = errs[0]
    out["retry_after_max_s"] = round(retry_after[0], 3)
    out["offered_rps"] = round(total / max(elapsed, 1e-9), 1)
    out["admitted_rps"] = round(len(lat) / max(elapsed, 1e-9), 1)
    return out


def tenant_phase() -> dict:
    """Noisy-neighbor A/B: QoS-on (rules file) vs QoS-off (ruleless),
    fresh single-volume cluster each, same key set and rates."""
    import numpy as np

    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    flood_rate = TEN_QUOTA_RPS * TEN_FLOOD_X
    doc: dict = {"quota_rps": TEN_QUOTA_RPS,
                 "flood_offered_rps": flood_rate,
                 "victim_rate_rps": TEN_VICTIM_RATE,
                 "seconds": TEN_SECONDS, "workers": TEN_WORKERS}
    for mode in ("qos_on", "qos_off"):
        tmp = tempfile.mkdtemp(prefix=f"bench_tenant_{mode}_")
        rules = None
        if mode == "qos_on":
            rules = os.path.join(tmp, "tenants.txt")
            with open(rules, "w") as fh:
                fh.write(f"flood   max_rps={TEN_QUOTA_RPS:g} weight=1\n"
                         "victim  weight=4 max_bytes=1TB\n")
        cluster = Cluster(tmp, attribution=False, traces=False,
                          volumes=1, tenant_rules=rules)
        try:
            cluster.wait_ready()
            rng = np.random.default_rng(1)
            client = WeedClient(cluster.master_url)
            urls = _resolve_read_urls(
                cluster, populate(client, min(KEYS, 60), SIZE, rng))
            row: dict = {}
            if mode == "qos_on":
                log(f"  {mode}: victim solo baseline "
                    f"({TEN_VICTIM_RATE:g} rps x {TEN_SECONDS:g}s) ...")
                row["victim_solo"] = _tenant_probe(
                    urls, "victim", TEN_VICTIM_RATE, TEN_SECONDS)
            log(f"  {mode}: flood {flood_rate:g} rps vs victim "
                f"{TEN_VICTIM_RATE:g} rps ...")
            flood_box: dict = {}

            def run_flood() -> None:
                flood_box.update(_tenant_probe(
                    urls, "flood", flood_rate, TEN_SECONDS + 1.5))

            ft = threading.Thread(target=run_flood)
            ft.start()
            time.sleep(0.75)  # flood ramps first: victim measures UNDER it
            row["victim_under_flood"] = _tenant_probe(
                urls, "victim", TEN_VICTIM_RATE, TEN_SECONDS)
            ft.join()
            row["flood"] = flood_box
            snap = rpc.call(
                f"http://{cluster.volume_urls[0]}/debug/tenants")
            row["server_view"] = {
                k: snap[k] for k in ("rates", "admission") if k in snap}
            doc[mode] = row
        finally:
            cluster.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    solo = doc["qos_on"]["victim_solo"]
    under = doc["qos_on"]["victim_under_flood"]
    flood = doc["qos_on"]["flood"]
    ratio = under["p99"] / max(solo["p99"], 1e-9)
    doc["victim_p99_ratio"] = round(ratio, 3)
    # 50ms absolute escape hatch: on a shared 1-core box a 1ms solo
    # baseline makes the 3x ratio a sub-noise gate; a victim tail that
    # stays under 50ms absolute is unharmed by any reading.
    doc["gates"] = {
        "victim_p99_within_3x_solo":
            under["p99"] <= max(3.0 * solo["p99"], 0.05),
        "flood_excess_shed_as_429": flood["shed_429"] > 0,
        "flood_held_near_quota":
            flood["admitted_rps"] <= TEN_QUOTA_RPS * 1.6,
        "victim_zero_errors":
            solo["errors"] == 0 and under["errors"] == 0
            and solo["shed_429"] == 0 and under["shed_429"] == 0,
    }
    doc["qos_ok"] = all(doc["gates"].values())
    return doc


def tenant_round(out_path: str) -> int:
    """Round 4 runner: publish BENCH_tenant_r01.json, gate on qos_ok."""
    t0 = time.time()
    log("tenant phase (round 4: noisy-neighbor QoS fairness) ...")
    phase = tenant_phase()
    doc = {"bench": "tenant", "round": 4, "quick": QUICK,
           **phase, "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(doc, indent=1))
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    log(f"wrote {out_path}")
    return 0 if doc["qos_ok"] else 1


# -- round 5: the geo active/active phase ------------------------------------
#
# Two single-node regions cross-wired active/active (epoch-fenced
# leases, zlib-compressed bidirectional shipping).  The claim under
# test: region A's read tail is WAN-isolated — while region B absorbs
# a local write storm (which region B's shipper streams back to A in
# the background), region A's read p99 stays within GEO_P99_X of its
# solo baseline.  The phase also publishes the compressed-vs-raw ship
# bytes from the storm, from both the shipper's own ack accounting and
# the rlog.ship row of the flow ledger.

GEO_KEYS = int(_env("BENCH_GEO_KEYS", 30 if QUICK else 100))
GEO_SIZE = int(_env("BENCH_GEO_SIZE", 4096 if QUICK else 8192))
GEO_SECONDS = _env("BENCH_GEO_SECONDS", 3.0 if QUICK else 8.0)
GEO_READ_WORKERS = int(_env("BENCH_GEO_READ_WORKERS", 6))
GEO_STORM_WORKERS = int(_env("BENCH_GEO_STORM_WORKERS", 6))
GEO_P99_X = _env("BENCH_GEO_P99_X", 1.5)


class GeoCluster:
    """Two regions ("A", "B"), one master + one volume server each,
    cross-wired exactly as the README runbook spells it: disjoint
    volume-id residue classes, `-replicate.peer` at the OTHER region's
    master, `-geo.cluster.id` + `-replicate.compress` on the volume
    servers, lookup steering on the masters."""

    def __init__(self, tmp: str):
        from seaweedfs_tpu.cluster import rpc
        self.tmp = tmp
        self.procs: list[subprocess.Popen] = []
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONHASHSEED="0", SEAWEEDFS_TPU_TRACES="0")
        pa, pb = rpc.free_port(), rpc.free_port()
        while pb == pa:
            pb = rpc.free_port()
        self.master_a = f"http://127.0.0.1:{pa}"
        self.master_b = f"http://127.0.0.1:{pb}"
        for port, peer, cid, off in ((pa, pb, "A", 1), (pb, pa, "B", 0)):
            self._spawn(["master", f"-port={port}", f"-mdir={tmp}/m{cid}",
                         f"-geo.cluster.id={cid}", "-geo.vid.stride=2",
                         f"-geo.vid.offset={off}",
                         "-replicate.lag.slo=5",
                         "-replicate.steer",
                         f"-replicate.steer.peer=127.0.0.1:{peer}",
                         "-replicate.steer.refresh=1"], env)
        self.volume_a = ""
        self.volume_b = ""
        for cid, mport, peer_port in (("A", pa, pb), ("B", pb, pa)):
            vport = rpc.free_port()
            d = f"{tmp}/vs{cid}"
            os.makedirs(d)
            self._spawn(["volume", f"-port={vport}", f"-dir={d}",
                         "-max=50", f"-mserver=127.0.0.1:{mport}",
                         f"-geo.cluster.id={cid}", "-replicate.compress",
                         f"-replicate.peer=127.0.0.1:{peer_port}",
                         "-replicate.interval=0.2"], env)
            url = f"127.0.0.1:{vport}"
            if cid == "A":
                self.volume_a = url
            else:
                self.volume_b = url

    _spawn = Cluster._spawn
    stop = Cluster.stop

    def wait_ready(self, timeout: float = 60.0) -> None:
        from seaweedfs_tpu.cluster import rpc
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                ok = 0
                for m in (self.master_a, self.master_b):
                    st, doc = rpc.call_status(
                        f"{m}/cluster/healthz", timeout=2.0)
                    if st == 200 and len(doc.get("nodes", [])) == 1:
                        ok += 1
                if ok == 2:
                    return
            except Exception:  # noqa: BLE001 — still starting
                pass
            time.sleep(0.2)
        raise TimeoutError("geo regions never became healthy")


def _geo_read_round(urls: list[str], seconds: float) -> dict:
    """Closed-loop direct-to-volume-server reads (steering is a
    lookup-time feature; the tail being priced here is the region-A
    SERVER plane, which is what a WAN storm must not perturb)."""
    import random as _random

    from seaweedfs_tpu.cluster import rpc
    lat: list[list[float]] = [[] for _ in range(GEO_READ_WORKERS)]
    stop = time.perf_counter() + seconds

    def worker(wi: int) -> None:
        rng = _random.Random(1000 + wi)
        while time.perf_counter() < stop:
            u = rng.choice(urls)
            t0 = time.perf_counter()
            try:
                rpc.call(u, timeout=10.0)
            except Exception:  # noqa: BLE001
                continue
            lat[wi].append(time.perf_counter() - t0)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(GEO_READ_WORKERS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return percentiles([x for row in lat for x in row])


def _geo_lease_home(client, fids: list[str]) -> list[int]:
    """Acquire the write lease at each fid's hosting node — the
    runbook path; acquire also switches the change log on, so every
    storm write journals and ships."""
    import json as _json

    from seaweedfs_tpu.cluster import rpc
    vids = sorted({int(f.split(",")[0]) for f in fids})
    for vid in vids:
        url = client.lookup(vid)[0]["url"]
        rpc.call(f"http://{url}/admin/lease/acquire", "POST",
                 _json.dumps({"volume": vid}).encode())
    return vids


def geo_phase() -> dict:
    import numpy as np

    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient

    tmp = tempfile.mkdtemp(prefix="bench_geo_")
    geo = GeoCluster(tmp)
    try:
        geo.wait_ready()
        log("geo regions ready:", geo.master_a, "<->", geo.master_b)
        rng = np.random.default_rng(7)
        ca = WeedClient(geo.master_a)
        cb = WeedClient(geo.master_b)
        fids_a = populate(ca, GEO_KEYS, GEO_SIZE, rng)
        vids_a = _geo_lease_home(ca, fids_a)
        seed_b = populate(cb, max(4, GEO_KEYS // 4), GEO_SIZE, rng)
        vids_b = _geo_lease_home(cb, seed_b)
        read_urls = [
            f"http://{ca.lookup(int(f.split(',')[0]))[0]['url']}/{f}"
            for f in fids_a]

        log(f"solo baseline: region-A reads {GEO_SECONDS:.0f}s ...")
        solo = _geo_read_round(read_urls, GEO_SECONDS)

        ship0 = rpc.call(f"http://{geo.volume_b}/debug/replication") \
            .get("shipper", {}).get("shipped", {})
        payload = rng.integers(0, 256, GEO_SIZE, dtype="uint8").tobytes()
        halt = threading.Event()
        wrote = [0] * GEO_STORM_WORKERS

        def storm(wi: int) -> None:
            while not halt.is_set():
                try:
                    cb.upload_data(payload)
                    wrote[wi] += 1
                except Exception:  # noqa: BLE001
                    pass

        log(f"storm: region-B writes x{GEO_STORM_WORKERS} while "
            f"region-A reads {GEO_SECONDS:.0f}s ...")
        sthreads = [threading.Thread(target=storm, args=(i,))
                    for i in range(GEO_STORM_WORKERS)]
        for th in sthreads:
            th.start()
        stormy = _geo_read_round(read_urls, GEO_SECONDS)
        halt.set()
        for th in sthreads:
            th.join()
        # Let the WAN tail drain so the ship accounting is the whole
        # storm, then pull both books: the shipper's own ack totals
        # and the flow ledger's rlog.ship row.
        time.sleep(2.0)
        ship1 = rpc.call(f"http://{geo.volume_b}/debug/replication") \
            .get("shipper", {}).get("shipped", {})
        raw_b = int(ship1.get("raw_bytes", 0)) - int(ship0.get("raw_bytes", 0))
        wire_b = int(ship1.get("wire_bytes", 0)) - int(ship0.get("wire_bytes", 0))
        flows_doc = rpc.call(f"http://{geo.volume_b}/debug/flows")
        ledger_out = sum(
            r["bytes"] for r in flows_doc.get("rows", [])
            if r.get("purpose") == "rlog.ship"
            and r.get("direction") == "out")

        ratio = stormy["p99"] / max(solo["p99"], 1e-9)
        doc = {
            "keys": GEO_KEYS, "size": GEO_SIZE,
            "seconds": GEO_SECONDS,
            "read_workers": GEO_READ_WORKERS,
            "storm_workers": GEO_STORM_WORKERS,
            "volumes_a": vids_a, "volumes_b": vids_b,
            "storm_writes": sum(wrote),
            "solo_read": solo,
            "storm_read": stormy,
            "read_p99_ratio": round(ratio, 3),
            "ship": {
                "raw_bytes": raw_b,
                "wire_bytes": wire_b,
                "compression_ratio": round(raw_b / max(wire_b, 1), 3),
                "ledger_rlog_ship_out_bytes": ledger_out,
            },
            "gates": {
                # 50ms absolute escape hatch, the tenant round's
                # reasoning verbatim: on a shared 1-core box the two
                # regions and the storm client all contend for the
                # SAME core, so the ratio prices the box's scheduler,
                # not WAN isolation; a region-A tail that stays under
                # 50ms absolute is unharmed by any reading.
                "read_p99_within_1_5x_solo":
                    stormy["p99"] <= max(GEO_P99_X * solo["p99"], 0.05),
                "storm_shipped_compressed":
                    0 < wire_b < raw_b,
                "ledger_saw_wan_bytes": ledger_out >= wire_b > 0,
            },
        }
        doc["geo_ok"] = all(doc["gates"].values())
        return doc
    finally:
        geo.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def geo_round(out_path: str) -> int:
    """Round 5 runner: publish BENCH_geo_r01.json, gate on geo_ok."""
    t0 = time.time()
    log("geo phase (round 5: active/active WAN isolation) ...")
    phase = geo_phase()
    doc = {"bench": "geo", "round": 5, "quick": QUICK,
           **phase, "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(doc, indent=1))
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    log(f"wrote {out_path}")
    return 0 if doc["geo_ok"] else 1


# -- round 6: the metadata-plane HA phase ------------------------------------
#
# A sharded filer fleet (master with -filer.shards=N + N filer
# processes, each journaling to its own -filer.ha.dir) absorbs a
# closed-loop mkdir/rename storm through the shard-map-aware client.
# The phase prices the sharding itself: a single shard serializes
# every metadata commit behind one primary's journal-fsync +
# semi-sync fan-out critical section, so spreading the N shard
# primaries across the fleet (filer.shards.move, exactly the runbook
# step) must scale committed throughput.  Gates: the N-shard fleet
# beats the 1-shard fleet by >= META_SCALE_X (>= 4 cores; on smaller
# boxes the gate bounds coordination overhead at META_FLOOR_X — see
# the escape-hatch comment at the knobs), the moves actually spread
# the primaries, every shard's journal advanced, and both storms
# commit with zero client-visible errors.

META_SHARDS = int(_env("BENCH_META_SHARDS", 4))
META_FILERS = int(_env("BENCH_META_FILERS", 4))
META_SECONDS = _env("BENCH_META_SECONDS", 3.0 if QUICK else 8.0)
META_WORKERS = int(_env("BENCH_META_WORKERS", 4 if QUICK else 8))
META_SCALE_X = _env("BENCH_META_SCALE_X", 1.2)
# The 1-core escape hatch (the tenant/geo phases' reasoning): N shard
# primaries on one core time-slice a single CPU, so the ratio prices
# the scheduler, not the sharding — there the gate only bounds the
# coordination overhead (sharded must hold >= FLOOR_X of the
# single-shard fleet).  Boxes with >= 4 cores must show real scaling.
META_FLOOR_X = _env("BENCH_META_FLOOR_X", 0.6)
META_PULSE = _env("BENCH_META_PULSE", 1.0)


class MetaFleet:
    """Subprocess master (-filer.shards=N) + META_FILERS filers.  No
    volume servers: mkdir/rename are pure metadata commits, and the
    plane being priced is the shard journal path, not blob IO."""

    def __init__(self, tmp: str, shards: int):
        from seaweedfs_tpu.cluster import rpc
        self.tmp = tmp
        self.shards = shards
        self.procs: list[subprocess.Popen] = []
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONHASHSEED="0", SEAWEEDFS_TPU_TRACES="0")
        mport = rpc.free_port()
        self.master_url = f"http://127.0.0.1:{mport}"
        self._spawn(["master", f"-port={mport}", f"-mdir={tmp}/meta",
                     f"-filer.shards={shards}"], env)
        self.filer_urls: list[str] = []
        for i in range(META_FILERS):
            fport = rpc.free_port()
            self._spawn(["filer", f"-port={fport}",
                         f"-master=127.0.0.1:{mport}",
                         f"-pulseSeconds={META_PULSE}",
                         f"-filer.ha.dir={tmp}/ha{i}"], env)
            self.filer_urls.append(f"http://127.0.0.1:{fport}")

    _spawn = Cluster._spawn
    stop = Cluster.stop

    def shard_map(self) -> dict:
        from seaweedfs_tpu.cluster import rpc
        return rpc.call(self.master_url + "/cluster/filer/shards",
                        timeout=5.0)

    def wait_ready(self, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                doc = self.shard_map()
                rows = doc.get("shards") or {}
                alive = [f for f in doc.get("filers", [])
                         if f.get("alive")]
                if len(alive) == META_FILERS and \
                        len(rows) == self.shards and \
                        all(r.get("primary") for r in rows.values()):
                    return
            except Exception:  # noqa: BLE001 — still starting
                pass
            time.sleep(0.2)
        raise TimeoutError("filer fleet never became healthy")

    def spread_primaries(self, timeout: float = 30.0) -> None:
        """filer.shards.move shard k -> filer k%N: the master hands
        every shard to the first registrant, so an unspread fleet
        measures one process, not N."""
        import json as _json

        from seaweedfs_tpu.cluster import rpc
        targets = {k: self.filer_urls[k % len(self.filer_urls)]
                   for k in range(self.shards)}
        deadline = time.time() + timeout
        while time.time() < deadline:
            doc = self.shard_map()
            rows = {int(k): v for k, v in
                    (doc.get("shards") or {}).items()}
            pending = [k for k, to in targets.items()
                       if rows.get(k, {}).get("primary") != to]
            if not pending:
                return
            for k in pending:
                try:
                    rpc.call(self.master_url +
                             "/cluster/filer/shards/move", "POST",
                             _json.dumps({"shard": k,
                                          "to": targets[k]}).encode(),
                             timeout=10.0)
                except Exception:  # noqa: BLE001 — contested mid-
                    pass           # move; re-checked next lap
            time.sleep(0.3)
        raise TimeoutError("shard primaries never spread")


def _meta_dirs(shards: int) -> list[str]:
    """Top-level dirs covering every shard (2 per shard): the storm
    must offer work to ALL primaries or the scaling gate measures the
    hash, not the plane."""
    from seaweedfs_tpu.filer.metaha import shard_of
    per: dict[int, list[str]] = {k: [] for k in range(max(shards, 1))}
    i = 0
    while any(len(v) < 2 for v in per.values()):
        name = f"bench{i}"
        k = shard_of("/" + name, shards) if shards > 1 else 0
        if len(per[k]) < 2:
            per[k].append(name)
        i += 1
    return [d for row in per.values() for d in row]


def _meta_storm(master_url: str, dirs: list[str],
                seconds: float) -> dict:
    """Closed-loop mkdir/rename storm through ShardedFilerClient —
    every 4th committed dir is renamed (same top-level dir: renames
    never cross shards).  One client per worker: the map cache and
    retry state are per-thread, like real gateway processes."""
    from seaweedfs_tpu.filer.client import ShardedFilerClient
    lat: list[list[float]] = [[] for _ in range(META_WORKERS)]
    errs = [0] * META_WORKERS
    ops = [0] * META_WORKERS
    start = time.perf_counter()
    stop = start + seconds

    def worker(wi: int) -> None:
        client = ShardedFilerClient(master_url, map_ttl=2.0)
        n = 0
        while time.perf_counter() < stop:
            top = dirs[(wi + n) % len(dirs)]
            path = f"/{top}/w{wi}-n{n}"
            t0 = time.perf_counter()
            try:
                client.mkdir(path)
                if n % 4 == 3:
                    client.rename(path, path + "-r")
            except Exception:  # noqa: BLE001 — counted, gated
                errs[wi] += 1
            else:
                ops[wi] += 2 if n % 4 == 3 else 1
                lat[wi].append(time.perf_counter() - t0)
            n += 1

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(META_WORKERS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - start
    total = sum(ops)
    return {"ops": total, "errors": sum(errs),
            "ops_per_s": round(total / max(wall, 1e-9), 1),
            "latency": percentiles([x for row in lat for x in row])}


def meta_phase() -> dict:
    from seaweedfs_tpu.cluster import rpc
    doc: dict = {"shards": META_SHARDS, "filers": META_FILERS,
                 "seconds": META_SECONDS, "workers": META_WORKERS}
    dirs = _meta_dirs(META_SHARDS)
    for label, shards in (("single", 1), ("sharded", META_SHARDS)):
        tmp = tempfile.mkdtemp(prefix=f"bench_meta_{label}_")
        fleet = MetaFleet(tmp, shards)
        try:
            fleet.wait_ready()
            if shards > 1:
                fleet.spread_primaries()
                # Let the post-move reshuffle settle (followers
                # re-tail the moved primaries and rejoin the sync
                # sets) so the storm starts in steady state.
                time.sleep(2 * META_PULSE)
            log(f"{label} fleet ready ({shards} shard(s), "
                f"{META_FILERS} filers); storm "
                f"{META_SECONDS:.0f}s x{META_WORKERS} workers ...")
            # Warm every top dir through the client first: the first
            # touch of a fresh shard map + parent mkdirs is one-time
            # cost, not steady-state metadata throughput.
            from seaweedfs_tpu.filer.client import ShardedFilerClient
            warm = ShardedFilerClient(fleet.master_url)
            for d in dirs:
                warm.mkdir(f"/{d}/warm")
            storm = _meta_storm(fleet.master_url, dirs, META_SECONDS)
            smap = fleet.shard_map()
            rows = {int(k): v for k, v in
                    (smap.get("shards") or {}).items()}
            shard_rows = {}
            for k, row in sorted(rows.items()):
                st = rpc.call(
                    row["primary"] +
                    f"/.meta/shard/status?shard={k}", timeout=5.0)
                shard_rows[k] = {
                    "primary": row["primary"],
                    "epoch": row.get("epoch"),
                    "followers": len(row.get("followers", [])),
                    "last_seq": int(st.get("last_seq", 0))}
            doc[label] = {**storm, "shard_rows": shard_rows,
                          "primaries": sorted(
                              {r["primary"] for r in rows.values()})}
        finally:
            fleet.stop()
            shutil.rmtree(tmp, ignore_errors=True)
    single, sharded = doc["single"], doc["sharded"]
    ratio = sharded["ops_per_s"] / max(single["ops_per_s"], 1e-9)
    cores = os.cpu_count() or 1
    doc["scaling_ratio"] = round(ratio, 3)
    doc["cores"] = cores
    doc["scale_required"] = META_SCALE_X if cores >= 4 else META_FLOOR_X
    doc["gates"] = {
        "sharded_scales_over_single": ratio >= doc["scale_required"],
        "primaries_spread": len(sharded["primaries"]) ==
            min(META_SHARDS, META_FILERS),
        "every_shard_journaled": all(
            r["last_seq"] > 0
            for r in sharded["shard_rows"].values()),
        "zero_errors": single["errors"] == 0 and
            sharded["errors"] == 0,
    }
    doc["meta_ok"] = all(doc["gates"].values())
    return doc


def meta_round(out_path: str) -> int:
    """Round 6 runner: publish BENCH_meta_r01.json, gate on meta_ok."""
    t0 = time.time()
    log("meta phase (round 6: sharded filer metadata HA) ...")
    phase = meta_phase()
    doc = {"bench": "meta", "round": 6, "quick": QUICK,
           **phase, "elapsed_s": round(time.time() - t0, 1)}
    print(json.dumps(doc, indent=1))
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    log(f"wrote {out_path}")
    return 0 if doc["meta_ok"] else 1


def main() -> int:
    out_path = "BENCH_load_r03.json"
    args = sys.argv[1:]
    tenant_only = "--tenant" in args
    geo_only = "--geo" in args
    meta_only = "--meta" in args
    if tenant_only:
        out_path = "BENCH_tenant_r01.json"
    if geo_only:
        out_path = "BENCH_geo_r01.json"
    if meta_only:
        out_path = "BENCH_meta_r01.json"
    if "-o" in args:
        out_path = args[args.index("-o") + 1]

    from seaweedfs_tpu.utils.jaxenv import force_cpu
    force_cpu(device_count=1)
    # The client-side measurement needs the same 1ms GIL switch
    # interval the servers set: with the 5ms default, worker threads
    # convoy and the measured CLIENT tail is the interpreter's, not
    # the cluster's.
    sys.setswitchinterval(0.001)

    if tenant_only:
        return tenant_round(out_path)
    if geo_only:
        return geo_round(out_path)
    if meta_only:
        return meta_round(out_path)

    tmp = tempfile.mkdtemp(prefix="bench_load_")
    cluster = Cluster(tmp, attribution=True)
    t_start = time.time()
    try:
        cluster.wait_ready()
        log("cluster ready:", cluster.master_url, cluster.volume_urls)
        res = run_load(cluster)
        server_q = server_read_quantiles(cluster)
        agree = agreement(res["recent_read"], server_q)
        log("saturation round (attribution plane ON) ...")
        sat_on = saturation_rps(cluster, res["fids"])
        log("merging cluster profile across the 3 nodes ...")
        profile = cluster_profile_merge(cluster)
        checks = fault_phase(cluster, res["client"], res["fids"])
        budget = phase_budget(cluster)
    finally:
        cluster.stop()
    # Overhead comparison on ONE cluster instance: the plane is
    # armed/disarmed at RUNTIME via POST /debug/attribution between
    # rounds, in ABBA blocks (armed, disarmed, disarmed, armed).  Two
    # separate clusters — even identically configured — differ by
    # several % from instance luck alone (allocator layout, ASLR),
    # which would drown a 2-3% effect; toggling one instance removes
    # that term entirely, and the ABBA order cancels linear machine
    # drift inside each block.
    try:
        from seaweedfs_tpu.cluster import rpc as _rpc
        log("overhead phase: fresh plane-armed cluster "
            "(runtime-toggled A/B) ...")
        tmp_ov = tempfile.mkdtemp(prefix="bench_load_ov_")
        # traces=False: the overhead cluster runs at PRODUCTION trace
        # defaults — the 100%-sampled tracing the fault phase needs
        # would record a span per request in BOTH A and B rounds and
        # is not part of the plane being priced.
        c_ov = Cluster(tmp_ov, attribution=True, traces=False)
        try:
            c_ov.wait_ready()
            import numpy as np

            from seaweedfs_tpu.cluster.client import WeedClient
            rng = np.random.default_rng(1)
            urls_ov = _resolve_read_urls(c_ov, populate(
                WeedClient(c_ov.master_url), min(KEYS, 100), SIZE,
                rng))

            def set_plane(on: bool) -> None:
                flag = "1" if on else "0"
                for node in [c_ov.master_url] + \
                        [f"http://{u}" for u in c_ov.volume_urls]:
                    _rpc.call(f"{node}/debug/attribution"
                              f"?enabled={flag}", "POST")

            def set_plane_settled(on: bool) -> None:
                # Short untimed burst after each flip: the first round
                # in a new plane state runs measurably hot (profiler
                # thread restart, branch-predictor/cache transients) —
                # timed rounds must start in steady state.
                set_plane(on)
                _sat_round(urls_ov, 0.5)

            def measured_round() -> tuple[float, float]:
                """(achieved rps, server cpu-µs per request)."""
                cpu0 = _server_cpu_seconds(c_ov)
                rps, n = _sat_round(urls_ov, SAT_SECONDS)
                cpu1 = _server_cpu_seconds(c_ov)
                return rps, (cpu1 - cpu0) / max(n, 1) * 1e6

            log(f"warming {SAT_WARMUP:g}s ...")
            _sat_round(urls_ov, SAT_WARMUP)
            rounds_on, rounds_off = [], []
            cpu_on, cpu_off, ratios, cpu_ratios = [], [], [], []
            for i in range(SAT_BLOCKS):
                set_plane_settled(True)
                a1, ca1 = measured_round()
                set_plane_settled(False)
                b1, cb1 = measured_round()
                b2, cb2 = measured_round()
                set_plane_settled(True)
                a2, ca2 = measured_round()
                rounds_on += [a1, a2]
                rounds_off += [b1, b2]
                cpu_on += [ca1, ca2]
                cpu_off += [cb1, cb2]
                ratios.append((a1 + a2) / (b1 + b2))
                cpu_ratios.append((ca1 + ca2) / (cb1 + cb2))
                log(f"  block {i} (ABBA): on {a1:.0f}/{a2:.0f} rps "
                    f"{ca1:.0f}/{ca2:.0f} us/req, "
                    f"off {b1:.0f}/{b2:.0f} rps "
                    f"{cb1:.0f}/{cb2:.0f} us/req "
                    f"(cpu ratio {cpu_ratios[-1]:.3f})")
        finally:
            c_ov.stop()
            shutil.rmtree(tmp_ov, ignore_errors=True)

        def _sat_doc(rounds: list[float], cpus: list[float]) -> dict:
            ordered = sorted(rounds)
            cpu_ordered = sorted(cpus)
            return {"rounds_rps": [round(r, 1) for r in rounds],
                    "median_rps": round(
                        ordered[len(ordered) // 2], 1),
                    "cpu_us_per_request": [round(c, 1) for c in cpus],
                    "median_cpu_us_per_request": round(
                        cpu_ordered[len(cpu_ordered) // 2], 1),
                    "workers": SAT_WORKERS, "seconds": SAT_SECONDS,
                    "warmup": SAT_WARMUP}

        sat_on_fresh = _sat_doc(rounds_on, cpu_on)
        sat_off = _sat_doc(rounds_off, cpu_off)
        # The GATING measurand is the criterion's: end-to-end
        # throughput (median ABBA block ratio).  Server CPU per
        # request rides along as the sharper diagnostic — it isolates
        # the server-side plane cost from the client/framing share of
        # the core, so the two numbers bracket the truth: wall-clock
        # is what users see, cpu/req is what the refactor arc should
        # watch.
        ratios.sort()
        cpu_ratios.sort()
        overhead = 1.0 - ratios[len(ratios) // 2]
        overhead_cpu = cpu_ratios[len(cpu_ratios) // 2] - 1.0
        overhead_doc = {
            "on": sat_on_fresh, "off": sat_off,
            "loaded_cluster_on": sat_on,
            "pair_ratios": [round(r, 4) for r in ratios],
            "cpu_pair_ratios": [round(r, 4) for r in cpu_ratios],
            "overhead_fraction": round(overhead, 4),
            "overhead_fraction_server_cpu": round(overhead_cpu, 4),
            "measurand": "closed-loop throughput, median ABBA block "
                         "ratio (runtime-toggled plane, one cluster "
                         "instance); server cpu-us/request is the "
                         "noise-resistant diagnostic",
            "within_3pct": overhead < 0.03,
        }
        # round 3: the connection-scaling phase (fresh single-volume
        # clusters, one per transport) runs after the main cluster is
        # gone so its /proc numbers aren't polluted by neighbors.
        log("connection-scaling phase (threads vs aio) ...")
        conn_doc = connection_scaling()
        # p99 regression gate against the r02 record at the same rate,
        # when the r02 file is around to compare with (25% headroom:
        # single-core bench boxes jitter more than the effect floor).
        try:
            with open(os.path.join(REPO, "BENCH_load_r02.json")) as f:
                r02_p99 = json.load(f)["client"]["read"]["p99"]
            conn_doc["r02_read_p99_s"] = r02_p99
            conn_doc["p99_vs_r02_ok"] = \
                conn_doc["aio"]["probe_p99_s"] <= r02_p99 * 1.25
        except (OSError, KeyError):
            conn_doc["p99_vs_r02_ok"] = None

        doc = {
            "bench": "load", "round": 3, "quick": QUICK,
            "config": {"rate": RATE, "duration": DURATION,
                       "warmup": WARMUP, "keys": KEYS, "size": SIZE,
                       "workers": WORKERS, "zipf_s": ZIPF_S,
                       "write_fraction": WRITE_FRACTION,
                       "slo_read_p99": SLO_READ_P99,
                       "slo_availability": 0.999,
                       "short_window": SHORT_WINDOW,
                       "long_window": LONG_WINDOW,
                       "sketch_alpha": ALPHA,
                       "sat_seconds": SAT_SECONDS,
                       "sat_workers": SAT_WORKERS,
                       "sat_rounds": SAT_ROUNDS,
                       "conns_threads": CONN_BASE,
                       "conns_aio": CONN_BASE * CONN_MULT,
                       "probe_seconds": PROBE_SECONDS,
                       "probe_workers": PROBE_WORKERS},
            "achieved_rps": round(res["achieved_rps"], 2),
            "target_rps": RATE,
            "totals": res["totals"],
            "client": {"read": res["read"], "write": res["write"],
                       "recent_read": res["recent_read"]},
            "client_sketch_vs_exact": res["sketch_vs_exact"],
            "server": {"read": server_q},
            "agreement": {"read": agree},
            "fault_checks": checks,
            "phase_budget": budget,
            "cluster_profile": profile,
            "attribution_overhead": overhead_doc,
            "connection_scaling": conn_doc,
            "elapsed_s": round(time.time() - t_start, 1),
        }
        print(json.dumps(doc, indent=1))
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        log(f"wrote {out_path}")
        ok = (res["sketch_vs_exact"]["within_alpha"]
              and agree["within_bound"]
              and all(checks.get(k) for k in
                      ("exemplar_recorded", "trace_resolved",
                       "healthz_degraded", "slo_burn_emitted"))
              and budget["budget_ok"]
              and profile["merged_ok"]
              and conn_doc["scaling_ok"]
              and conn_doc["p99_vs_r02_ok"] is not False)
        # The attribution-overhead re-measure is PUBLISHED but no
        # longer gates: r02 established the plane's price (2% wall,
        # +5.7us CPU/req) under a calm box, and the shared 1-core CI
        # box's 10-15% throughput noise now exceeds the 3% effect
        # floor — a ratio gate below the noise floor flaps on weather,
        # not regressions.  Round 3's gating measurands are the
        # connection-scaling claims; drift in the overhead ratios
        # stays visible in the JSON series.
        # round 4: the tenancy / QoS noisy-neighbor phase publishes
        # its own JSON (BENCH_tenant_r01.json) and gates alongside.
        ten_rc = tenant_round(
            os.path.join(REPO, "BENCH_tenant_r01.json"))
        # round 5: the geo active/active phase publishes its own JSON
        # (BENCH_geo_r01.json) and gates alongside.
        geo_rc = geo_round(
            os.path.join(REPO, "BENCH_geo_r01.json"))
        meta_rc = meta_round(
            os.path.join(REPO, "BENCH_meta_r01.json"))
        return 0 if (ok and ten_rc == 0 and geo_rc == 0
                     and meta_rc == 0) else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
