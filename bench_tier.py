#!/usr/bin/env python3
"""Cold-read latency across the tier boundary — the lifecycle plane's
BENCH row.

One live in-process master + volume server; one volume of small
needles read over real HTTP in three phases:

1. `local`     — the volume's .dat on local disk (the baseline).
2. `uncached`  — the volume tiered to a remote backend, block cache
   emptied, a WAN-scale delay armed on the backend-fetch fault point
   (`tier.read`, the block-cache fetch leg): every miss pays the
   simulated round trip.
3. `cached`    — the same reads again: block-cache hits, no backend
   fetch, no delay.

The gap between 2 and 3 is what the read-through cache buys; the gap
between 3 and 1 is the residual cost of being tiered at all.

Knobs: BENCH_TIER_N (needles, default 64), BENCH_TIER_SIZE (payload
bytes, default 65536), BENCH_TIER_WAN_MS (injected per-fetch delay,
default 20).  Diagnostics on stderr; stdout carries one JSON line per
phase; the full document lands in BENCH_tier_r01.json.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def _quantiles(samples_s: list[float]) -> dict:
    xs = sorted(samples_s)

    def q(p: float) -> float:
        return round(xs[min(len(xs) - 1, int(p * len(xs)))] * 1000, 3)

    return {"p50_ms": q(0.50), "p90_ms": q(0.90), "p99_ms": q(0.99),
            "mean_ms": round(sum(xs) / len(xs) * 1000, 3)}


def _read_all(fids: list[str], url: str, payload_len: int) -> dict:
    from seaweedfs_tpu.cluster import rpc

    samples = []
    for fid in fids:
        t0 = time.perf_counter()
        body = rpc.call(f"http://{url}/{fid}", timeout=30.0)
        samples.append(time.perf_counter() - t0)
        assert len(body) == payload_len, (fid, len(body))
    return _quantiles(samples)


def bench_tier(out_path: str = "BENCH_tier_r01.json") -> dict:
    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.fault import registry as fault
    from seaweedfs_tpu.storage.remote_cache import CACHE

    n = int(os.environ.get("BENCH_TIER_N", "64"))
    size = int(os.environ.get("BENCH_TIER_SIZE", str(64 * 1024)))
    wan_ms = float(os.environ.get("BENCH_TIER_WAN_MS", "20"))
    payload = os.urandom(size)

    tmp = tempfile.mkdtemp(prefix="bench_tier_")
    master = None
    vs = None
    try:
        master = MasterServer(volume_size_limit_mb=256, meta_dir=tmp,
                              pulse_seconds=60)
        master.start()
        d = os.path.join(tmp, "vs0")
        os.makedirs(d)
        vs = VolumeServer(master.url(), [d], pulse_seconds=60)
        vs.start()

        rpc.call(f"{master.url()}/vol/grow?count=1&collection=bench",
                 "POST")
        fids, vurl, vid = [], "", 0
        for _ in range(n):
            a = rpc.call(f"{master.url()}/dir/assign?collection=bench")
            rpc.call(f"http://{a['url']}/{a['fid']}", "POST", payload)
            fids.append(a["fid"])
            vurl = a["url"]
            vid = int(a["fid"].split(",")[0])
        log(f"wrote {n} x {size >> 10}KB needles into volume {vid}")

        local = _read_all(fids, vurl, size)
        log(f"local: {local}")

        dest = f"local://{tmp}/remote"
        rpc.call_json(f"http://{vurl}/admin/readonly",
                      payload={"volume": vid})
        rpc.call_json(f"http://{vurl}/admin/tier_upload",
                      payload={"volume": vid, "dest": dest},
                      timeout=120.0)
        log(f"tiered volume {vid} -> {dest}")

        fault.arm("tier.read", f"delay:{wan_ms / 1000.0}")
        CACHE.reset()
        uncached = _read_all(fids, vurl, size)
        log(f"uncached (+{wan_ms}ms/fetch): {uncached}")
        miss_cold = CACHE.stats()["miss_bytes"]

        cached = _read_all(fids, vurl, size)
        log(f"cached: {cached}")
        st = CACHE.stats()
        fault.disarm("tier.read")
        assert st["miss_bytes"] == miss_cold, \
            "second pass fetched from the backend"

        doc = {
            "bench": "tier_cold_read", "round": 1,
            "config": {"needles": n, "payload_bytes": size,
                       "wan_delay_ms": wan_ms,
                       "cache_max_bytes": st["max_bytes"]},
            "local": local,
            "uncached": uncached,
            "cached": cached,
            "cache": {"hit_bytes": st["hit_bytes"],
                      "miss_bytes": st["miss_bytes"],
                      "blocks": st["blocks"]},
            "note": ("cold reads over live HTTP: local .dat vs tiered "
                     "with an empty block cache (every 1MiB-block miss "
                     "pays the armed tier.read delay, modeling a WAN "
                     "round trip) vs tiered with a warm cache. cached "
                     "p50 ~= local p50 is the read-through cache "
                     "working; uncached-cached gap is the WAN cost it "
                     "absorbs."),
        }
        with open(out_path, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        for phase in ("local", "uncached", "cached"):
            print(json.dumps({"metric": f"tier cold read, {phase}",
                              **doc[phase]}), flush=True)
        log(f"wrote {out_path}")
        return doc
    finally:
        fault.disarm("tier.read")
        if vs is not None:
            vs.stop()
        if master is not None:
            master.stop()
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    bench_tier()
