#!/usr/bin/env python3
"""Repair-traffic benchmark: shard-bytes-read and wall time per
single-shard rebuild, `rs` vs `lrc` — prints ONE JSON line to stdout.

Metric: the new BENCH series beside kernel MB/s.  At production scale
rebuild bandwidth, not encode throughput, is the dominant EC cost
(arxiv 1309.0186), and this measures exactly that: a volume is encoded
with each codec, one data shard is deleted, and `rebuild_ec_files`
regenerates it while SeaweedFS_ec_repair_read_bytes_total counts every
survivor byte read.  RS(10,4) reads 10 shards; LRC(10,2,2) reads the
lost shard's 5-member locality group — the read_savings field is the
measured ratio.

Environment knobs: BENCH_REPAIR_MB (volume size, default 256),
SEAWEEDFS_TPU_CODER (backend; default auto — pallas on TPU).

All diagnostics go to stderr; stdout carries exactly one JSON line.
Run on a real chip: python bench_repair_traffic.py [-o BENCH_repair_rNN.json]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

VOLUME_MB = int(os.environ.get("BENCH_REPAIR_MB", "256"))
LOST_SHARD = 3  # a data shard inside LRC local group A


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def bench_codec(name: str, tmp: str, payload: np.ndarray) -> dict:
    from seaweedfs_tpu.codecs import get_codec
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.ec.encoder import rebuild_ec_files, write_ec_files
    from seaweedfs_tpu.ops.erasure import new_coder
    from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total

    codec = get_codec(name)
    base = os.path.join(tmp, f"vol_{name}")
    with open(base + ".dat", "wb") as f:
        f.write(payload.tobytes())

    coder = new_coder(codec=name)
    t0 = time.perf_counter()
    write_ec_files(base, coder=coder)
    encode_s = time.perf_counter() - t0

    shard_path = base + to_ext(LOST_SHARD)
    shard_bytes = os.path.getsize(shard_path)
    os.remove(shard_path)

    plan = codec.repair_plan(
        tuple(s for s in range(codec.total_shards) if s != LOST_SHARD),
        [LOST_SHARD])[0]
    before = ec_repair_read_bytes_total.value(codec=name)
    t0 = time.perf_counter()
    rebuilt = rebuild_ec_files(base, coder=coder)
    rebuild_s = time.perf_counter() - t0
    read_bytes = ec_repair_read_bytes_total.value(codec=name) - before
    assert rebuilt == [LOST_SHARD]
    assert read_bytes == len(plan.reads) * shard_bytes, \
        "metric disagrees with the planner — harness bug"

    out = {
        "codec": name,
        "volume_mb": VOLUME_MB,
        "shard_bytes": shard_bytes,
        "planned_reads": len(plan.reads),
        "local_repair": plan.local,
        "repair_read_bytes": int(read_bytes),
        "rebuild_seconds": round(rebuild_s, 4),
        "rebuild_mbps": round(shard_bytes / rebuild_s / 1e6, 1),
        "encode_seconds": round(encode_s, 4),
    }
    log(f"{name}: rebuilt shard {LOST_SHARD} reading "
        f"{len(plan.reads)} shards ({read_bytes / 1e6:.1f} MB) "
        f"in {rebuild_s:.3f}s")
    return out


def main() -> int:
    out_path = None
    args = sys.argv[1:]
    if "-o" in args:
        out_path = args[args.index("-o") + 1]
    try:
        import jax
        log(f"device: {jax.devices()[0]}")
    except Exception as e:  # noqa: BLE001 — CPU-only runs are fine
        log(f"jax device probe failed ({e}); CPU coder path")
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, VOLUME_MB * 1024 * 1024,
                           dtype=np.uint8)
    results = {"metric": "repair_traffic", "volume_mb": VOLUME_MB}
    with tempfile.TemporaryDirectory(prefix="bench_repair_") as tmp:
        for name in ("rs", "lrc"):
            results[name] = bench_codec(name, tmp, payload)
    results["read_savings"] = round(
        1.0 - results["lrc"]["repair_read_bytes"]
        / results["rs"]["repair_read_bytes"], 4)
    line = json.dumps(results)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
        log(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
