#!/usr/bin/env python3
"""Repair-traffic benchmark: shard-bytes-read and wall time per
single-shard rebuild, `rs` vs `lrc` — prints ONE JSON line to stdout.

Metric: the new BENCH series beside kernel MB/s.  At production scale
rebuild bandwidth, not encode throughput, is the dominant EC cost
(arxiv 1309.0186), and this measures exactly that: a volume is encoded
with each codec, one data shard is deleted, and `rebuild_ec_files`
regenerates it while SeaweedFS_ec_repair_read_bytes_total counts every
survivor byte read.  RS(10,4) reads 10 shards; LRC(10,2,2) reads the
lost shard's 5-member locality group — the read_savings field is the
measured ratio.

Round 2 adds the ON-WIRE leg: the same lost-shard rebuild driven
through a live in-process cluster (master + 3 volume servers, shards
spread 5/5/4, `ec.rebuild -batch`), with ACTUAL network bytes read
from the wire-flow ledger's ec.gather/ec.scatter purposes
(stats/flows.py) beside the planner's PREDICTED reads — the
measurement gate ROADMAP item 1 (regenerating codes) needs: a codec
whose predicted savings don't survive contact with the wire (sidecar
overhead, retry amplification) is not a savings.

Round 3 adds end-to-end **MTTR**: a holder of 001-replicated data, an
rs(10,4) stripe, and an lrc(10,2,2) stripe is killed for good and the
durability autopilot (cluster/repair_daemon.py) drives the deficit to
convergence — wall time kill -> restored redundancy per scheme, with
bytes-on-wire (repair.fetch / ec.gather in the flow ledger)
cross-asserted against the actual file sizes moved.

Environment knobs: BENCH_REPAIR_MB (local volume size, default 256),
BENCH_REPAIR_WIRE_MB (wire-leg volume size, default 16),
BENCH_REPAIR_MTTR_MB (MTTR-leg volume size, default 8),
SEAWEEDFS_TPU_CODER (backend; default auto — pallas on TPU).

All diagnostics go to stderr; stdout carries exactly one JSON line.
Run on a real chip: python bench_repair_traffic.py [-o BENCH_repair_rNN.json]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

VOLUME_MB = int(os.environ.get("BENCH_REPAIR_MB", "256"))
WIRE_MB = int(os.environ.get("BENCH_REPAIR_WIRE_MB", "16"))
MTTR_MB = int(os.environ.get("BENCH_REPAIR_MTTR_MB", "8"))
LOST_SHARD = 3  # a data shard inside LRC local group A


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def bench_codec(name: str, tmp: str, payload: np.ndarray) -> dict:
    from seaweedfs_tpu.codecs import get_codec
    from seaweedfs_tpu.ec import to_ext
    from seaweedfs_tpu.ec.encoder import rebuild_ec_files, write_ec_files
    from seaweedfs_tpu.ops.erasure import new_coder
    from seaweedfs_tpu.stats.metrics import ec_repair_read_bytes_total

    codec = get_codec(name)
    base = os.path.join(tmp, f"vol_{name}")
    with open(base + ".dat", "wb") as f:
        f.write(payload.tobytes())

    coder = new_coder(codec=name)
    t0 = time.perf_counter()
    write_ec_files(base, coder=coder)
    encode_s = time.perf_counter() - t0

    shard_path = base + to_ext(LOST_SHARD)
    shard_bytes = os.path.getsize(shard_path)
    os.remove(shard_path)

    plan = codec.repair_plan(
        tuple(s for s in range(codec.total_shards) if s != LOST_SHARD),
        [LOST_SHARD])[0]
    before = ec_repair_read_bytes_total.value(codec=name)
    t0 = time.perf_counter()
    rebuilt = rebuild_ec_files(base, coder=coder)
    rebuild_s = time.perf_counter() - t0
    read_bytes = ec_repair_read_bytes_total.value(codec=name) - before
    assert rebuilt == [LOST_SHARD]
    assert read_bytes == len(plan.reads) * shard_bytes, \
        "metric disagrees with the planner — harness bug"

    out = {
        "codec": name,
        "volume_mb": VOLUME_MB,
        "shard_bytes": shard_bytes,
        "planned_reads": len(plan.reads),
        "local_repair": plan.local,
        "repair_read_bytes": int(read_bytes),
        "rebuild_seconds": round(rebuild_s, 4),
        "rebuild_mbps": round(shard_bytes / rebuild_s / 1e6, 1),
        "encode_seconds": round(encode_s, 4),
    }
    log(f"{name}: rebuilt shard {LOST_SHARD} reading "
        f"{len(plan.reads)} shards ({read_bytes / 1e6:.1f} MB) "
        f"in {rebuild_s:.3f}s")
    return out


def bench_codec_wire(name: str) -> dict:
    """Planner-predicted vs actual on-wire bytes for one lost-shard
    rebuild through a live cluster, measured by the flow ledger."""
    import tempfile as _tf

    import numpy as _np

    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.codecs import get_codec
    from seaweedfs_tpu.shell import CommandEnv, run_command
    from seaweedfs_tpu.stats import flows

    codec = get_codec(name)
    tmp = _tf.mkdtemp(prefix=f"bench_wire_{name}_")
    master = MasterServer(volume_size_limit_mb=max(WIRE_MB * 4, 64),
                          meta_dir=os.path.join(tmp, "meta"),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = os.path.join(tmp, f"vs{i}")
        os.makedirs(d)
        vs = VolumeServer(master.url(), [d], pulse_seconds=60)
        vs.start()
        servers.append(vs)
    env = None
    try:
        client = WeedClient(master.url())
        col = f"wire{name}"
        rpc.call(f"{master.url()}/vol/grow?count=1&collection={col}",
                 "POST")
        rng = _np.random.default_rng(1)
        blob = rng.integers(0, 256, 1 << 20, dtype=_np.uint8).tobytes()
        fid0 = client.upload_data(blob, collection=col)
        vid = int(fid0.split(",")[0])
        for _ in range(WIRE_MB - 1):
            client.upload_data(blob, collection=col)
        src = client.lookup(vid)[0]["url"]
        rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                      {"volume": vid, "codec": name})
        spread = [(servers[0], [0, 1, 2, 3, 4]),
                  (servers[1], [5, 6, 7, 8, 9]),
                  (servers[2], list(range(10, codec.total_shards)))]
        for vs, shards in spread:
            if vs.url() != src:
                rpc.call_json(
                    f"http://{vs.url()}/admin/ec/copy_shard", "POST",
                    {"volume": vid, "source": src, "shards": shards,
                     "copy_ecx": True})
        for vs, shards in spread:
            rpc.call_json(f"http://{vs.url()}/admin/ec/mount", "POST",
                          {"volume": vid})
            drop = [s for s in range(codec.total_shards)
                    if s not in shards]
            rpc.call_json(
                f"http://{vs.url()}/admin/ec/delete_shards", "POST",
                {"volume": vid, "shards": drop})
        rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                      {"volume": vid})
        for vs in servers:
            vs._send_heartbeat(full=True)

        env = CommandEnv(master.url())
        locs = env.ec_shard_locations(vid)
        survivor = next(s for s in locs if s != LOST_SHARD)
        shard_bytes = len(bytes(rpc.call(
            f"http://{locs[survivor][0]}/admin/ec/shard_file"
            f"?volume={vid}&shard={survivor}")))
        rpc.call_json(
            f"http://{locs[LOST_SHARD][0]}/admin/ec/delete_shards",
            "POST", {"volume": vid, "shards": [LOST_SHARD]})
        for vs in servers:
            vs._send_heartbeat(full=True)
            vs._ec_loc_cache.clear()

        plan = codec.repair_plan(
            tuple(s for s in range(codec.total_shards)
                  if s != LOST_SHARD), [LOST_SHARD])[0]
        predicted = len(plan.reads) * shard_bytes

        flows.LEDGER.reset()
        run_command(env, "lock")
        t0 = time.perf_counter()
        out = run_command(env, "ec.rebuild -batch")
        wall = time.perf_counter() - t0
        assert f"volume {vid}: rebuilt shards" in out, out
        time.sleep(0.3)  # settle: notes land after the last syscall
        gather, _ops = flows.LEDGER.totals(purpose_="ec.gather",
                                           direction="in")
        scatter, _ = flows.LEDGER.totals(purpose_="ec.scatter",
                                         direction="out")
        log(f"{name} wire: predicted {predicted / 1e6:.1f} MB, "
            f"gathered {gather / 1e6:.1f} MB on the wire "
            f"(+{(gather - predicted) / 1e3:.0f} KB overhead), "
            f"scattered {scatter / 1e6:.1f} MB in {wall:.2f}s")
        return {
            "codec": name,
            "volume_mb": WIRE_MB,
            "shard_bytes": shard_bytes,
            "planned_reads": len(plan.reads),
            "predicted_read_bytes": int(predicted),
            "wire_gather_bytes": int(gather),
            "wire_scatter_bytes": int(scatter),
            "gather_overhead_bytes": int(gather - predicted),
            "rebuild_seconds": round(wall, 4),
        }
    finally:
        if env is not None:
            env.close()
        for vs in servers:
            vs.stop()
        master.stop()


def bench_repair_mttr(mode: str) -> dict:
    """Round 3: mean-time-to-repair, kill -> converged, through the
    durability autopilot.  One volume of BENCH_REPAIR_MTTR_MB data is
    made durable three ways — 001 replication, rs(10,4), lrc(10,2,2)
    — then a holder is killed for good and the repair daemon drives
    the deficit to convergence.  MTTR is wall time from the kill to
    restored redundancy; bytes-on-wire come from the flow ledger
    (repair.fetch for re-replication, ec.gather for rebuilds) and are
    cross-asserted against the actual file sizes so the ledger can
    never silently under-count repair traffic."""
    import shutil
    import tempfile as _tf

    from seaweedfs_tpu.cluster import rpc
    from seaweedfs_tpu.cluster.client import WeedClient
    from seaweedfs_tpu.cluster.master import MasterServer
    from seaweedfs_tpu.cluster.volume_server import VolumeServer
    from seaweedfs_tpu.codecs import get_codec
    from seaweedfs_tpu.stats import flows

    tmp = _tf.mkdtemp(prefix=f"bench_mttr_{mode}_")
    master = MasterServer(volume_size_limit_mb=max(MTTR_MB * 4, 64),
                          meta_dir=os.path.join(tmp, "meta"),
                          pulse_seconds=60)
    master.start()
    servers = []
    for i in range(3):
        d = os.path.join(tmp, f"vs{i}")
        os.makedirs(d)
        vs = VolumeServer(master.url(), [d], pulse_seconds=60)
        vs.start()
        servers.append(vs)

    def kill(vs):
        t0 = time.perf_counter()
        vs.stop()
        dn = next(n for n in master.topo.leaves()
                  if n.url() == vs.url())
        dn.last_seen = 0.0
        master._sweep_dead_nodes()
        return t0

    try:
        client = WeedClient(master.url())
        col = f"mttr{mode}"
        blob = os.urandom(1 << 20)
        master.repair.enabled = True
        master.repair.delay = 0.0

        if mode == "replicated":
            rpc.call(f"{master.url()}/vol/grow?count=1"
                     f"&collection={col}&replication=001", "POST")
            fid = client.upload_data(blob, collection=col,
                                     replication="001")
            vid = int(fid.split(",")[0])
            for _ in range(MTTR_MB - 1):
                client.upload_data(blob, collection=col,
                                   replication="001")
            holders = {dn.url() for dn in master.topo.lookup(col, vid)}
            dead = next(vs for vs in servers if vs.url() in holders)
            survivor = next(vs for vs in servers
                            if vs.url() in holders and vs is not dead)
            v = survivor.store.find_volume(vid)
            v.sync()
            expect = (v.dat_size()
                      + os.path.getsize(v.file_name() + ".idx"))
            flows.LEDGER.reset()
            t_kill = kill(dead)
            out = master.repair.run_now(kinds=["replicate"])
            mttr = time.perf_counter() - t_kill
            assert any(r["outcome"] == "ok" for r in out["results"])
            assert len(master.topo.lookup(col, vid)) == 2
            time.sleep(0.3)
            wire, _ops = flows.LEDGER.totals(purpose_="repair.fetch",
                                             direction="in")
            purpose = "repair.fetch"
        else:
            codec = get_codec(mode)
            rpc.call(f"{master.url()}/vol/grow?count=1"
                     f"&collection={col}", "POST")
            fid = client.upload_data(blob, collection=col)
            vid = int(fid.split(",")[0])
            for _ in range(MTTR_MB - 1):
                client.upload_data(blob, collection=col)
            src = client.lookup(vid)[0]["url"]
            rpc.call_json(f"http://{src}/admin/ec/generate", "POST",
                          {"volume": vid, "codec": mode})
            spread = [(servers[0], [0, 1, 2, 3, 4]),
                      (servers[1], [5, 6, 7, 8, 9]),
                      (servers[2], list(range(10, codec.total_shards)))]
            for vs, shards in spread:
                if vs.url() != src:
                    rpc.call_json(
                        f"http://{vs.url()}/admin/ec/copy_shard",
                        "POST", {"volume": vid, "source": src,
                                 "shards": shards, "copy_ecx": True})
            for vs, shards in spread:
                rpc.call_json(f"http://{vs.url()}/admin/ec/mount",
                              "POST", {"volume": vid})
                drop = [s for s in range(codec.total_shards)
                        if s not in shards]
                rpc.call_json(
                    f"http://{vs.url()}/admin/ec/delete_shards",
                    "POST", {"volume": vid, "shards": drop})
            rpc.call_json(f"http://{src}/admin/delete_volume", "POST",
                          {"volume": vid})
            for vs in servers:
                vs._send_heartbeat(full=True)
                vs._ec_loc_cache.clear()
            locs = master.topo.lookup_ec_shards(vid).locations
            shard_bytes = len(bytes(rpc.call(
                f"http://{locs[0][0].url()}/admin/ec/shard_file"
                f"?volume={vid}&shard=0")))
            missing = list(range(10, codec.total_shards))
            plans = codec.repair_plan(tuple(range(10)), missing)
            expect = (len({r for p in plans for r in p.reads})
                      * shard_bytes)
            flows.LEDGER.reset()
            t_kill = kill(servers[2])  # shards 10.. gone for good
            out = master.repair.run_now(kinds=["ec"])
            mttr = time.perf_counter() - t_kill
            assert any(r["outcome"] == "ok" for r in out["results"]), \
                out
            present = {s for s, dns in master.topo.lookup_ec_shards(
                vid).locations.items() if dns}
            assert present == set(range(codec.total_shards))
            time.sleep(0.3)
            wire, _ops = flows.LEDGER.totals(purpose_="ec.gather",
                                             direction="in")
            purpose = "ec.gather"

        # The cross-assert: the ledger's repair bytes bound the actual
        # payload below (it must have moved at least the files) and
        # within 25% + 1 MB above (framing/sidecar overhead only).
        assert expect <= wire <= expect * 1.25 + (1 << 20), \
            f"{mode}: ledger says {wire}, files say {expect}"
        log(f"{mode}: MTTR {mttr:.2f}s, {wire / 1e6:.1f} MB on the "
            f"wire via {purpose} (files: {expect / 1e6:.1f} MB)")
        return {
            "mode": mode,
            "volume_mb": MTTR_MB,
            "mttr_seconds": round(mttr, 3),
            "wire_purpose": purpose,
            "wire_repair_bytes": int(wire),
            "expected_repair_bytes": int(expect),
            "overhead_bytes": int(wire - expect),
        }
    finally:
        for vs in servers:
            try:
                vs.stop()
            except Exception:  # noqa: BLE001 — the killed one
                pass
        master.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    out_path = None
    args = sys.argv[1:]
    if "-o" in args:
        out_path = args[args.index("-o") + 1]
    try:
        import jax
        log(f"device: {jax.devices()[0]}")
    except Exception as e:  # noqa: BLE001 — CPU-only runs are fine
        log(f"jax device probe failed ({e}); CPU coder path")
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, VOLUME_MB * 1024 * 1024,
                           dtype=np.uint8)
    results = {"metric": "repair_traffic", "volume_mb": VOLUME_MB}
    with tempfile.TemporaryDirectory(prefix="bench_repair_") as tmp:
        for name in ("rs", "lrc"):
            results[name] = bench_codec(name, tmp, payload)
    results["read_savings"] = round(
        1.0 - results["lrc"]["repair_read_bytes"]
        / results["rs"]["repair_read_bytes"], 4)
    # Round 2: the same comparison measured ON THE WIRE by the flow
    # ledger — predicted planner reads vs actual ec.gather bytes.
    results["wire"] = {name: bench_codec_wire(name)
                       for name in ("rs", "lrc")}
    results["wire"]["read_savings_predicted"] = round(
        1.0 - results["wire"]["lrc"]["predicted_read_bytes"]
        / results["wire"]["rs"]["predicted_read_bytes"], 4)
    results["wire"]["read_savings_actual"] = round(
        1.0 - results["wire"]["lrc"]["wire_gather_bytes"]
        / results["wire"]["rs"]["wire_gather_bytes"], 4)
    # Round 3: end-to-end MTTR (kill -> converged) through the
    # durability autopilot, per durability scheme, ledger-checked.
    results["mttr"] = {mode: bench_repair_mttr(mode)
                       for mode in ("replicated", "rs", "lrc")}
    line = json.dumps(results)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
        log(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
