"""ctypes loader for the optional C++ host-side library (native/).

The native library accelerates host-path hot spots the way the reference
leans on Go-assembly SIMD (klauspost/crc32, klauspost/reedsolomon):
CRC32-C, GF(2^8) encode for the CPU fallback path, and needle scanning.
Pure-Python fallbacks exist for every entry point; everything degrades
gracefully when the library hasn't been built.

Build: `make -C native` (produces native/libseaweed_native.so).
"""

from __future__ import annotations

import ctypes
import functools
import os

_LIB_NAMES = ("libseaweed_native.so",)


@functools.lru_cache(maxsize=1)
def load() -> ctypes.CDLL | None:
    override = os.environ.get("SEAWEEDFS_TPU_NATIVE_LIB")
    candidates = [override] if override else []
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        candidates.append(os.path.join(here, "native", name))
    for path in candidates:
        if path and os.path.exists(path):
            try:
                return ctypes.CDLL(path)
            except OSError:
                continue
    return None


def crc32c_fn(lib: ctypes.CDLL):
    """Wrap uint32 sw_crc32c(uint32 crc, const uint8* buf, size_t len)."""
    fn = lib.sw_crc32c
    fn.restype = ctypes.c_uint32
    fn.argtypes = [ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]

    def crc32c(data: bytes, crc: int = 0) -> int:
        return fn(crc, bytes(data), len(data))

    return crc32c


def gf_encode_fn(lib: ctypes.CDLL):
    """Wrap the C++ GF(2^8) row-mix (CPU fallback coder).

    void sw_gf_mix(const uint8* mat, int rows, int cols,
                   const uint8* const* shards_in, uint8** shards_out,
                   size_t n)
    """
    fn = lib.sw_gf_mix
    fn.restype = None
    fn.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
                   ctypes.POINTER(ctypes.c_void_p),
                   ctypes.POINTER(ctypes.c_void_p), ctypes.c_size_t]
    return fn
