"""Profiling hooks — the reference's pprof plane, Python-native.

Reference: weed/util/grace/pprof.go:17-29 (`-cpuprofile/-memprofile`
flags writing profiles at graceful exit) and the `net/http/pprof`
debug handlers.  Equivalents here:

- setup_profiling(cpuprofile, memprofile): a 100Hz ALL-THREADS stack
  sampler from launch, dumped at exit in collapsed-stack format
  (flamegraph.pl / speedscope compatible); tracemalloc for the heap.
- enable_pprof_routes(server): /debug/pprof/{profile,heap,threads} —
  on-demand sampling, heap ranking (with ?stop), live thread stacks.

Sampling (sys._current_frames) rather than cProfile because cProfile
instruments only the thread that enables it — useless for servers
whose work runs on handler threads; a sampler sees every thread.

The routes are mounted only when SEAWEEDFS_TPU_PPROF=1: they are
unauthenticated by design (like net/http/pprof) and heap tracing taxes
every allocation, so exposing them is an operator decision.
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
import time
import traceback
from collections import Counter


def _collect_stacks(exclude_thread: int | None) -> list[tuple[str, ...]]:
    """One sample: the collapsed stack of every live thread."""
    out = []
    for tid, frame in sys._current_frames().items():
        if tid == exclude_thread:
            continue
        stack = []
        f = frame
        while f is not None:
            code = f.f_code
            stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
            f = f.f_back
        out.append(tuple(reversed(stack)))
    return out


def sample_stacks(seconds: float, hz: float = 100.0,
                  stop_event: threading.Event | None = None
                  ) -> tuple[Counter, int]:
    """Sample all threads (except the caller) for `seconds`; returns
    (Counter of collapsed stacks, total samples taken)."""
    me = threading.get_ident()
    counts: Counter = Counter()
    samples = 0
    interval = 1.0 / hz
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        if stop_event is not None and stop_event.is_set():
            break
        for stack in _collect_stacks(me):
            counts[stack] += 1
        samples += 1
        time.sleep(interval)
    return counts, samples


def setup_profiling(cpuprofile: str = "",
                    memprofile: str = "") -> None:
    """grace.SetupProfiling: begin profiling now, write at exit."""
    if cpuprofile:
        stop = threading.Event()
        counts: Counter = Counter()
        state = {"samples": 0}

        def sampler() -> None:
            while not stop.is_set():
                c, n = sample_stacks(1.0, stop_event=stop)
                counts.update(c)
                state["samples"] += n

        sampler_thread = threading.Thread(target=sampler, daemon=True,
                                          name="cpu-sampler")
        sampler_thread.start()

        def dump_cpu() -> None:
            stop.set()
            # Join before reading: a concurrent counts.update() while
            # iterating would RuntimeError and lose the whole profile.
            sampler_thread.join(timeout=2.0)
            with open(cpuprofile, "w") as f:
                for stack, n in counts.most_common():
                    f.write(";".join(stack) + f" {n}\n")
            print(f"cpu profile ({state['samples']} samples, all "
                  f"threads, collapsed-stack format — feed to "
                  f"flamegraph.pl/speedscope) written to {cpuprofile}",
                  file=sys.stderr)
        atexit.register(dump_cpu)
    if memprofile:
        import tracemalloc
        tracemalloc.start(16)

        def dump_mem() -> None:
            snap = tracemalloc.take_snapshot()
            with open(memprofile, "w") as f:
                for stat in snap.statistics("lineno")[:200]:
                    f.write(f"{stat}\n")
            print(f"heap profile written to {memprofile}",
                  file=sys.stderr)
        atexit.register(dump_mem)


def _profile_handler(query: dict, body: bytes):
    """CPU sample of EVERY thread for ?seconds=N (default 5, cap 30):
    collapsed stacks ranked by sample count."""
    seconds = min(float(query.get("seconds", 5) or 5), 30.0)
    counts, samples = sample_stacks(seconds)
    lines = [f"{samples} samples over {seconds:.1f}s at ~100Hz, "
             f"all threads (collapsed stacks; count = samples seen)",
             ""]
    for stack, n in counts.most_common(100):
        lines.append(f"{n:6d}  {';'.join(stack)}")
    return (200, ("\n".join(lines) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def _heap_handler(query: dict, body: bytes):
    """Heap ranking via tracemalloc.  First call starts tracing (which
    taxes every allocation); ?stop=true turns it back off."""
    import tracemalloc
    if query.get("stop") == "true":
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        return (200, b"tracemalloc stopped\n",
                {"Content-Type": "text/plain"})
    if not tracemalloc.is_tracing():
        tracemalloc.start(16)
        return (200, b"tracemalloc started; call again for a ranking, "
                     b"?stop=true to disable\n",
                {"Content-Type": "text/plain"})
    snap = tracemalloc.take_snapshot()
    top = snap.statistics("lineno")[:int(query.get("top", 50) or 50)]
    cur, peak = tracemalloc.get_traced_memory()
    lines = [f"traced: current {cur / 1e6:.1f}MB peak {peak / 1e6:.1f}MB",
             ""]
    lines += [str(s) for s in top]
    return (200, ("\n".join(lines) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def _threads_handler(query: dict, body: bytes):
    """Stacks of every live thread (the goroutine-dump analog)."""
    frames = sys._current_frames()
    out = []
    for th in threading.enumerate():
        frame = frames.get(th.ident)
        out.append(f"--- {th.name} (daemon={th.daemon}, "
                   f"alive={th.is_alive()}) ---")
        if frame is not None:
            out.append("".join(traceback.format_stack(frame)))
    return (200, ("\n".join(out) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def enable_pprof_routes(server) -> None:
    """Mount /debug/pprof handlers — ONLY when the operator opted in
    via SEAWEEDFS_TPU_PPROF=1 (they are unauthenticated and heap
    tracing is expensive; same operator-choice stance as exposing Go's
    net/http/pprof)."""
    if os.environ.get("SEAWEEDFS_TPU_PPROF", "") not in ("1", "true"):
        return
    server.route("GET", "/debug/pprof/profile", _profile_handler)
    server.route("GET", "/debug/pprof/heap", _heap_handler)
    server.route("GET", "/debug/pprof/threads", _threads_handler)
