"""Profiling hooks — the reference's pprof plane, Python-native.

Reference: weed/util/grace/pprof.go:17-29 (`-cpuprofile/-memprofile`
flags writing profiles at graceful exit) and the `net/http/pprof`
debug handlers.  Equivalents here:

- setup_profiling(cpuprofile, memprofile): a 100Hz ALL-THREADS stack
  sampler from launch, dumped at exit in collapsed-stack format
  (flamegraph.pl / speedscope compatible); tracemalloc for the heap.
- enable_pprof_routes(server): /debug/pprof/{profile,heap,threads} —
  ring-buffered or on-demand sampling, heap ranking (with ?stop),
  live thread stacks.
- ContinuousProfiler: an ALWAYS-ON low-rate (default ~19Hz — prime,
  so it can't phase-lock with periodic work) background sampler
  feeding a ring of 60s collapsed-stack windows.
  `/debug/pprof/profile?window=N` answers instantly from the last N
  windows; `?seconds=S` still takes a live high-rate sample.  The
  profiler also tracks a runnable-thread gauge
  (`SeaweedFS_runnable_threads`): how many sampled threads were NOT
  parked in a known wait — on CPython a direct GIL-pressure proxy.

Sampling (sys._current_frames) rather than cProfile because cProfile
instruments only the thread that enables it — useless for servers
whose work runs on handler threads; a sampler sees every thread.

The routes are mounted only when SEAWEEDFS_TPU_PPROF=1: they are
unauthenticated by design (like net/http/pprof) and heap tracing taxes
every allocation, so exposing them is an operator decision.  With the
routes mounted the continuous profiler starts too (that is the
"always-on" in the always-on cluster profiler);
SEAWEEDFS_TPU_PPROF_CONTINUOUS=0 keeps the routes but not the
sampler, =1 starts the sampler even without routes.  Knobs:
SEAWEEDFS_TPU_PPROF_HZ (default 19) and SEAWEEDFS_TPU_PPROF_WINDOW
(window seconds, default 60; ring holds 30 windows).
"""

from __future__ import annotations

import atexit
import math
import os
import sys
import threading
import time
import traceback
from collections import Counter, deque

from ..stats.metrics import Gauge


def _collect_stacks(exclude_thread: int | None) -> list[tuple[str, ...]]:
    """One sample: the collapsed stack of every live thread."""
    out = []
    for tid, frame in sys._current_frames().items():
        if tid == exclude_thread:
            continue
        stack = []
        f = frame
        while f is not None:
            code = f.f_code
            stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
            f = f.f_back
        out.append(tuple(reversed(stack)))
    return out


# Innermost frames that mean "parked, not runnable": waiting on a
# lock/condition/queue, blocked in select/poll or a socket read, or
# sleeping.  Everything else counts as runnable — i.e. holding or
# contending for the GIL.
_WAIT_FUNCS = frozenset({
    "wait", "wait_for", "acquire", "sleep", "select", "poll", "epoll",
    "kqueue", "accept", "recv", "recv_into", "recvfrom", "read",
    "readline", "readinto", "get", "join", "_recv", "do_handshake",
    "flowinfo", "getaddrinfo", "_wait_for_tstate_lock",
})
_WAIT_FILES = ("threading.py", "selectors.py", "queue.py", "ssl.py",
               "socket.py")  # matched as exact basenames by the
#                              cached collector below


def _collect_stacks_cached(exclude_thread: int | None,
                           frame_cache: dict,
                           thread_cache: dict
                           ) -> list[tuple[tuple, bool]]:
    """Cheap all-threads sample for the ALWAYS-ON sampler; two caches:

    - frame_cache: code object -> (label, is_wait).  Labels render
      once per code object using its static co_firstlineno — no
      per-tick f_lineno computation or f-string work.  The trade is
      function-granularity line numbers, which is what a flamegraph
      shows anyway; the live `?seconds=` sampler keeps the exact-line
      collector.
    - thread_cache: tid -> (frame id, code id, f_lasti, stack, wait).
      A PARKED thread's innermost frame is the same object at the
      same bytecode offset tick after tick, so its whole stack walk
      is skipped — and parked threads are the majority on a server.
      The identity check is (id(frame), id(code), f_lasti); an
      address-reuse collision would need a freed frame's address
      recycled for a frame of the same code paused at the same
      offset, at which point the cached stack is almost certainly
      right anyway — an acceptable heuristic for a SAMPLING profile
      (the same one py-spy-class profilers lean on).

    Returns [(stack, leaf_is_waiting), ...]."""
    out = []
    frames = sys._current_frames()
    for tid, frame in frames.items():
        if tid == exclude_thread:
            continue
        code = frame.f_code
        key = (id(frame), id(code), frame.f_lasti)
        hit = thread_cache.get(tid)
        if hit is not None and hit[0] == key:
            out.append((hit[1], hit[2]))
            continue
        leaf_ent = None
        stack = []
        f = frame
        while f is not None:
            c = f.f_code
            ent = frame_cache.get(c)
            if ent is None:
                fn = c.co_filename.rsplit("/", 1)[-1]
                label = f"{c.co_name} ({fn}:{c.co_firstlineno})"
                ent = frame_cache[c] = (
                    label,
                    c.co_name in _WAIT_FUNCS or fn in _WAIT_FILES)
            if leaf_ent is None:
                leaf_ent = ent
            stack.append(ent[0])
            f = f.f_back
        tup = tuple(reversed(stack))
        waiting = leaf_ent[1] if leaf_ent else True
        thread_cache[tid] = (key, tup, waiting)
        out.append((tup, waiting))
    # Thread churn (conn threads come and go): drop dead tids once
    # the cache outgrows the live set.
    if len(thread_cache) > 2 * len(frames):
        for tid in list(thread_cache):
            if tid not in frames:
                del thread_cache[tid]
    return out


def sample_stacks(seconds: float, hz: float = 100.0,
                  stop_event: threading.Event | None = None
                  ) -> tuple[Counter, int, float]:
    """Sample all threads (except the caller) for `seconds`; returns
    (Counter of collapsed stacks, total samples, measured elapsed).

    Drift-compensated: each tick is scheduled on an absolute grid
    (t0 + k/hz) instead of sleeping a full interval AFTER collection —
    with many threads the old full-interval sleep under-delivered the
    advertised rate by the (unbounded) collection cost per tick.
    Callers report the MEASURED rate (samples / elapsed), never the
    nominal one."""
    me = threading.get_ident()
    counts: Counter = Counter()
    samples = 0
    interval = 1.0 / hz
    t0 = time.monotonic()
    deadline = t0 + seconds
    next_tick = t0
    while True:
        now = time.monotonic()
        if now >= deadline or (stop_event is not None
                               and stop_event.is_set()):
            break
        for stack in _collect_stacks(me):
            counts[stack] += 1
        samples += 1
        next_tick += interval
        now = time.monotonic()
        if next_tick > now:
            # Clamp at 0: when the collection pass ends inside the
            # (deadline, next_tick) window — a re-anchored grid, or a
            # `seconds` that isn't a multiple of the interval —
            # deadline-now is negative and a raw sleep would raise.
            time.sleep(max(0.0, min(next_tick - now, deadline - now)))
        elif next_tick < now - 1.0:
            # Hopelessly behind (a multi-second GC/GIL stall): re-anchor
            # instead of machine-gunning catch-up samples.
            next_tick = now
    return counts, samples, time.monotonic() - t0


class ContinuousProfiler:
    """Always-on low-rate sampler feeding a ring of collapsed-stack
    windows.  One per process (PROFILER below); window merges are
    cheap Counter additions, so `?window=N` answers instantly."""

    def __init__(self, hz: float | None = None,
                 window_seconds: float | None = None,
                 windows: int = 30):
        from ..utils import env_float as _env_float
        self.hz = hz if hz is not None else \
            _env_float("SEAWEEDFS_TPU_PPROF_HZ", 19.0)
        self.window_seconds = window_seconds if window_seconds \
            is not None else _env_float("SEAWEEDFS_TPU_PPROF_WINDOW",
                                        60.0)
        # ring of (end_unix_ts, Counter, samples, elapsed_seconds)
        self._ring: "deque[tuple[float, Counter, int, float]]" = \
            deque(maxlen=windows)
        self._cur: Counter = Counter()
        self._cur_samples = 0
        self._cur_t0 = 0.0
        self._lock = threading.Lock()
        # Lifecycle guard (separate from _lock: stop() joins the loop
        # thread, which takes _lock — holding it across the join
        # would deadlock).  Serializes concurrent start/stop pairs
        # from racing /debug/attribution toggles.
        self._life = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Recent runnable-thread sample values (~last 256 ticks) for
        # the saturation gauge.
        self._runnable: "deque[int]" = deque(maxlen=256)
        self.started_at = 0.0

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        with self._life:
            if self.running:
                return
            self._stop.clear()
            self.started_at = time.time()
            # A resume starts a FRESH partial window: the old one's
            # clock stopped while paused, and carrying its samples
            # against a restarted _cur_t0 would overstate the
            # measured rate.  (Closed ring windows are untouched.)
            with self._lock:
                self._cur = Counter()
                self._cur_samples = 0
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="pprof-continuous")
            self._thread.start()

    def stop(self) -> None:
        with self._life:
            thread = self._thread
            if thread is None:
                return
            self._stop.set()
            thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        frame_cache: dict = {}
        thread_cache: dict = {}
        self._cur_t0 = time.monotonic()
        window_end = self._cur_t0 + self.window_seconds
        next_tick = self._cur_t0
        while not self._stop.is_set():
            sampled = _collect_stacks_cached(me, frame_cache,
                                             thread_cache)
            runnable = sum(1 for _s, waiting in sampled
                           if not waiting)
            with self._lock:
                for stack, _waiting in sampled:
                    self._cur[stack] += 1
                self._cur_samples += 1
                self._runnable.append(runnable)
                now = time.monotonic()
                if now >= window_end:
                    self._ring.append(
                        (time.time(), self._cur, self._cur_samples,
                         now - self._cur_t0))
                    self._cur = Counter()
                    self._cur_samples = 0
                    self._cur_t0 = now
                    window_end = now + self.window_seconds
            next_tick += interval
            now = time.monotonic()
            if next_tick > now:
                self._stop.wait(next_tick - now)
            elif next_tick < now - 1.0:
                next_tick = now

    # -- reads ---------------------------------------------------------------

    def merged(self, windows: int = 5) -> tuple[Counter, int, float]:
        """Last `windows` closed windows + the in-progress one, merged:
        (counts, samples, covered_seconds).  Instant — no sampling."""
        with self._lock:
            take = list(self._ring)[-windows:] if windows > 0 else []
            counts: Counter = Counter()
            samples = 0
            elapsed = 0.0
            for _ts, c, n, el in take:
                counts.update(c)
                samples += n
                elapsed += el
            if self._cur_samples:
                counts.update(self._cur)
                samples += self._cur_samples
                elapsed += time.monotonic() - self._cur_t0
        return counts, samples, elapsed

    def runnable_avg(self) -> float:
        """Mean runnable-thread count over the recent sample window —
        >1 sustained means threads are queueing on the GIL."""
        with self._lock:
            if not self._runnable:
                return 0.0
            return sum(self._runnable) / len(self._runnable)


PROFILER: ContinuousProfiler | None = None
_PROFILER_LOCK = threading.Lock()


def ensure_continuous_profiler() -> ContinuousProfiler:
    """Process-wide singleton, started on first call."""
    global PROFILER
    with _PROFILER_LOCK:
        if PROFILER is None:
            PROFILER = ContinuousProfiler()
        if not PROFILER.running:
            PROFILER.start()
        return PROFILER


def _runnable_gauge_value() -> float:
    p = PROFILER
    return p.runnable_avg() if p is not None and p.running else 0.0


# Registered on every role's scrape by rpc.enable_metrics: 0.0 until
# the continuous profiler runs (the gauge itself is always cheap).
runnable_threads = Gauge(
    "SeaweedFS_runnable_threads",
    "mean concurrently-runnable (non-waiting) threads over the "
    "profiler's recent samples — a GIL-pressure proxy; 0 when the "
    "continuous profiler is off",
    callback=_runnable_gauge_value)


def setup_profiling(cpuprofile: str = "",
                    memprofile: str = "") -> None:
    """grace.SetupProfiling: begin profiling now, write at exit."""
    if cpuprofile:
        stop = threading.Event()
        counts: Counter = Counter()
        state = {"samples": 0}

        def sampler() -> None:
            while not stop.is_set():
                c, n, _elapsed = sample_stacks(1.0, stop_event=stop)
                counts.update(c)
                state["samples"] += n

        sampler_thread = threading.Thread(target=sampler, daemon=True,
                                          name="cpu-sampler")
        sampler_thread.start()

        def dump_cpu() -> None:
            stop.set()
            # Join before reading: a concurrent counts.update() while
            # iterating would RuntimeError and lose the whole profile.
            sampler_thread.join(timeout=2.0)
            with open(cpuprofile, "w") as f:
                for stack, n in counts.most_common():
                    f.write(";".join(stack) + f" {n}\n")
            print(f"cpu profile ({state['samples']} samples, all "
                  f"threads, collapsed-stack format — feed to "
                  f"flamegraph.pl/speedscope) written to {cpuprofile}",
                  file=sys.stderr)
        atexit.register(dump_cpu)
    if memprofile:
        import tracemalloc
        tracemalloc.start(16)

        def dump_mem() -> None:
            snap = tracemalloc.take_snapshot()
            with open(memprofile, "w") as f:
                for stat in snap.statistics("lineno")[:200]:
                    f.write(f"{stat}\n")
            print(f"heap profile written to {memprofile}",
                  file=sys.stderr)
        atexit.register(dump_mem)


def _bad_request(msg: str):
    return (400, {"error": msg})


def _parse_float(query: dict, key: str) -> float | None:
    """Parse a finite float query param; raises ValueError with the
    offending text on garbage INCLUDING NaN/inf — `?seconds=NaN` must
    400, not propagate through min/max clamps unordered."""
    raw = query.get(key)
    if raw in (None, ""):
        return None
    val = float(raw)          # ValueError -> caller 400s
    if math.isnan(val) or math.isinf(val):
        raise ValueError(raw)
    return val


def _render_profile(counts: Counter, samples: int, elapsed: float,
                    query: dict, source: str):
    """Ranked human text, or raw collapsed-stack lines for
    ?format=collapsed (flamegraph.pl / speedscope / cluster.profile
    input)."""
    if query.get("format") == "collapsed":
        lines = [f"{';'.join(stack)} {n}"
                 for stack, n in counts.most_common()]
        return (200, ("\n".join(lines) + "\n").encode() if lines
                else b"", {"Content-Type": "text/plain; charset=utf-8",
                           "X-Pprof-Samples": str(samples),
                           "X-Pprof-Seconds": f"{elapsed:.3f}"})
    rate = samples / elapsed if elapsed > 0 else 0.0
    lines = [f"{samples} samples over {elapsed:.1f}s at "
             f"{rate:.1f}Hz measured ({source}), all threads "
             f"(collapsed stacks; count = samples seen)",
             ""]
    for stack, n in counts.most_common(100):
        lines.append(f"{n:6d}  {';'.join(stack)}")
    return (200, ("\n".join(lines) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def _profile_handler(query: dict, body: bytes):
    """CPU profile.  `?window=N` merges the last N ring windows of the
    continuous profiler (instant); `?seconds=S` (clamped to [0.1, 30])
    takes a live ~100Hz sample; with neither, the ring is preferred
    when the continuous profiler runs, else a live 5s sample."""
    try:
        seconds = _parse_float(query, "seconds")
    except ValueError:
        return _bad_request(
            f"seconds={query.get('seconds')!r} is not a finite number")
    try:
        window = _parse_float(query, "window")
    except ValueError:
        return _bad_request(
            f"window={query.get('window')!r} is not a finite number")
    prof = PROFILER
    if seconds is None and window is None:
        if prof is not None and prof.running:
            window = 5.0
        else:
            seconds = 5.0
    if window is not None:
        if prof is None or not prof.running:
            return (404, {"error":
                          "continuous profiler not running "
                          "(SEAWEEDFS_TPU_PPROF_CONTINUOUS=0?) — "
                          "use ?seconds= for a live sample"})
        n = max(1, int(window))
        counts, samples, elapsed = prof.merged(n)
        return _render_profile(
            counts, samples, elapsed, query,
            f"ring: last {n} windows of {prof.window_seconds:g}s "
            f"at ~{prof.hz:g}Hz")
    seconds = min(max(seconds, 0.1), 30.0)
    counts, samples, elapsed = sample_stacks(seconds)
    return _render_profile(counts, samples, elapsed, query,
                           "live sample")


# tracemalloc is process-global with a start/stop world switch; two
# concurrent /debug/pprof/heap calls racing start against take_snapshot
# (or stop) can die inside the tracer.  One handler at a time.
_HEAP_LOCK = threading.Lock()


def _heap_handler(query: dict, body: bytes):
    """Heap ranking via tracemalloc.  First call starts tracing (which
    taxes every allocation); ?stop=true turns it back off."""
    import tracemalloc
    try:
        top_n = int(query.get("top", 50) or 50)
    except ValueError:
        return _bad_request(f"top={query.get('top')!r} is not a number")
    top_n = min(max(top_n, 1), 1000)
    with _HEAP_LOCK:
        if query.get("stop") == "true":
            if tracemalloc.is_tracing():
                tracemalloc.stop()
            return (200, b"tracemalloc stopped\n",
                    {"Content-Type": "text/plain"})
        if not tracemalloc.is_tracing():
            tracemalloc.start(16)
            return (200, b"tracemalloc started; call again for a "
                         b"ranking, ?stop=true to disable\n",
                    {"Content-Type": "text/plain"})
        snap = tracemalloc.take_snapshot()
        cur, peak = tracemalloc.get_traced_memory()
    top = snap.statistics("lineno")[:top_n]
    lines = [f"traced: current {cur / 1e6:.1f}MB peak {peak / 1e6:.1f}MB",
             ""]
    lines += [str(s) for s in top]
    return (200, ("\n".join(lines) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def _threads_handler(query: dict, body: bytes):
    """Stacks of every live thread (the goroutine-dump analog)."""
    frames = sys._current_frames()
    out = []
    for th in threading.enumerate():
        frame = frames.get(th.ident)
        out.append(f"--- {th.name} (daemon={th.daemon}, "
                   f"alive={th.is_alive()}) ---")
        if frame is not None:
            out.append("".join(traceback.format_stack(frame)))
    return (200, ("\n".join(out) + "\n").encode(),
            {"Content-Type": "text/plain; charset=utf-8"})


def enable_pprof_routes(server) -> None:
    """Mount /debug/pprof handlers — ONLY when the operator opted in
    via SEAWEEDFS_TPU_PPROF=1 (they are unauthenticated and heap
    tracing is expensive; same operator-choice stance as exposing Go's
    net/http/pprof).  Starting the routes also starts the process's
    continuous profiler (SEAWEEDFS_TPU_PPROF_CONTINUOUS=0 opts out)."""
    continuous = os.environ.get("SEAWEEDFS_TPU_PPROF_CONTINUOUS", "")
    if os.environ.get("SEAWEEDFS_TPU_PPROF", "") in ("1", "true"):
        server.route("GET", "/debug/pprof/profile", _profile_handler)
        server.route("GET", "/debug/pprof/heap", _heap_handler)
        server.route("GET", "/debug/pprof/threads", _threads_handler)
        if continuous not in ("0", "false"):
            ensure_continuous_profiler()
    elif continuous in ("1", "true"):
        ensure_continuous_profiler()
