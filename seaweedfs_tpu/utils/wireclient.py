"""Shared skeleton for the no-SDK wire clients (RESP / OP_MSG / CQL):
one socket, one in-flight command, redial-once on a dead connection.

Subclasses implement `_handshake()` (post-connect protocol setup) and
call `_call(fn)` with a closure that performs one round trip on the
live socket — the retry/reconnect/close lifecycle lives here once
instead of per protocol (filer/redis_store.py, mongo_store.py,
cassandra_store.py)."""

from __future__ import annotations

import socket
import threading

from ..trace import span as _trace_span


class WireClient:
    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    # -- subclass hooks ------------------------------------------------------

    def _handshake(self) -> None:
        """Protocol setup after the TCP connect (AUTH/STARTUP/...)."""

    def _on_connect(self) -> None:
        """Wrap the fresh socket (buffered readers etc.)."""

    # -- lifecycle -----------------------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._on_connect()
        self._handshake()

    def _recv_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            piece = self._sock.recv(n - len(out))
            if not piece:
                raise ConnectionError(
                    f"{type(self).__name__}: peer closed the connection")
            out += piece
        return bytes(out)

    def _call(self, fn):
        """Run one round trip under the lock, redialing once if the
        pooled connection died between commands.

        Binary wire protocols (RESP/OP_MSG/CQL) carry no traceparent
        header, so the backing-store hop appears on a trace as a client
        span here instead — a no-op outside an active request."""
        with _trace_span(f"wire.{type(self).__name__}",
                         peer=f"{self.host}:{self.port}"):
            with self._lock:
                for attempt in (0, 1):
                    if self._sock is None:
                        self._connect()
                    try:
                        return fn()
                    except (OSError, ConnectionError):
                        self.close_nolock()
                        if attempt:
                            raise
        raise AssertionError("unreachable")

    def close_nolock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self.close_nolock()
