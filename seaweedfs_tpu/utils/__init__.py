"""Shared utilities: native-library loading, misc helpers."""
