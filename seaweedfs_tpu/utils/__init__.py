"""Shared utilities: native-library loading, misc helpers."""

import os as _os


def env_float(name: str, default: float) -> float:
    """Float env-var knob with a safe fallback — the one parser behind
    the trace, resilience, and EC-rebuild tunables (an unset or
    malformed value must never crash a server at import).  Lives here,
    dependency-free: utils.config needs tomllib (3.11+), and knob
    readers must import on 3.10."""
    try:
        return float(_os.environ.get(name, "") or default)
    except ValueError:
        return default
