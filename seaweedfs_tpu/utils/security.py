"""Write-access JWT + request guard + TLS plane (reference:
weed/security/jwt.go:21, guard.go:43-65, tls.go).

The reference guards volume-server writes with an HS256 JWT minted by the
master (claim `fid` binds the token to one file id) when `jwt.signing.key`
is set in security.toml, plus an IP white list.  Same scheme here, using
only the stdlib: compact JWS, HS256, `exp` + `fid` claims.

TLS follows security.toml's `[grpc.<component>]` sections exactly like
the reference (tls.go LoadServerTLS/LoadClientTLS): each server role
loads `grpc.<role>.cert/key` and requires client certificates signed by
`grpc.ca` (mutual TLS, RequireAndVerifyClientCert); clients present
`grpc.client.cert/key`.  Our transport is the pooled HTTP RPC plane, so
the contexts install into cluster.rpc (JsonHttpServer(ssl_context=...) +
set_client_ssl_context), and every inter-server URL is upgraded to
https by the transport — addresses stay `host:port`, the scheme is the
dial option, as in grpc_client_server.go.  One deliberate improvement:
the reference's client sets InsecureSkipVerify (tls.go:70); ours
verifies the server chain against the same CA.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class JwtError(Exception):
    pass


def gen_jwt(signing_key: str, expires_seconds: int, fid: str) -> str:
    """Mint the write token the master attaches to Assign responses
    (security/jwt.go GenJwt)."""
    if not signing_key:
        return ""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = {"fid": fid}
    if expires_seconds:
        claims["exp"] = int(time.time()) + expires_seconds
    payload = _b64(json.dumps(claims).encode())
    msg = f"{header}.{payload}".encode()
    sig = hmac.new(signing_key.encode(), msg, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64(sig)}"


def decode_jwt(signing_key: str, token: str) -> dict:
    """Verify signature + expiry, return claims (security/jwt.go DecodeJwt)."""
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        raise JwtError("malformed token") from None
    msg = f"{header}.{payload}".encode()
    want = hmac.new(signing_key.encode(), msg, hashlib.sha256).digest()
    if not hmac.compare_digest(want, _unb64(sig)):
        raise JwtError("bad signature")
    claims = json.loads(_unb64(payload))
    if "exp" in claims and claims["exp"] < time.time():
        raise JwtError("token expired")
    return claims


class Guard:
    """Per-request access check: IP white list OR valid JWT
    (security/guard.go WhiteList/Secure)."""

    def __init__(self, white_list: list[str] | None = None,
                 signing_key: str = "", expires_seconds: int = 10):
        self.white_list = set(white_list or [])
        self.signing_key = signing_key
        self.expires_seconds = expires_seconds

    @property
    def is_active(self) -> bool:
        return bool(self.white_list or self.signing_key)

    def check_white_list(self, peer_ip: str) -> bool:
        return not self.white_list or peer_ip in self.white_list

    def check_jwt(self, token: str, fid: str) -> None:
        """Raises JwtError unless the token authorizes writing `fid`."""
        if not self.signing_key:
            return
        if not token:
            raise JwtError("jwt required")
        claims = decode_jwt(self.signing_key, token)
        claimed = claims.get("fid", "")
        # The reference accepts a token minted for the base fid on its
        # _suffix variants (jwt.go: strips after '_').
        if claimed and claimed != fid and not fid.startswith(claimed + "_"):
            raise JwtError(f"token fid {claimed!r} != {fid!r}")


# -- TLS plane (security/tls.go) ---------------------------------------------


def tls_server_context(cert_file: str, key_file: str, ca_file: str = "",
                       require_client_cert: bool = False):
    """Server-side context: serve the given cert; with
    require_client_cert, demand a CA-signed client certificate — the
    reference's RequireAndVerifyClientCert mutual TLS (tls.go:33-38)."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    if ca_file and require_client_cert:
        ctx.load_verify_locations(cafile=ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def tls_client_context(cert_file: str = "", key_file: str = "",
                       ca_file: str = ""):
    """Client-side context: present cert/key for mTLS and verify the
    server chain against the CA.  Hostname checking is off because
    cluster addresses are bare `host:port` (the reference skips server
    verification entirely; we keep chain verification)."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    if ca_file:
        ctx.load_verify_locations(cafile=ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    else:
        ctx.verify_mode = ssl.CERT_NONE
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx


def load_server_tls(cfg, component: str):
    """security.toml `[grpc.<component>]` -> server SSLContext, or None
    when no cert/key is configured (tls.go LoadServerTLS: missing config
    degrades to plaintext).

    Client-certificate policy: the reference runs mutual TLS on a
    dedicated inter-server gRPC port while the public HTTP ports stay
    separate; our servers expose ONE port serving both planes, so
    demanding client certs by default would lock standard end-user
    clients (aws-cli, curl, davfs2) out of the gateways.  Default is
    therefore server-auth TLS; set `client_auth = "require"` per
    component to get the reference's RequireAndVerifyClientCert
    behavior where the port is cluster-internal."""
    parsed = server_tls_config(cfg, component)
    if parsed is None:
        return None
    cert, key, ca, mode = parsed
    return tls_server_context(cert, key, ca,
                              require_client_cert=mode == "require")


def server_tls_config(cfg, component: str):
    """Parse + validate `[grpc.<component>]` -> (cert, key, ca, mode)
    or None — the ONE config reader behind both the HTTPS and gRPC
    planes, so a client_auth typo fails loudly on both."""
    if cfg is None:
        return None
    cert = cfg.get_string(f"grpc.{component}.cert")
    key = cfg.get_string(f"grpc.{component}.key")
    if not cert or not key:
        return None
    ca = cfg.get_string(f"grpc.{component}.ca") or cfg.get_string("grpc.ca")
    mode = cfg.get_string(f"grpc.{component}.client_auth", "none").lower()
    if mode not in ("none", "require"):
        raise ValueError(
            f"grpc.{component}.client_auth must be 'none' or 'require', "
            f"got {mode!r}")
    if mode == "require" and not ca:
        raise ValueError(
            f"grpc.{component}.client_auth = 'require' needs grpc.ca")
    return cert, key, ca, mode


def load_client_tls(cfg, component: str = "client"):
    """security.toml `[grpc.client]` -> client SSLContext, or None.
    Like the reference (tls.go:48-51), all of cert/key/ca must be set."""
    if cfg is None:
        return None
    cert = cfg.get_string(f"grpc.{component}.cert")
    key = cfg.get_string(f"grpc.{component}.key")
    ca = cfg.get_string(f"grpc.{component}.ca") or cfg.get_string("grpc.ca")
    if not cert or not key or not ca:
        return None
    return tls_client_context(cert, key, ca)


_security_cfg = None


def security_configuration():
    """The process-wide parsed security.toml, loaded once and shared by
    the CLI dispatcher and every server command — one source of truth
    (the reference loads it once via viper at command start)."""
    global _security_cfg
    if _security_cfg is None:
        from .config import load_configuration
        _security_cfg = load_configuration("security")
    return _security_cfg


def install_cluster_tls(cfg) -> bool:
    """Wire the client half of the TLS plane process-wide: install the
    `[grpc.client]` context into the RPC transport and upgrade every
    inter-server http:// URL to https.  Returns True when TLS is on."""
    ctx = load_client_tls(cfg)
    if ctx is None:
        return False
    from ..cluster import rpc
    rpc.set_client_ssl_context(ctx, force_https=True)
    return True


def grpc_server_credentials(cfg, component: str):
    """security.toml `[grpc.<component>]` -> grpc.ServerCredentials, or
    None when no cert/key is configured — the same parsed/validated
    config as the HTTPS plane (server_tls_config), so both planes of
    one component share one TLS story."""
    parsed = server_tls_config(cfg, component)
    if parsed is None:
        return None
    cert, key, ca, mode = parsed
    import grpc
    with open(key, "rb") as f:
        key_pem = f.read()
    with open(cert, "rb") as f:
        cert_pem = f.read()
    root = None
    if ca:
        with open(ca, "rb") as f:
            root = f.read()
    return grpc.ssl_server_credentials(
        [(key_pem, cert_pem)], root_certificates=root,
        require_client_auth=(mode == "require"))
