"""Write-access JWT + request guard (reference: weed/security/jwt.go:21,
guard.go:43-65).

The reference guards volume-server writes with an HS256 JWT minted by the
master (claim `fid` binds the token to one file id) when `jwt.signing.key`
is set in security.toml, plus an IP white list.  Same scheme here, using
only the stdlib: compact JWS, HS256, `exp` + `fid` claims.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class JwtError(Exception):
    pass


def gen_jwt(signing_key: str, expires_seconds: int, fid: str) -> str:
    """Mint the write token the master attaches to Assign responses
    (security/jwt.go GenJwt)."""
    if not signing_key:
        return ""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = {"fid": fid}
    if expires_seconds:
        claims["exp"] = int(time.time()) + expires_seconds
    payload = _b64(json.dumps(claims).encode())
    msg = f"{header}.{payload}".encode()
    sig = hmac.new(signing_key.encode(), msg, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64(sig)}"


def decode_jwt(signing_key: str, token: str) -> dict:
    """Verify signature + expiry, return claims (security/jwt.go DecodeJwt)."""
    try:
        header, payload, sig = token.split(".")
    except ValueError:
        raise JwtError("malformed token") from None
    msg = f"{header}.{payload}".encode()
    want = hmac.new(signing_key.encode(), msg, hashlib.sha256).digest()
    if not hmac.compare_digest(want, _unb64(sig)):
        raise JwtError("bad signature")
    claims = json.loads(_unb64(payload))
    if "exp" in claims and claims["exp"] < time.time():
        raise JwtError("token expired")
    return claims


class Guard:
    """Per-request access check: IP white list OR valid JWT
    (security/guard.go WhiteList/Secure)."""

    def __init__(self, white_list: list[str] | None = None,
                 signing_key: str = "", expires_seconds: int = 10):
        self.white_list = set(white_list or [])
        self.signing_key = signing_key
        self.expires_seconds = expires_seconds

    @property
    def is_active(self) -> bool:
        return bool(self.white_list or self.signing_key)

    def check_white_list(self, peer_ip: str) -> bool:
        return not self.white_list or peer_ip in self.white_list

    def check_jwt(self, token: str, fid: str) -> None:
        """Raises JwtError unless the token authorizes writing `fid`."""
        if not self.signing_key:
            return
        if not token:
            raise JwtError("jwt required")
        claims = decode_jwt(self.signing_key, token)
        claimed = claims.get("fid", "")
        # The reference accepts a token minted for the base fid on its
        # _suffix variants (jwt.go: strips after '_').
        if claimed and claimed != fid and not fid.startswith(claimed + "_"):
            raise JwtError(f"token fid {claimed!r} != {fid!r}")
