"""A simple writer-preferring readers-writer lock.

The volume engine's read path (O(1) pread) must run concurrently across
readers while write batches / vacuum file swaps get exclusivity — the same
discipline as the reference's `dataFileAccessLock` RWMutex
(weed/storage/volume.go:36).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class RWLock:
    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()
