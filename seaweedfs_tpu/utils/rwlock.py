"""A simple writer-preferring readers-writer lock.

The volume engine's read path (O(1) pread) must run concurrently across
readers while write batches / vacuum file swaps get exclusivity — the same
discipline as the reference's `dataFileAccessLock` RWMutex
(weed/storage/volume.go:36).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from ..stats import contention as _contention
from ..stats import phases as _phases


class RWLock:
    def __init__(self, name: str | None = None):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        # Optional contention metering of the WRITE side (the volume
        # engine names its file lock, stats/contention.py): write
        # wait/hold land in the lock histograms + the request phase
        # ledger.  The read side stays unmetered — concurrent readers
        # are the uncontended common case.  Set post-construction via
        # contention.wrap_rwlock_write too.
        self._meter_name = name
        self._write_since = 0.0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        metered = self._meter_name is not None and _contention.ENABLED
        t0 = None
        with self._cond:
            self._writers_waiting += 1
            if self._writer or self._readers:
                # Contended: measure the wait (only then — the
                # uncontended pass stays condition-check cheap).
                if metered:
                    t0 = time.perf_counter()
                while self._writer or self._readers:
                    self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
            if metered:
                self._write_since = time.perf_counter()
            elif self._meter_name is not None:
                self._write_since = 0.0  # disarmed: no hold to settle
        # Histogram/ledger work happens OUTSIDE the condition: readers
        # and other writers must never queue behind metrics (the same
        # stance as MeteredLock.release observing after the release).
        if t0 is not None:
            wait = self._write_since - t0
            _contention.lock_wait_seconds.observe(
                wait, lock=self._meter_name)
            _phases.note("lock", wait)

    def release_write(self) -> None:
        name = self._meter_name
        if name is not None and _contention.ENABLED and \
                self._write_since:
            hold = time.perf_counter() - self._write_since
            _contention.lock_hold_seconds.observe(hold, lock=name)
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()
