"""TOML config loading — the reference's viper setup (weed/util/config.go,
weed/command/scaffold.go:12-58).

Search order for `<name>.toml`: `./`, `~/.seaweedfs/`, `/etc/seaweedfs/`
(util/config.go LoadConfiguration).  `WEED_<SECTION>_<KEY>=val` environment
variables override file values, matching viper's `WEED_` AutomaticEnv with
`.`->`_` replacement.
"""

from __future__ import annotations

import os
from typing import Any

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.10: stdlib tomllib is 3.11+
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        # No TOML parser available.  The common case — no config file
        # on disk — must still work (every CLI command loads
        # security.toml at startup and an absent file is an empty
        # config); only actually PARSING a file requires the parser.
        tomllib = None  # type: ignore[assignment]

SEARCH_PATHS = [".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"]


class Configuration:
    """Flattened dotted-key view over a parsed TOML tree + env overrides."""

    def __init__(self, tree: dict[str, Any] | None = None):
        self._flat: dict[str, Any] = {}
        if tree:
            self._flatten("", tree)

    def _flatten(self, prefix: str, tree: dict[str, Any]) -> None:
        for k, val in tree.items():
            key = f"{prefix}{k}"
            if isinstance(val, dict):
                self._flatten(key + ".", val)
            else:
                self._flat[key.lower()] = val

    def _env_override(self, key: str) -> str | None:
        env_key = "WEED_" + key.upper().replace(".", "_").replace("-", "_")
        return os.environ.get(env_key)

    def get(self, key: str, default: Any = None) -> Any:
        env = self._env_override(key)
        if env is not None:
            return env
        return self._flat.get(key.lower(), default)

    def get_bool(self, key: str, default: bool = False) -> bool:
        val = self.get(key, default)
        if isinstance(val, str):
            return val.lower() in ("1", "true", "yes", "on")
        return bool(val)

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self.get(key, default))

    def get_string(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def sub(self, prefix: str) -> dict[str, Any]:
        """All keys under `prefix.` with the prefix stripped."""
        p = prefix.lower() + "."
        return {k[len(p):]: v for k, v in self._flat.items()
                if k.startswith(p)}


def load_configuration(name: str, required: bool = False,
                       search_paths: list[str] | None = None
                       ) -> Configuration:
    """Find and parse `<name>.toml` along the search path."""
    for d in search_paths or SEARCH_PATHS:
        path = os.path.join(d, name + ".toml")
        if os.path.isfile(path):
            if tomllib is None:
                raise RuntimeError(
                    f"found {path} but no TOML parser is available "
                    "(stdlib tomllib needs Python 3.11+; or pip "
                    "install tomli)")
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f))
    if required:
        raise FileNotFoundError(
            f"{name}.toml not found in {search_paths or SEARCH_PATHS}; "
            f"run `weed scaffold -config={name}` to generate a template")
    return Configuration()
