"""Leveled glog-style logging (reference: weed/glog/, a vendored glog fork).

The reference logs with `glog.V(n).Infof(...)` verbosity gates plus
Info/Warning/Error/Fatal severities, `-v` controlling the verbosity
threshold.  This is the same surface on Python's stdlib logging:

    from seaweedfs_tpu.utils import glog
    glog.setup(verbosity=2)
    glog.v(1).infof("volume %d loaded", vid)
    glog.infof("serving on %s", addr)
    glog.errorf("read %s: %s", fid, err)

Format mirrors glog's header: `I0729 14:03:02.123456 file.py:87] msg`.
"""

from __future__ import annotations

import io
import logging
import os
import sys
import threading
import time
import traceback

_LEVEL_CHARS = {logging.DEBUG: "D", logging.INFO: "I",
                logging.WARNING: "W", logging.ERROR: "E",
                logging.CRITICAL: "F"}

_logger = logging.getLogger("seaweedfs_tpu")
_verbosity = 0
_setup_done = False
_lock = threading.Lock()


class _GlogFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        c = _LEVEL_CHARS.get(record.levelno, "I")
        t = time.localtime(record.created)
        us = int((record.created % 1) * 1e6)
        head = (f"{c}{t.tm_mon:02d}{t.tm_mday:02d} "
                f"{t.tm_hour:02d}:{t.tm_min:02d}:{t.tm_sec:02d}.{us:06d} "
                f"{os.path.basename(record.pathname)}:{record.lineno}]")
        msg = record.getMessage()
        if record.exc_info:
            buf = io.StringIO()
            traceback.print_exception(*record.exc_info, file=buf)
            msg += "\n" + buf.getvalue().rstrip()
        return f"{head} {msg}"


class _StderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time (it is swapped under pytest
    capture and by daemonizers)."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        return sys.stderr


def setup(verbosity: int | None = None, log_file: str | None = None) -> None:
    """Install handlers. Idempotent; env WEED_V overrides verbosity."""
    global _verbosity, _setup_done
    with _lock:
        if verbosity is None:
            verbosity = int(os.environ.get("WEED_V", "0"))
        _verbosity = verbosity
        if _setup_done:
            return
        _setup_done = True
        handler = _StderrHandler()
        handler.setFormatter(_GlogFormatter())
        _logger.addHandler(handler)
        if log_file:
            fh = logging.FileHandler(log_file)
            fh.setFormatter(_GlogFormatter())
            _logger.addHandler(fh)
        _logger.setLevel(logging.DEBUG)
        _logger.propagate = False


def _emit(level: int, fmt: str, *args) -> None:
    if not _setup_done:
        setup()
    # stacklevel=3: caller -> infof/_emit -> here
    _logger.log(level, fmt, *args, stacklevel=3)


def infof(fmt: str, *args) -> None:
    _emit(logging.INFO, fmt, *args)


def warningf(fmt: str, *args) -> None:
    _emit(logging.WARNING, fmt, *args)


def errorf(fmt: str, *args) -> None:
    _emit(logging.ERROR, fmt, *args)


def fatalf(fmt: str, *args) -> None:
    _emit(logging.CRITICAL, fmt, *args)
    raise SystemExit(1)


class _V:
    """glog.V(n) gate: logs only when n <= the configured verbosity."""

    __slots__ = ("on",)

    def __init__(self, on: bool):
        self.on = on

    def infof(self, fmt: str, *args) -> None:
        if self.on:
            _emit(logging.DEBUG, fmt, *args)


def v(level: int) -> _V:
    return _V(level <= _verbosity)
