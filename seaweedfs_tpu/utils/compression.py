"""Upload-side gzip: compress what shrinks, skip what won't.

Reference: weed/util/compression.go (GzipData/UnGzipData) and the
IsGzippableFileType heuristic in weed/operation/upload_content.go:
compressible mime families / extensions are gzipped on the client, sent
with `Content-Encoding: gzip`, stored with the needle's compressed flag,
and transparently decompressed for readers that don't accept gzip.
"""

from __future__ import annotations

import gzip
import io
import zlib

# Extension / mime families that reliably shrink.  Media containers
# (jpeg/png/zip/mp4/...) are already entropy-coded and excluded.
_EXTS = {
    ".txt", ".log", ".md", ".csv", ".tsv", ".json", ".js", ".css",
    ".html", ".htm", ".xml", ".svg", ".yaml", ".yml", ".toml", ".ini",
    ".conf", ".py", ".go", ".c", ".h", ".cpp", ".cc", ".java", ".rs",
    ".sh", ".sql", ".proto", ".ps", ".pdf",
}
_MIME_PREFIXES = ("text/",)
_MIME_EXACT = {
    "application/json", "application/javascript", "application/xml",
    "application/xhtml+xml", "application/x-javascript",
    "image/svg+xml", "application/x-ndjson",
}


def is_compressable(name: str = "", mime: str = "") -> bool:
    mime = (mime or "").split(";")[0].strip().lower()
    if mime:
        if any(mime.startswith(p) for p in _MIME_PREFIXES):
            return True
        if mime in _MIME_EXACT:
            return True
    name = (name or "").lower()
    dot = name.rfind(".")
    return dot >= 0 and name[dot:] in _EXTS


def gzip_data(data: bytes, level: int = 3) -> bytes:
    """Deterministic gzip (no mtime in the header) so replicas built
    from the same bytes stay byte-identical."""
    buf = io.BytesIO()
    with gzip.GzipFile(fileobj=buf, mode="wb",
                       compresslevel=level, mtime=0) as f:
        f.write(data)
    return buf.getvalue()


def ungzip_data(data: bytes) -> bytes:
    # 32+15: accept both gzip and raw-zlib wrapped payloads.
    try:
        return gzip.decompress(data)
    except (OSError, EOFError):
        return zlib.decompress(data, 32 + 15)


def maybe_gzip(data: bytes, name: str = "", mime: str = "",
               force: bool = False) -> tuple[bytes, bool]:
    """Gzip when the content type suggests it AND it actually shrinks
    (upload_content.go keeps the original if compression loses)."""
    if len(data) < 128:
        return data, False
    if not force and not is_compressable(name, mime):
        return data, False
    z = gzip_data(data)
    if len(z) >= len(data):
        return data, False
    return z, True
