"""At-rest chunk encryption: AES-256-GCM over OpenSSL's libcrypto.

Reference: weed/util/cipher.go (Encrypt/Decrypt used by the cipher
upload option, weed/operation/upload_content.go:150-170) — the chunks a
filer writes are sealed with a fresh random 256-bit key per chunk and
the key lives only in the filer's metadata (FileChunk.cipher_key), so a
volume server holds opaque bytes.

The AES primitive comes from the system libcrypto through ctypes (the
EVP interface) — a native code path, not a Python reimplementation.
Wire format: 12-byte nonce || ciphertext || 16-byte GCM tag, matching
Go's cipher.NewGCM layout of nonce + Seal output.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import threading

NONCE_SIZE = 12
TAG_SIZE = 16
KEY_SIZE = 32


class CipherError(Exception):
    """Encryption unavailable or decryption failed (tamper/wrong key)."""


_lib = None
_lib_err: str | None = None
_lock = threading.Lock()


def _crypto():
    """Load libcrypto once and declare the EVP signatures we use."""
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            if _lib is None:
                raise CipherError(_lib_err)
            return _lib
        name = ctypes.util.find_library("crypto")
        if not name:
            _lib_err = ("libcrypto not found: the cipher upload option "
                        "requires OpenSSL's libcrypto on the host")
            raise CipherError(_lib_err)
        try:
            lib = ctypes.CDLL(name)
            lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
            lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
            lib.EVP_aes_256_gcm.restype = ctypes.c_void_p
            for fn in ("EVP_EncryptInit_ex", "EVP_DecryptInit_ex"):
                getattr(lib, fn).argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_char_p, ctypes.c_char_p]
            for fn in ("EVP_EncryptUpdate", "EVP_DecryptUpdate"):
                getattr(lib, fn).argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
                    ctypes.c_int]
            for fn in ("EVP_EncryptFinal_ex", "EVP_DecryptFinal_ex"):
                getattr(lib, fn).argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_int)]
            lib.EVP_CIPHER_CTX_ctrl.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_void_p]
        except (OSError, AttributeError) as e:
            _lib_err = f"libcrypto unusable: {e}"
            raise CipherError(_lib_err) from None
        _lib = lib
        return _lib


# EVP_CIPHER_CTX_ctrl commands (openssl/evp.h)
_EVP_CTRL_GCM_SET_IVLEN = 0x9
_EVP_CTRL_GCM_GET_TAG = 0x10
_EVP_CTRL_GCM_SET_TAG = 0x11


def available() -> bool:
    try:
        _crypto()
        return True
    except CipherError:
        return False


def new_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(plaintext: bytes, key: bytes | None = None
            ) -> tuple[bytes, bytes]:
    """Seal plaintext; returns (nonce||ct||tag, key). A fresh random key
    is minted when none is given (the per-chunk key model)."""
    lib = _crypto()
    if key is None:
        key = new_key()
    if len(key) != KEY_SIZE:
        raise CipherError(f"key must be {KEY_SIZE} bytes")
    nonce = os.urandom(NONCE_SIZE)
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise CipherError("EVP_CIPHER_CTX_new failed")
    try:
        if not lib.EVP_EncryptInit_ex(ctx, lib.EVP_aes_256_gcm(),
                                      None, None, None):
            raise CipherError("EncryptInit(cipher) failed")
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_IVLEN,
                                NONCE_SIZE, None)
        if not lib.EVP_EncryptInit_ex(ctx, None, None, key, nonce):
            raise CipherError("EncryptInit(key) failed")
        out = ctypes.create_string_buffer(len(plaintext) or 1)
        n = ctypes.c_int(0)
        if not lib.EVP_EncryptUpdate(ctx, out, ctypes.byref(n),
                                     plaintext, len(plaintext)):
            raise CipherError("EncryptUpdate failed")
        ct = out.raw[:n.value]
        fin = ctypes.create_string_buffer(TAG_SIZE)
        if not lib.EVP_EncryptFinal_ex(ctx, fin, ctypes.byref(n)):
            raise CipherError("EncryptFinal failed")
        ct += fin.raw[:n.value]
        tag = ctypes.create_string_buffer(TAG_SIZE)
        if not lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_GET_TAG,
                                       TAG_SIZE, tag):
            raise CipherError("GET_TAG failed")
        return nonce + ct + tag.raw, key
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


def decrypt(blob: bytes, key: bytes) -> bytes:
    """Open nonce||ct||tag; raises CipherError on wrong key or tamper."""
    lib = _crypto()
    if len(key) != KEY_SIZE:
        raise CipherError(f"key must be {KEY_SIZE} bytes")
    if len(blob) < NONCE_SIZE + TAG_SIZE:
        raise CipherError("ciphertext too short")
    nonce = blob[:NONCE_SIZE]
    tag = blob[-TAG_SIZE:]
    ct = blob[NONCE_SIZE:-TAG_SIZE]
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise CipherError("EVP_CIPHER_CTX_new failed")
    try:
        if not lib.EVP_DecryptInit_ex(ctx, lib.EVP_aes_256_gcm(),
                                      None, None, None):
            raise CipherError("DecryptInit(cipher) failed")
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_IVLEN,
                                NONCE_SIZE, None)
        if not lib.EVP_DecryptInit_ex(ctx, None, None, key, nonce):
            raise CipherError("DecryptInit(key) failed")
        out = ctypes.create_string_buffer(len(ct) or 1)
        n = ctypes.c_int(0)
        if not lib.EVP_DecryptUpdate(ctx, out, ctypes.byref(n),
                                     ct, len(ct)):
            raise CipherError("DecryptUpdate failed")
        pt = out.raw[:n.value]
        tag_buf = ctypes.create_string_buffer(tag, TAG_SIZE)
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_GCM_SET_TAG,
                                TAG_SIZE, tag_buf)
        fin = ctypes.create_string_buffer(TAG_SIZE)
        if lib.EVP_DecryptFinal_ex(ctx, fin, ctypes.byref(n)) <= 0:
            raise CipherError("decryption failed: bad key or "
                              "tampered ciphertext")
        return pt + fin.raw[:n.value]
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


# -- RS256 (service-account JWT signing) -------------------------------------
#
# Google service accounts authenticate with an RS256-signed JWT grant
# (the Pub/Sub notification queue needs one); the RSA-SHA256 primitive
# comes from the same libcrypto the AES path uses.

def _crypto_rsa():
    lib = _crypto()
    if getattr(lib, "_rsa_ready", False):
        return lib
    lib.BIO_new_mem_buf.restype = ctypes.c_void_p
    lib.BIO_new_mem_buf.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.BIO_free.argtypes = [ctypes.c_void_p]
    lib.PEM_read_bio_PrivateKey.restype = ctypes.c_void_p
    lib.PEM_read_bio_PrivateKey.argtypes = [ctypes.c_void_p] + \
        [ctypes.c_void_p] * 3
    lib.PEM_read_bio_PUBKEY.restype = ctypes.c_void_p
    lib.PEM_read_bio_PUBKEY.argtypes = [ctypes.c_void_p] + \
        [ctypes.c_void_p] * 3
    lib.EVP_PKEY_free.argtypes = [ctypes.c_void_p]
    lib.EVP_MD_CTX_new.restype = ctypes.c_void_p
    lib.EVP_MD_CTX_free.argtypes = [ctypes.c_void_p]
    lib.EVP_sha256.restype = ctypes.c_void_p
    lib.EVP_DigestSignInit.argtypes = [ctypes.c_void_p] * 5
    lib.EVP_DigestSign.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p,
        ctypes.c_size_t]
    lib.EVP_DigestVerifyInit.argtypes = [ctypes.c_void_p] * 5
    lib.EVP_DigestVerify.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    lib._rsa_ready = True
    return lib


def _load_pem(pem: bytes, public: bool):
    lib = _crypto_rsa()
    bio = lib.BIO_new_mem_buf(pem, len(pem))
    if not bio:
        raise CipherError("BIO_new_mem_buf failed")
    try:
        fn = lib.PEM_read_bio_PUBKEY if public \
            else lib.PEM_read_bio_PrivateKey
        pkey = fn(bio, None, None, None)
        if not pkey:
            raise CipherError("could not parse PEM key")
        return pkey
    finally:
        lib.BIO_free(bio)


def rs256_sign(pem_private_key: bytes, data: bytes) -> bytes:
    """RSASSA-PKCS1-v1_5 over SHA-256 (JWT alg RS256)."""
    lib = _crypto_rsa()
    pkey = _load_pem(pem_private_key, public=False)
    ctx = lib.EVP_MD_CTX_new()
    try:
        if lib.EVP_DigestSignInit(ctx, None, lib.EVP_sha256(),
                                  None, pkey) != 1:
            raise CipherError("DigestSignInit failed")
        n = ctypes.c_size_t(0)
        if lib.EVP_DigestSign(ctx, None, ctypes.byref(n),
                              data, len(data)) != 1:
            raise CipherError("DigestSign(size) failed")
        sig = ctypes.create_string_buffer(n.value)
        if lib.EVP_DigestSign(ctx, sig, ctypes.byref(n),
                              data, len(data)) != 1:
            raise CipherError("DigestSign failed")
        return sig.raw[:n.value]
    finally:
        lib.EVP_MD_CTX_free(ctx)
        lib.EVP_PKEY_free(pkey)


def rs256_verify(pem_public_key: bytes, data: bytes,
                 signature: bytes) -> bool:
    lib = _crypto_rsa()
    pkey = _load_pem(pem_public_key, public=True)
    ctx = lib.EVP_MD_CTX_new()
    try:
        if lib.EVP_DigestVerifyInit(ctx, None, lib.EVP_sha256(),
                                    None, pkey) != 1:
            raise CipherError("DigestVerifyInit failed")
        return lib.EVP_DigestVerify(ctx, signature, len(signature),
                                    data, len(data)) == 1
    finally:
        lib.EVP_MD_CTX_free(ctx)
        lib.EVP_PKEY_free(pkey)
