"""JAX environment helpers.

`force_cpu()` pins this process to the virtual-CPU backend and, crucially,
unregisters the `axon` TPU PJRT plugin that the environment's sitecustomize
installs at interpreter startup.  Without this, *any* jax API call dials
the TPU tunnel — which serializes every process on the single chip grant
(and hangs outright while another process holds it).  Tools, tests, and
CLI paths that don't need the chip must call this before first jax use.
"""

from __future__ import annotations

import os


def force_cpu(device_count: int = 8) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{device_count}").strip()
    try:
        import jax
        import jax._src.xla_bridge as xb

        xb._backend_factories.pop("axon", None)
        # sitecustomize may have imported jax already, latching the
        # platform config; point it back at cpu.
        jax.config.update("jax_platforms", "cpu")
        # If a backend was ALREADY initialized (e.g. the driver ran the
        # single-chip entry() compile check first), the device count is
        # latched at 1 — drop the live backends so the next query
        # re-initializes with the forced CPU mesh.  Only when one
        # exists: querying devices() here would otherwise force eager
        # XLA client startup in every process that calls force_cpu()
        # defensively.
        if getattr(xb, "_backends", None) and \
                len(jax.devices()) < device_count:
            import jax.extend.backend as jeb
            jeb.clear_backends()
    except Exception:
        pass


def on_tpu() -> bool:
    try:
        import jax
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
