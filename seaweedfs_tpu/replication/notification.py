"""Notification queues: the input side of one-way replication.

Reference: weed/notification/ (pluggable message queues — kafka, AWS SQS,
GCP Pub/Sub, gocdk) feeding weed/replication/sub/.  The filer publishes
every meta event to the configured queue; `filer.replicate` consumes the
queue and drives sinks.

Kafka/SQS/PubSub need network egress + SDKs, so here the in-process
MemoryQueue and the durable FileQueue (JSONL spool, resumable by offset)
are real, and the cloud queues are registry stubs behind the same
interface.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable


class NotificationQueue:
    def publish(self, key: str, message: dict) -> None:
        raise NotImplementedError

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        """Deliver queued messages to fn(key, message); returns when the
        queue is drained (poll-style consumption)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryQueue(NotificationQueue):
    def __init__(self) -> None:
        self._items: list[tuple[str, dict]] = []
        self._lock = threading.Lock()

    def publish(self, key: str, message: dict) -> None:
        with self._lock:
            self._items.append((key, message))

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        while True:
            with self._lock:
                if not self._items:
                    return
                key, msg = self._items.pop(0)
            fn(key, msg)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class FileQueue(NotificationQueue):
    """Durable JSONL spool with a persisted consumer offset — survives
    producer/consumer restarts, like an SQS queue with checkpointing."""

    def __init__(self, path: str):
        self.path = path
        self.offset_path = path + ".offset"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def publish(self, key: str, message: dict) -> None:
        line = json.dumps({"key": key, "message": message},
                          separators=(",", ":")) + "\n"
        with self._lock, open(self.path, "a") as f:
            f.write(line)
            f.flush()

    def _offset(self) -> int:
        try:
            with open(self.offset_path) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        if not os.path.exists(self.path):
            return
        pos = self._offset()
        with open(self.path) as f:
            f.seek(pos)
            while True:
                line = f.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    break  # partial write; retry next consume
                try:
                    item = json.loads(line)
                except json.JSONDecodeError:
                    continue
                fn(item["key"], item["message"])
                pos = f.tell()
                # Checkpoint after each successful delivery: at-least-
                # once on crash, never skipping an undelivered message.
                with open(self.offset_path, "w") as of:
                    of.write(str(pos))


_STUB_QUEUES = ("kafka", "sqs", "pubsub", "gocdk")


def queue_for_spec(spec: str) -> NotificationQueue:
    """'memory://', 'file:///path/spool.jsonl'."""
    scheme, _, rest = spec.partition("://")
    if scheme == "memory":
        return MemoryQueue()
    if scheme == "file":
        return FileQueue("/" + rest.lstrip("/"))
    if scheme in _STUB_QUEUES:
        raise NotImplementedError(
            f"{scheme} queue needs a broker SDK + egress; add it behind "
            f"NotificationQueue (see weed/notification/{scheme})")
    raise ValueError(f"unknown queue spec: {spec}")
