"""Notification queues: the input side of one-way replication.

Reference: weed/notification/ (pluggable message queues — kafka, AWS SQS,
GCP Pub/Sub, gocdk) feeding weed/replication/sub/.  The filer publishes
every meta event to the configured queue; `filer.replicate` consumes the
queue and drives sinks.

The in-process MemoryQueue and the durable FileQueue (JSONL spool,
resumable by offset) are always available; SqsQueue speaks the real AWS
SQS query API with stdlib HTTP + the in-repo sig v4 signer (no SDK —
weed/notification/aws_sqs/aws_sqs_pub.go), KafkaQueue (kafka.py)
speaks the Kafka wire protocol directly over TCP, and PubSubQueue
(pubsub.py) speaks the Pub/Sub REST API with RS256 service-account
auth from libcrypto — all three broker queues are real.  gocdk, the
reference's Go-Cloud-Development-Kit portability shim over those same
brokers, stays a registry stub (it is Go-ecosystem glue, not a broker).
"""

from __future__ import annotations

import json
import os
import queue as _queue
import threading
import time
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Callable


class NotificationQueue:
    def publish(self, key: str, message: dict) -> None:
        raise NotImplementedError

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        """Deliver queued messages to fn(key, message); returns when the
        queue is drained (poll-style consumption)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryQueue(NotificationQueue):
    def __init__(self) -> None:
        self._items: list[tuple[str, dict]] = []
        self._lock = threading.Lock()

    def publish(self, key: str, message: dict) -> None:
        with self._lock:
            self._items.append((key, message))

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        while True:
            with self._lock:
                if not self._items:
                    return
                key, msg = self._items.pop(0)
            fn(key, msg)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class FileQueue(NotificationQueue):
    """Durable JSONL spool with a persisted consumer offset — survives
    producer/consumer restarts, like an SQS queue with checkpointing."""

    def __init__(self, path: str):
        self.path = path
        self.offset_path = path + ".offset"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()

    def publish(self, key: str, message: dict) -> None:
        line = json.dumps({"key": key, "message": message},
                          separators=(",", ":")) + "\n"
        with self._lock, open(self.path, "a") as f:
            f.write(line)
            f.flush()

    def _offset(self) -> int:
        try:
            with open(self.offset_path) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        if not os.path.exists(self.path):
            return
        pos = self._offset()
        with open(self.path) as f:
            f.seek(pos)
            while True:
                line = f.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    break  # partial write; retry next consume
                try:
                    item = json.loads(line)
                except json.JSONDecodeError:
                    continue
                fn(item["key"], item["message"])
                pos = f.tell()
                # Checkpoint after each successful delivery: at-least-
                # once on crash, never skipping an undelivered message.
                with open(self.offset_path, "w") as of:
                    of.write(str(pos))


def _xml_findall(root, tag: str):
    """Namespace-agnostic element search (SQS responses carry the
    doc namespace; a fake test endpoint may not)."""
    return [el for el in root.iter() if el.tag.split("}")[-1] == tag]



class _SendSpool:
    """Bounded in-order send spool drained by one daemon thread.

    publish() must never block the caller on the network: the filer
    publishes under its meta-log lock, so a slow endpoint would stall
    every namespace mutation.  Past the bound, events are dropped (with
    a counter) rather than backpressuring the filer — the durable
    FileQueue is the right choice when loss is unacceptable.

    close() is terminal: the sender drains-and-discards whatever
    remains and exits within ~1s (a sentinel would block put() forever
    on a full spool), and later put()s are counted as dropped.  Every
    get() is matched by task_done(), so flush()'s join() can never
    deadlock — including flush() after close().
    """

    MAX = 65536

    def __init__(self, send: Callable, name: str, maxsize: int = MAX):
        self._send = send
        self._name = name
        self.dropped = 0
        self._q: "_queue.Queue" = _queue.Queue(maxsize=maxsize)
        self._sender: threading.Thread | None = None
        self._lock = threading.Lock()
        self._closed = threading.Event()

    def put(self, item) -> None:
        if self._closed.is_set():
            self.dropped += 1  # closed is terminal: drop, don't
            return             # respawn a sender per late event
        self._ensure_sender()
        try:
            self._q.put_nowait(item)
        except _queue.Full:
            self.dropped += 1

    def _ensure_sender(self) -> None:
        with self._lock:
            if self._sender is None or not self._sender.is_alive():
                self._sender = threading.Thread(
                    target=self._loop, daemon=True, name=self._name)
                self._sender.start()

    def _loop(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=1.0)
            except _queue.Empty:
                if self._closed.is_set():
                    return
                continue
            try:
                if self._closed.is_set():
                    # close() already gave up waiting: discard instead
                    # of spending up to 70s per event on a dead
                    # endpoint, so the thread (and the spool it pins)
                    # actually terminates.
                    self.dropped += 1
                else:
                    self._send(item)
            except Exception:  # noqa: BLE001 — a dead endpoint drops
                self.dropped += 1  # the event; never wedges the loop
            finally:
                self._q.task_done()

    def flush(self, timeout: float | None = None) -> None:
        """Block until every spooled publish has been attempted (tests,
        graceful shutdown).  `timeout` bounds the wait.

        Waits on the queue's all_tasks_done condition directly instead
        of spawning a join() helper thread: a timed-out flush must not
        pin a thread (plus the spool it references) until the sends
        eventually finish — which on a dead endpoint is never."""
        q = self._q
        with q.all_tasks_done:
            if timeout is None:
                while q.unfinished_tasks:
                    q.all_tasks_done.wait()
                return
            deadline = time.monotonic() + timeout
            while q.unfinished_tasks:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                q.all_tasks_done.wait(remaining)

    def close(self) -> None:
        if self._sender is not None and self._sender.is_alive():
            self.flush(timeout=5.0)
        self._closed.set()


class SqsQueue(NotificationQueue):
    """AWS SQS over its HTTP query API — stdlib urllib + the in-repo
    sig v4 signer, no SDK (weed/notification/aws_sqs).

    Messages carry the same JSON envelope as FileQueue:
    {"key": ..., "message": ...} so the replicate worker is
    queue-agnostic.  consume() drains with short-poll ReceiveMessage
    batches and deletes each message only after fn() returns —
    at-least-once, like the reference's sqs consumer."""

    API_VERSION = "2012-11-05"

    def __init__(self, queue_url: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 wait_seconds: int = 0):
        self.queue_url = queue_url.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.wait_seconds = wait_seconds
        self._spool = _SendSpool(self._call, "sqs-sender")

    @property
    def dropped(self) -> int:
        return self._spool.dropped

    def _call(self, params: dict) -> ET.Element:
        body = urllib.parse.urlencode(
            {**params, "Version": self.API_VERSION}).encode()
        headers = {"Content-Type":
                   "application/x-www-form-urlencoded"}
        if self.access_key:
            from ..s3api.sigv4 import sign_request
            headers = sign_request("POST", self.queue_url, headers,
                                   body, self.access_key,
                                   self.secret_key, region=self.region,
                                   service="sqs")
        req = urllib.request.Request(self.queue_url, data=body,
                                     method="POST", headers=headers)
        with urllib.request.urlopen(req, timeout=70) as resp:
            return ET.fromstring(resp.read() or b"<empty/>")

    def publish(self, key: str, message: dict) -> None:
        self._spool.put({
            "Action": "SendMessage",
            "MessageBody": json.dumps({"key": key, "message": message},
                                      separators=(",", ":"))})

    def flush(self, timeout: float | None = None) -> None:
        self._spool.flush(timeout)

    def close(self) -> None:
        self._spool.close()

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        # Short polling (wait_seconds=0) samples a subset of SQS
        # backend hosts and can return empty while messages remain, so
        # "drained" needs consecutive empty receives; one empty long
        # poll is already authoritative.
        drained_after = 1 if self.wait_seconds > 0 else 3
        empty = 0
        while True:
            root = self._call({"Action": "ReceiveMessage",
                               "MaxNumberOfMessages": "10",
                               "WaitTimeSeconds":
                               str(self.wait_seconds)})
            messages = _xml_findall(root, "Message")
            if not messages:
                empty += 1
                if empty >= drained_after:
                    return
                continue
            empty = 0
            for msg in messages:
                bodies = _xml_findall(msg, "Body")
                handles = _xml_findall(msg, "ReceiptHandle")
                if not bodies or not handles:
                    continue
                try:
                    item = json.loads(bodies[0].text or "")
                except json.JSONDecodeError:
                    item = None
                # Anything not carrying our {key, message} envelope is
                # a poison message (foreign publisher on the same
                # queue): deliver nothing but still delete, or it
                # reappears after the visibility timeout and wedges
                # every future consume() on the same crash.
                if isinstance(item, dict) and "key" in item \
                        and "message" in item:
                    fn(item["key"], item["message"])
                # Delete AFTER delivery: a crash mid-fn redelivers
                # (at-least-once), never drops.
                self._call({"Action": "DeleteMessage",
                            "ReceiptHandle": handles[0].text or ""})


_STUB_QUEUES = ("gocdk",)


def queue_for_spec(spec: str, **kw) -> NotificationQueue:
    """'memory://', 'file:///path/spool.jsonl',
    'kafka://broker:9092/topic',
    'sqs://sqs.us-east-1.amazonaws.com/123456789012/queue' (keyword
    args: access_key/secret_key/region; http_endpoint=True for a
    plain-http test endpoint)."""
    scheme, _, rest = spec.partition("://")
    if scheme == "memory":
        return MemoryQueue()
    if scheme == "file":
        return FileQueue("/" + rest.lstrip("/"))
    if scheme == "kafka":
        bootstrap, _, topic = rest.partition("/")
        from .kafka import KafkaQueue
        return KafkaQueue(bootstrap, topic or "seaweedfs", **kw)
    if scheme == "sqs":
        proto = "http" if kw.pop("http_endpoint", False) else "https"
        return SqsQueue(f"{proto}://{rest}", **kw)
    if scheme == "pubsub":
        project, _, topic = rest.partition("/")
        from .pubsub import PubSubQueue
        return PubSubQueue(project, topic or "seaweedfs", **kw)
    if scheme in _STUB_QUEUES:
        raise NotImplementedError(
            f"{scheme} queue is a registry stub; add it behind "
            f"NotificationQueue (see weed/notification/{scheme})")
    raise ValueError(f"unknown queue spec: {spec}")


class AsyncPublisher(NotificationQueue):
    """Decorator that takes publish() off the caller's thread: a
    networked queue (Kafka TCP, Pub/Sub HTTP) rides a _SendSpool so it
    never blocks the filer's meta-log lock.  consume()/close()
    delegate to the inner queue.  (SqsQueue carries its own spool.)"""

    def __init__(self, inner: NotificationQueue):
        self.inner = inner
        self._spool = _SendSpool(
            lambda item: self.inner.publish(*item), "notify-sender")

    @property
    def dropped(self) -> int:
        return self._spool.dropped

    def publish(self, key: str, message: dict) -> None:
        self._spool.put((key, message))

    def flush(self, timeout: float | None = None) -> None:
        self._spool.flush(timeout)

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        self.inner.consume(fn)

    def close(self) -> None:
        self._spool.close()
        self.inner.close()


class LogQueue(NotificationQueue):
    """notification.log: events go to the process log — the reference's
    debugging sink (weed/notification/log/log_queue.go).  consume() is
    a no-op drain; nothing is stored."""

    def publish(self, key: str, message: dict) -> None:
        from ..utils import glog
        glog.infof("notify %s: %s", key,
                   json.dumps(message, separators=(",", ":"))[:512])

    def consume(self, fn: Callable[[str, dict], None]) -> None:
        return


def queue_from_config(cfg) -> NotificationQueue | None:
    """Build the filer's notification queue from notification.toml
    (weed/notification/configuration.go LoadConfiguration: the first
    `enabled = true` section wins)."""
    if cfg is None:
        return None
    if cfg.get_bool("notification.file_queue.enabled"):
        d = cfg.get_string("notification.file_queue.dir",
                           "/tmp/weed_notify")
        return FileQueue(os.path.join(d, "events.jsonl"))
    if cfg.get_bool("notification.kafka.enabled"):
        from .kafka import KafkaQueue
        return AsyncPublisher(KafkaQueue(
            cfg.get_string("notification.kafka.hosts",
                           "localhost:9092").split(",")[0],
            cfg.get_string("notification.kafka.topic", "seaweedfs")))
    if cfg.get_bool("notification.aws_sqs.enabled"):
        return SqsQueue(
            cfg.get_string("notification.aws_sqs.sqs_queue_url"),
            access_key=cfg.get_string(
                "notification.aws_sqs.aws_access_key_id"),
            secret_key=cfg.get_string(
                "notification.aws_sqs.aws_secret_access_key"),
            region=cfg.get_string("notification.aws_sqs.region",
                                  "us-east-1"))
    if cfg.get_bool("notification.google_pub_sub.enabled"):
        from .pubsub import PubSubQueue
        sa = None
        creds = cfg.get_string(
            "notification.google_pub_sub.google_application_credentials")
        if creds:
            with open(creds) as f:
                sa = json.load(f)
        kw = {}
        endpoint = cfg.get_string(
            "notification.google_pub_sub.endpoint")
        if endpoint:
            kw["endpoint"] = endpoint
        return AsyncPublisher(PubSubQueue(
            cfg.get_string("notification.google_pub_sub.project_id"),
            cfg.get_string("notification.google_pub_sub.topic",
                           "seaweedfs"),
            subscription=cfg.get_string(
                "notification.google_pub_sub.subscription", ""),
            service_account=sa, **kw))
    if cfg.get_bool("notification.log.enabled"):
        return LogQueue()
    return None
