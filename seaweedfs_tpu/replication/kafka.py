"""Kafka notification queue speaking the real wire protocol — no SDK.

Reference: weed/notification/kafka (sarama producer) and
weed/replication/sub/notification_kafka.go (consumer).  This build talks
to brokers directly over TCP with stdlib sockets: Metadata v1 to find
the partition leader, Produce v3 and Fetch v4 carrying record batches in
the **v2 (magic=2) format** every broker since 0.11 speaks — varint
record framing, CRC32-C over the batch body (the same Castagnoli core
the needle codec uses, core/crc.py).

Scope: one topic, all partitions (leaders discovered per partition),
no consumer groups — the `NotificationQueue.consume` contract is
poll-drain from checkpointed per-partition offsets, which maps to plain
Fetch (the reference's kafka consumer also tracks its own offsets in a
progress file rather than committing group offsets).

QUARANTINED: nothing in the tree constructs this queue outside
`queue_for_spec("kafka://...")` — cross-cluster disaster recovery now
rides the volume-level change-log shipper (rlog.py + shipper.py), not
a broker.  Kept (with its wire-protocol tests) for operators who feed
filer events into an existing Kafka estate; the public surface is
pinned by `__all__` below and everything else is implementation detail
that may change or be removed.
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading
import time

from ..core.crc import crc32c
from .notification import NotificationQueue

__all__ = ["KafkaQueue", "encode_record_batch", "decode_record_batches"]

_CLIENT_ID = "seaweedfs-tpu"


# -- wire primitives --------------------------------------------------------

def _w_i8(b: bytearray, v: int) -> None:
    b += struct.pack(">b", v)


def _w_i16(b: bytearray, v: int) -> None:
    b += struct.pack(">h", v)


def _w_i32(b: bytearray, v: int) -> None:
    b += struct.pack(">i", v)


def _w_i64(b: bytearray, v: int) -> None:
    b += struct.pack(">q", v)


def _w_str(b: bytearray, s: str | None) -> None:
    if s is None:
        _w_i16(b, -1)
        return
    raw = s.encode()
    _w_i16(b, len(raw))
    b += raw


def _w_bytes(b: bytearray, raw: bytes | None) -> None:
    if raw is None:
        _w_i32(b, -1)
        return
    _w_i32(b, len(raw))
    b += raw


def _w_varint(b: bytearray, v: int) -> None:
    """Zigzag varint (record framing)."""
    u = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    while True:
        if u < 0x80:
            b.append(u)
            return
        b.append((u & 0x7F) | 0x80)
        u >>= 7


class _Reader:
    def __init__(self, data: bytes):
        self.b = io.BytesIO(data)

    def i8(self) -> int:
        return struct.unpack(">b", self.b.read(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.b.read(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.b.read(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.b.read(8))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self.b.read(n).decode()

    def raw(self, n: int) -> bytes:
        return self.b.read(n)

    def nbytes(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.b.read(n)

    def varint(self) -> int:
        u = shift = 0
        while True:
            c = self.b.read(1)[0]
            u |= (c & 0x7F) << shift
            if not c & 0x80:
                break
            shift += 7
        return (u >> 1) ^ -(u & 1)

    def remaining(self) -> int:
        pos = self.b.tell()
        self.b.seek(0, io.SEEK_END)
        end = self.b.tell()
        self.b.seek(pos)
        return end - pos


# -- record batch v2 --------------------------------------------------------

def encode_record_batch(records: list[tuple[bytes | None, bytes]],
                        base_ts_ms: int = 0) -> bytes:
    """Encode (key, value) pairs as one magic=2 record batch."""
    body = bytearray()  # everything covered by the CRC
    _w_i16(body, 0)                   # attributes: no compression
    _w_i32(body, len(records) - 1)    # lastOffsetDelta
    _w_i64(body, base_ts_ms)          # baseTimestamp
    _w_i64(body, base_ts_ms)          # maxTimestamp
    _w_i64(body, -1)                  # producerId
    _w_i16(body, -1)                  # producerEpoch
    _w_i32(body, -1)                  # baseSequence
    _w_i32(body, len(records))
    for i, (key, value) in enumerate(records):
        rec = bytearray()
        _w_i8(rec, 0)                 # record attributes
        _w_varint(rec, 0)             # timestampDelta
        _w_varint(rec, i)             # offsetDelta
        if key is None:
            _w_varint(rec, -1)
        else:
            _w_varint(rec, len(key))
            rec += key
        _w_varint(rec, len(value))
        rec += value
        _w_varint(rec, 0)             # headers count
        _w_varint(body, len(rec))
        body += rec
    out = bytearray()
    _w_i64(out, 0)                          # baseOffset (broker assigns)
    _w_i32(out, 4 + 1 + 4 + len(body))      # batchLength (after this field)
    _w_i32(out, -1)                         # partitionLeaderEpoch
    _w_i8(out, 2)                           # magic
    out += struct.pack(">I", crc32c(bytes(body)))  # CRC32-C of body
    out += body
    return bytes(out)


def decode_record_batches(buf: bytes,
                          verify_crc: bool = True
                          ) -> list[tuple[int, bytes | None, bytes]]:
    """Parse concatenated magic=2 batches -> [(offset, key, value)].
    A trailing partial batch (Fetch may truncate at max_bytes) is
    ignored, matching broker-client convention."""
    out: list[tuple[int, bytes | None, bytes]] = []
    r = _Reader(buf)
    while r.remaining() >= 12:
        base_offset = r.i64()
        batch_len = r.i32()
        if r.remaining() < batch_len:
            break  # truncated tail
        batch = _Reader(r.raw(batch_len))
        batch.i32()               # partitionLeaderEpoch
        magic = batch.i8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        crc = batch.i32() & 0xFFFFFFFF
        body = batch.raw(batch.remaining())
        if verify_crc and crc32c(body) != crc:
            raise ValueError("record batch CRC mismatch")
        br = _Reader(body)
        attrs = br.i16()
        codec = attrs & 0x07
        if codec not in (0, 1):
            # snappy/lz4/zstd need non-stdlib codecs: fail loudly
            # instead of parsing a compressed blob as varint framing.
            raise ValueError(
                f"compressed record batch (codec {codec}) unsupported")
        br.i32()                  # lastOffsetDelta
        br.i64()                  # baseTimestamp
        br.i64()                  # maxTimestamp
        br.i64()                  # producerId
        br.i16()                  # producerEpoch
        br.i32()                  # baseSequence
        n = br.i32()
        if codec == 1:
            # gzip: the records area (after the plaintext count) is one
            # compressed blob (KIP-98); stdlib covers it.
            import gzip as _gzip
            br = _Reader(_gzip.decompress(br.raw(br.remaining())))
        for _ in range(n):
            rec_len = br.varint()
            rr = _Reader(br.raw(rec_len))
            rr.i8()               # attributes
            rr.varint()           # timestampDelta
            off_delta = rr.varint()
            klen = rr.varint()
            key = None if klen < 0 else rr.raw(klen)
            vlen = rr.varint()
            # vlen < 0 is a tombstone (compacted-topic delete): raw(-1)
            # would slurp the rest of the record as the "value".
            value = None if vlen < 0 else rr.raw(vlen)
            # headers skipped (count then pairs) — we produce none and
            # ignore any a foreign producer added
            out.append((base_offset + off_delta, key, value))
    return out


# -- broker connection ------------------------------------------------------

class _Broker:
    """One TCP connection; request framing + correlation ids."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout)
        self.sock.settimeout(timeout)
        self._corr = 0
        self._lock = threading.Lock()

    def call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            head = bytearray()
            _w_i16(head, api_key)
            _w_i16(head, api_version)
            _w_i32(head, corr)
            _w_str(head, _CLIENT_ID)
            msg = bytes(head) + body
            self.sock.sendall(struct.pack(">i", len(msg)) + msg)
            raw = self._read_n(4)
            (size,) = struct.unpack(">i", raw)
            resp = self._read_n(size)
        r = _Reader(resp)
        got = r.i32()
        if got != corr:
            raise ValueError(f"correlation id mismatch {got} != {corr}")
        return r

    def _read_n(self, n: int) -> bytes:
        out = b""
        while len(out) < n:
            piece = self.sock.recv(n - len(out))
            if not piece:
                raise ConnectionError("broker closed connection")
            out += piece
        return out

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class KafkaQueue(NotificationQueue):
    """Publish/consume the {key, message} envelope on one Kafka topic.

    Partitions are discovered from Metadata and ALL are consumed (the
    reference's sarama consumer does the same); produces are routed by
    CRC32-C of the key so per-path ordering holds, like sarama's hash
    partitioner.  consume() drains each partition from locally-tracked
    offsets (checkpointed to `offset_path` as JSON after each drained
    batch, like the reference's progress file) — at-least-once, no
    consumer groups.  Pass `partition` to pin a single partition."""

    API_PRODUCE, API_FETCH, API_LIST_OFFSETS, API_METADATA = 0, 1, 2, 3
    ERR_OFFSET_OUT_OF_RANGE = 1

    def __init__(self, bootstrap: str, topic: str,
                 partition: int | None = None,
                 offset_path: str | None = None,
                 timeout: float = 10.0):
        host, _, port = bootstrap.partition(":")
        self.topic = topic
        self.pinned = partition
        self.timeout = timeout
        self.offset_path = offset_path
        self._offsets: dict[int, int] = self._load_offsets()
        self._bootstrap = (host, int(port or 9092))
        self._conns: dict[tuple, _Broker] = {}
        self._leaders: dict[int, tuple] = {}   # pid -> (host, port)
        self._lock = threading.Lock()

    # -- offsets ------------------------------------------------------------

    def _load_offsets(self) -> dict[int, int]:
        if not self.offset_path:
            return {}
        try:
            with open(self.offset_path) as f:
                raw = f.read().strip()
        except OSError:
            return {}
        if not raw:
            return {}
        try:
            doc = json.loads(raw)
            if isinstance(doc, dict):
                return {int(k): int(v) for k, v in doc.items()}
        except (json.JSONDecodeError, ValueError):
            pass
        try:  # legacy single-int checkpoint (partition 0)
            return {0: int(raw)}
        except ValueError:
            return {}

    def _save_offsets(self) -> None:
        if self.offset_path:
            with open(self.offset_path, "w") as f:
                json.dump({str(k): v for k, v in self._offsets.items()},
                          f)

    # -- connection / metadata ---------------------------------------------

    def _refresh_metadata(self) -> None:
        """Metadata v1: partition list + per-partition leader addrs."""
        boot = _Broker(*self._bootstrap, timeout=self.timeout)
        try:
            body = bytearray()
            _w_i32(body, 1)
            _w_str(body, self.topic)
            r = boot.call(self.API_METADATA, 1, bytes(body))
            brokers = {}
            for _ in range(r.i32()):
                node = r.i32()
                bhost = r.string()
                bport = r.i32()
                r.string()  # rack
                brokers[node] = (bhost, bport)
            r.i32()      # controller id
            leaders: dict[int, tuple] = {}
            for _ in range(r.i32()):      # topics
                r.i16()                   # topic error
                r.string()                # name
                r.i8()                    # is_internal
                for _ in range(r.i32()):  # partitions
                    r.i16()               # partition error
                    pid = r.i32()
                    leader = r.i32()
                    for _ in range(r.i32()):
                        r.i32()           # replicas
                    for _ in range(r.i32()):
                        r.i32()           # isr
                    if leader in brokers:
                        leaders[pid] = brokers[leader]
        finally:
            boot.close()
        if not leaders:
            raise ConnectionError(f"no leaders for topic {self.topic}")
        self._leaders = leaders

    def _partitions(self) -> list[int]:
        with self._lock:
            if not self._leaders:
                self._refresh_metadata()
            if self.pinned is not None:
                return [self.pinned]
            return sorted(self._leaders)

    def _broker_for(self, pid: int) -> _Broker:
        with self._lock:
            if pid not in self._leaders:
                self._refresh_metadata()
            addr = self._leaders.get(pid)
            if addr is None:
                raise ConnectionError(
                    f"no leader for {self.topic}/{pid}")
            conn = self._conns.get(addr)
            if conn is None:
                conn = self._conns[addr] = _Broker(
                    *addr, timeout=self.timeout)
            return conn

    def _drop_connections(self) -> None:
        """Leadership moved or a conn died: rediscover everything."""
        with self._lock:
            for conn in self._conns.values():
                conn.close()
            self._conns = {}
            self._leaders = {}

    # back-compat aliases used by tests/tools
    _drop_leader = _drop_connections

    # -- NotificationQueue --------------------------------------------------

    def _pick_partition(self, key: str) -> int:
        if self.pinned is not None:
            return self.pinned
        parts = self._partitions()
        return parts[crc32c(key.encode()) % len(parts)]

    def publish(self, key: str, message: dict) -> None:
        value = json.dumps({"key": key, "message": message},
                           separators=(",", ":")).encode()
        # Real CreateTime: a zero timestamp is instantly past any
        # time-based retention window and the broker would reap the
        # segment before consumers see it.
        batch = encode_record_batch([(key.encode(), value)],
                                    base_ts_ms=int(time.time() * 1000))
        pid = self._pick_partition(key)
        body = bytearray()
        _w_str(body, None)            # transactional id (v3+)
        _w_i16(body, -1)              # acks: full ISR
        _w_i32(body, int(self.timeout * 1000))
        _w_i32(body, 1)               # one topic
        _w_str(body, self.topic)
        _w_i32(body, 1)               # one partition
        _w_i32(body, pid)
        _w_bytes(body, batch)
        try:
            r = self._broker_for(pid).call(self.API_PRODUCE, 3,
                                           bytes(body))
        except (OSError, ConnectionError):
            self._drop_connections()  # stale leader: retry once
            r = self._broker_for(pid).call(self.API_PRODUCE, 3,
                                           bytes(body))
        r.i32()                       # topic count
        r.string()
        r.i32()                       # partition count
        r.i32()                       # partition id
        err = r.i16()
        if err:
            self._drop_connections()
            raise ConnectionError(f"kafka produce error code {err}")

    def consume(self, fn) -> None:
        # Round-robin the partitions until a full pass delivers
        # nothing — each partition drains from its own offset.
        while True:
            delivered = False
            for pid in self._partitions():
                delivered |= self._drain_partition(pid, fn)
            if not delivered:
                return

    def _drain_partition(self, pid: int, fn) -> bool:
        delivered = False
        while True:
            body = bytearray()
            _w_i32(body, -1)          # replica id (consumer)
            _w_i32(body, 100)         # max wait ms
            _w_i32(body, 1)           # min bytes
            _w_i32(body, 1 << 25)     # max bytes (v3+)
            _w_i8(body, 0)            # isolation level (v4+)
            _w_i32(body, 1)           # one topic
            _w_str(body, self.topic)
            _w_i32(body, 1)
            _w_i32(body, pid)
            _w_i64(body, self._offsets.get(pid, 0))
            _w_i32(body, 1 << 24)     # partition max bytes
            try:
                r = self._broker_for(pid).call(self.API_FETCH, 4,
                                               bytes(body))
            except (OSError, ConnectionError):
                self._drop_connections()
                r = self._broker_for(pid).call(self.API_FETCH, 4,
                                               bytes(body))
            r.i32()                   # throttle time
            r.i32()                   # topic count
            r.string()
            r.i32()                   # partition count
            r.i32()                   # partition id
            err = r.i16()
            if err == self.ERR_OFFSET_OUT_OF_RANGE:
                # Retention truncated the log below our checkpoint: a
                # permanent raise would wedge the consumer forever, so
                # resume from the earliest retained offset (events in
                # the gap are gone either way — at-least-once, not
                # exactly-once).
                self._offsets[pid] = self._earliest_offset(pid)
                self._save_offsets()
                continue
            if err:
                self._drop_connections()
                raise ConnectionError(f"kafka fetch error code {err}")
            r.i64()                   # high watermark
            r.i64()                   # last stable offset (v4+)
            for _ in range(r.i32()):  # aborted txns (v4+)
                r.i64()
                r.i64()
            records = r.nbytes() or b""
            batch = decode_record_batches(records)
            got = False
            for offset, _key, value in batch:
                if offset < self._offsets.get(pid, 0):
                    continue  # broker returns from batch start
                doc = None
                if value is not None:  # tombstones aren't our envelope
                    try:
                        doc = json.loads(value)
                    except json.JSONDecodeError:
                        pass
                if isinstance(doc, dict) and "key" in doc \
                        and "message" in doc:
                    fn(doc["key"], doc["message"])
                self._offsets[pid] = offset + 1
                got = True
            if not got:
                return delivered
            delivered = True
            # One checkpoint per drained batch: a crash mid-batch
            # redelivers the batch (at-least-once), and the hot loop
            # isn't N file rewrites for N records.
            self._save_offsets()

    def _earliest_offset(self, pid: int) -> int:
        """ListOffsets v1 with timestamp=-2 (earliest)."""
        body = bytearray()
        _w_i32(body, -1)          # replica id
        _w_i32(body, 1)           # one topic
        _w_str(body, self.topic)
        _w_i32(body, 1)
        _w_i32(body, pid)
        _w_i64(body, -2)          # EARLIEST
        r = self._broker_for(pid).call(self.API_LIST_OFFSETS, 1,
                                       bytes(body))
        r.i32()                   # topic count
        r.string()
        r.i32()                   # partition count
        r.i32()                   # partition id
        err = r.i16()
        if err:
            raise ConnectionError(f"kafka list_offsets error {err}")
        r.i64()                   # timestamp
        return r.i64()

    def close(self) -> None:
        self._drop_connections()
