"""Durable per-volume replication change log: the `.rlog` sidecar.

The event journal (events/journal.py) is a bounded in-process ring — it
cannot survive a restart, so it cannot drive disaster recovery.  This
module is the durable change feed cross-cluster mirroring ships from: a
crash-safe append-only sidecar next to the volume's `.dat`, journaled
at the SAME commit points as the needle write (storage/volume.py), so
every acked mutation has a log record and shipping resumes exactly
where it stopped after a kill -9.

One record per committed mutation, fixed size (40 bytes):

    seq u64 | op u8 | pad3 | needle_id u64 | cookie u32 | size u32
    | ts_ns u64 | crc32c u32 (of the preceding 36 bytes)

- `seq` is contiguous and strictly increasing per volume — the
  receiver's idempotency key (with needle_id + cookie) and the unit the
  acked watermark counts in.  Fixed-size records + contiguous seqs make
  seek-by-seq pure arithmetic: no index sidecar for the sidecar.
- `op` is write / delete / vacuum-rewrite.  Deletes are first-class so
  tombstones always propagate (a delete must never resurrect — the
  same rule the PR 4 repair path enforces); vacuum records document a
  log rewrite and keep the seq chain alive across compactions.
- Torn-tail tolerant like the `.dat` recovery (storage/scrub.py): on
  open, a trailing partial record is truncated and CRC-bad trailing
  records are stepped back over — a crash mid-append costs at most the
  unacked tail, never the log.

The remote-acked offset lives in a `.rwm` watermark sidecar (atomic
tmp+rename JSON, the `.qrt` ticket idiom) persisted only AFTER the
standby acknowledged a batch — a shipper restart re-reads it and
resumes from acked+1, re-sending at most one in-flight batch that the
receiver's own applied-seq watermark then no-ops.

Vacuum compaction (storage/vacuum.py) rewrites the log too: the acked
prefix is dropped (those records can never be shipped again) and a
vacuum record is appended so the log is never empty and the next seq
is recoverable from the file alone.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from dataclasses import dataclass

from ..core.crc import crc32c

# seq, op, pad3, needle_id, cookie, size, ts_ns  (+ trailing crc32c u32)
_REC = struct.Struct(">QB3xQIIQ")
_CRC = struct.Struct(">I")
RECORD_SIZE = _REC.size + _CRC.size  # 40

OP_WRITE, OP_DELETE, OP_VACUUM = 1, 2, 3
OP_NAMES = {OP_WRITE: "write", OP_DELETE: "delete", OP_VACUUM: "vacuum"}


@dataclass(frozen=True)
class LogRecord:
    seq: int
    op: int
    needle_id: int
    cookie: int
    size: int
    ts_ns: int

    def to_bytes(self) -> bytes:
        head = _REC.pack(self.seq, self.op, self.needle_id,
                         self.cookie, self.size, self.ts_ns)
        return head + _CRC.pack(crc32c(head))

    @classmethod
    def from_bytes(cls, buf: bytes) -> "LogRecord | None":
        """Parse one record; None when the CRC disagrees (torn tail)."""
        if len(buf) < RECORD_SIZE:
            return None
        head = buf[:_REC.size]
        if crc32c(head) != _CRC.unpack_from(buf, _REC.size)[0]:
            return None
        seq, op, needle_id, cookie, size, ts_ns = _REC.unpack(head)
        return cls(seq, op, needle_id, cookie, size, ts_ns)


class Watermark:
    """Durable monotonic seq checkpoint (atomic tmp+rename JSON).

    Used on both ends of the wire: `.rwm` on the primary records the
    highest seq the standby ACKED (persisted only after the ack, so a
    crash re-ships rather than skips), `.rap` on the standby records
    the highest seq APPLIED (persisted before the ack, so a replayed
    batch is a no-op instead of a resurrection)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        try:
            with open(path) as f:
                self._value = int(json.load(f).get("seq", 0))
        except (OSError, ValueError):
            self._value = 0

    @property
    def value(self) -> int:
        return self._value

    def set(self, seq: int) -> None:
        """Advance (never regress) and persist durably."""
        with self._lock:
            if seq <= self._value:
                return
            self._value = seq
            tmp = f"{self.path}.{os.getpid()}.tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump({"seq": seq}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
            except OSError:
                pass  # re-ship on restart, never skip

    def remove(self) -> None:
        with self._lock:
            self._value = 0
            try:
                os.remove(self.path)
            except OSError:
                pass


class ReplicationLog:
    """The append-only `.rlog` + its `.rwm` acked watermark.

    Thread-safe; append() is called from inside the volume's locked
    commit sections while read_from()/set_acked() run on the shipper
    daemon thread."""

    OP_WRITE, OP_DELETE, OP_VACUUM = OP_WRITE, OP_DELETE, OP_VACUUM

    def __init__(self, base: str):
        self.path = base + ".rlog"
        self.watermark = Watermark(base + ".rwm")
        self._lock = threading.Lock()
        self.first_seq = 0  # seq of the record at file offset 0
        self.last_seq = 0
        self._open_recovered()

    # -- crash-safe open ----------------------------------------------------

    def _open_recovered(self) -> None:
        """Open the log, truncating a torn tail like the .dat recovery:
        drop a trailing partial record, then step back over CRC-bad
        trailing records until a good one (or the head) is reached."""
        mode = "r+b" if os.path.exists(self.path) else "w+b"
        self._f = open(self.path, mode)
        self._f.seek(0, os.SEEK_END)
        keep = self._f.tell() - self._f.tell() % RECORD_SIZE
        while keep > 0:
            self._f.seek(keep - RECORD_SIZE)
            if LogRecord.from_bytes(self._f.read(RECORD_SIZE)) is not None:
                break
            keep -= RECORD_SIZE
        self._f.truncate(keep)
        if keep:
            self._f.seek(0)
            head = LogRecord.from_bytes(self._f.read(RECORD_SIZE))
            self._f.seek(keep - RECORD_SIZE)
            tail = LogRecord.from_bytes(self._f.read(RECORD_SIZE))
            if head is None or tail is None:
                # A rotten head breaks seq arithmetic for the whole
                # file: reset, resuming the seq chain from the acked
                # watermark (unacked tail records are lost, which the
                # shipper surfaces as a gap it cannot re-ship — the
                # same contract as losing the disk they lived on).
                self._f.truncate(0)
                self.first_seq = self.last_seq = 0
            else:
                self.first_seq, self.last_seq = head.seq, tail.seq
        self._f.seek(0, os.SEEK_END)
        if self.last_seq == 0:
            self.last_seq = self.watermark.value

    # -- append (volume commit points) --------------------------------------

    def append(self, op: int, needle_id: int, cookie: int,
               size: int, ts_ns: int | None = None) -> int:
        """Journal one committed mutation; returns its seq.  Flushes to
        the OS (like the .dat write path); call sync() for the fsync'd
        commit points."""
        if ts_ns is None:
            import time
            ts_ns = time.time_ns()
        with self._lock:
            seq = self.last_seq + 1
            rec = LogRecord(seq, op, needle_id, cookie, size, ts_ns)
            self._f.write(rec.to_bytes())
            self._f.flush()
            if self.first_seq == 0:
                self.first_seq = seq
            self.last_seq = seq
            return seq

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    # -- shipper side --------------------------------------------------------

    @property
    def acked_seq(self) -> int:
        return self.watermark.value

    def set_acked(self, seq: int) -> None:
        self.watermark.set(seq)

    def pending(self) -> int:
        return max(0, self.last_seq - self.acked_seq)

    def read_from(self, seq: int, limit: int = 128) -> list[LogRecord]:
        """Up to `limit` records starting at `seq` (seek is arithmetic:
        fixed-size records, contiguous seqs)."""
        with self._lock:
            if self.first_seq == 0 or seq > self.last_seq:
                return []
            seq = max(seq, self.first_seq)
            off = (seq - self.first_seq) * RECORD_SIZE
            n = min(limit, self.last_seq - seq + 1)
            buf = os.pread(self._f.fileno(), n * RECORD_SIZE, off)
        out = []
        for i in range(len(buf) // RECORD_SIZE):
            rec = LogRecord.from_bytes(
                buf[i * RECORD_SIZE:(i + 1) * RECORD_SIZE])
            if rec is None:
                break  # torn tail raced in; ship what checks out
            out.append(rec)
        return out

    # -- compaction (vacuum) -------------------------------------------------

    def compact(self) -> int:
        """Drop the acked prefix (those records can never need
        re-shipping) and append a vacuum record so the log is never
        empty and the seq chain stays recoverable from the file alone.
        Atomic rewrite (tmp + os.replace) like the .dat swap.  Returns
        the number of records dropped."""
        import time
        with self._lock:
            acked = self.watermark.value
            if self.first_seq == 0:
                start = self.last_seq + 1
            else:
                start = max(self.first_seq, acked + 1)
            tmp = f"{self.path}.{os.getpid()}.tmp"
            dropped = start - self.first_seq if self.first_seq else 0
            with open(tmp, "wb") as f:
                if self.first_seq and start <= self.last_seq:
                    off = (start - self.first_seq) * RECORD_SIZE
                    n = self.last_seq - start + 1
                    f.write(os.pread(self._f.fileno(),
                                     n * RECORD_SIZE, off))
                seq = self.last_seq + 1
                f.write(LogRecord(seq, OP_VACUUM, 0, 0, 0,
                                  time.time_ns()).to_bytes())
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "r+b")
            self._f.seek(0, os.SEEK_END)
            self.first_seq = start if start <= self.last_seq else seq
            self.last_seq = seq
            return max(0, dropped)

    # -- lifecycle -----------------------------------------------------------

    def status(self) -> dict:
        return {"first_seq": self.first_seq, "last_seq": self.last_seq,
                "acked_seq": self.acked_seq, "pending": self.pending()}

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                self._f.close()
            except (OSError, ValueError):
                pass


# -- variable-length framing: the filer shard `.mlog` ------------------------

# seq u64 | epoch u32 | len u32, then `len` payload bytes, then a
# crc32c u32 over header+payload.
_FRAME = struct.Struct(">QII")
FRAME_HEADER_SIZE = _FRAME.size  # 16


class FramedLog:
    """Durable variable-length CRC-framed journal: the shard `.mlog`.

    The fixed-width ReplicationLog above frames needle mutations, where
    40 bytes fits; filer metadata events are JSON documents of arbitrary
    size, so the shard journal frames each record with an explicit
    length and covers header+payload with one crc32c.  Everything else
    matches the `.rlog` stance: contiguous strictly-increasing seqs
    (the follower idempotency key, with the epoch), torn-tail
    truncation at open, a Watermark sidecar (`.map`) for the applied
    seq on followers, and flush-on-append / fsync-on-demand so the
    primary can batch the fsync right before the ack.

    `epoch` rides in the frame header: after a failover the promoted
    primary keeps the seq chain but bumps the epoch, so a record's
    (epoch, seq) pair is globally unambiguous and a rejoining stale
    primary can locate exactly where its history diverged.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self.watermark = Watermark(path + ".map")
        self.first_seq = 0
        self.last_seq = 0
        self.last_epoch = 0
        self._offsets: list[int] = []  # offset of first_seq + i
        if not os.path.exists(path):
            with open(path, "wb"):
                pass
        self._f = open(path, "r+b")
        self._recover()

    def _recover(self) -> None:
        """Sequential scan: index every whole, CRC-good record; the
        first short or CRC-bad frame truncates the file there (a crash
        mid-append costs at most the unacked tail, never the log)."""
        data_end = os.fstat(self._f.fileno()).st_size
        off = 0
        while off + FRAME_HEADER_SIZE + _CRC.size <= data_end:
            self._f.seek(off)
            head = self._f.read(FRAME_HEADER_SIZE)
            seq, epoch, length = _FRAME.unpack(head)
            frame_end = off + FRAME_HEADER_SIZE + length + _CRC.size
            if length > (1 << 30) or frame_end > data_end:
                break  # torn or garbage length field
            payload = self._f.read(length)
            (crc,) = _CRC.unpack(self._f.read(_CRC.size))
            if crc32c(head + payload) != crc:
                break
            if not self._offsets:
                self.first_seq = seq
            elif seq != self.last_seq + 1:
                break  # seq discontinuity: treat the rest as rot
            self._offsets.append(off)
            self.last_seq = seq
            self.last_epoch = epoch
            off = frame_end
        if off < data_end:
            self._f.truncate(off)
            self._f.flush()
            os.fsync(self._f.fileno())
        self._f.seek(off)

    # -- write ---------------------------------------------------------------

    def append(self, epoch: int, payload: dict,
               seq: int | None = None) -> int:
        """Frame + append one record; returns its seq.  Flushes to the
        OS but does NOT fsync — call sync() at the commit point (before
        the ack), so a storm of appends shares one barrier.

        A primary omits `seq` (auto-assigned last+1); a follower passes
        the primary's seq through verbatim, and a gap raises — the
        chain must stay contiguous for seek-by-seq to stay honest."""
        body = json.dumps(payload, separators=(",", ":")).encode()
        with self._lock:
            if seq is None:
                seq = self.last_seq + 1
            elif self._offsets and seq != self.last_seq + 1:
                raise ValueError(
                    f"seq gap: have {self.last_seq}, got {seq}")
            head = _FRAME.pack(seq, epoch, len(body))
            off = self._f.seek(0, os.SEEK_END)
            self._f.write(head + body +
                          _CRC.pack(crc32c(head + body)))
            self._f.flush()
            if not self._offsets:
                self.first_seq = seq
            self._offsets.append(off)
            self.last_seq = seq
            self.last_epoch = epoch
            return seq

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    # -- read ----------------------------------------------------------------

    def read_from(self, start_seq: int, max_records: int = 1 << 30
                  ) -> list[tuple[int, int, dict]]:
        """Records with seq >= start_seq as (seq, epoch, payload)."""
        out: list[tuple[int, int, dict]] = []
        with self._lock:
            if not self._offsets:
                return out
            start = max(start_seq, self.first_seq)
            if start > self.last_seq:
                return out
            i = start - self.first_seq
            end = os.fstat(self._f.fileno()).st_size
            off = self._offsets[i]
            buf = os.pread(self._f.fileno(), end - off, off)
        pos = 0
        while pos + FRAME_HEADER_SIZE + _CRC.size <= len(buf) and \
                len(out) < max_records:
            seq, epoch, length = _FRAME.unpack_from(buf, pos)
            body = buf[pos + FRAME_HEADER_SIZE:
                       pos + FRAME_HEADER_SIZE + length]
            out.append((seq, epoch, json.loads(body)))
            pos += FRAME_HEADER_SIZE + length + _CRC.size
        return out

    # -- repair (rejoin after a failed-over primacy) -------------------------

    def truncate_from(self, seq: int) -> list[tuple[int, int, dict]]:
        """Drop every record with seq >= `seq` and return them (newest
        first) so the caller can reverse-apply the divergent suffix.
        Used when a deposed primary rejoins: records it journaled but
        never replicated were never acked, so unwinding them is safe —
        the promoted primary's history is the truth."""
        with self._lock:
            if not self._offsets or seq > self.last_seq:
                return []
            seq = max(seq, self.first_seq)
            dropped = self.read_from(seq)
            i = seq - self.first_seq
            cut = self._offsets[i]
            self._f.truncate(cut)
            self._f.flush()
            os.fsync(self._f.fileno())
            del self._offsets[i:]
            self.last_seq = seq - 1
            if not self._offsets:
                self.first_seq = 0
                self.last_epoch = 0
            else:
                tail = self.read_from(self.last_seq)
                self.last_epoch = tail[0][1] if tail else 0
            return list(reversed(dropped))

    # -- lifecycle -----------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {"first_seq": self.first_seq,
                    "last_seq": self.last_seq,
                    "last_epoch": self.last_epoch,
                    "applied_seq": self.watermark.value}

    def close(self) -> None:
        with self._lock:
            try:
                self._f.flush()
                self._f.close()
            except (OSError, ValueError):
                pass
