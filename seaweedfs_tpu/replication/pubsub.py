"""GCP Pub/Sub notification queue over its REST API — no SDK.

Reference: weed/notification/google_pub_sub (cloud.google.com/go/pubsub)
and weed/replication/sub/notification_google_pub_sub.go.  This build
authenticates the way the SDK does under the hood — an RS256-signed
service-account JWT grant exchanged at the token endpoint for a bearer
token (RFC 7523) — with the RSA-SHA256 primitive from libcrypto
(utils/cipher.rs256_sign) and everything else stdlib HTTP + JSON.

publish  -> POST v1/projects/{p}/topics/{t}:publish
consume  -> POST v1/projects/{p}/subscriptions/{s}:pull, then
            :acknowledge after delivery (at-least-once)

QUARANTINED: nothing in the tree constructs this queue outside
`queue_for_spec("pubsub://...")` — cross-cluster disaster recovery now
rides the volume-level change-log shipper (rlog.py + shipper.py), not
a cloud queue.  Kept (with its auth/wire tests) for operators who feed
filer events into Pub/Sub; the public surface is pinned by `__all__`
below and everything else may change or be removed.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import urllib.request

from .notification import NotificationQueue

__all__ = ["PubSubQueue", "make_service_account_jwt"]

_SCOPE = "https://www.googleapis.com/auth/pubsub"


def _b64url(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()


def make_service_account_jwt(sa: dict, audience: str,
                             scope: str = _SCOPE,
                             lifetime: int = 3600,
                             now: int | None = None) -> str:
    """RS256 service-account JWT (RFC 7523 grant assertion)."""
    from ..utils.cipher import rs256_sign
    now = int(time.time()) if now is None else now
    header = {"alg": "RS256", "typ": "JWT"}
    if sa.get("private_key_id"):
        header["kid"] = sa["private_key_id"]
    claims = {"iss": sa["client_email"], "scope": scope,
              "aud": audience, "iat": now, "exp": now + lifetime}
    signing_input = (_b64url(json.dumps(header).encode()) + "." +
                     _b64url(json.dumps(claims).encode()))
    sig = rs256_sign(sa["private_key"].encode(), signing_input.encode())
    return signing_input + "." + _b64url(sig)


class PubSubQueue(NotificationQueue):
    """Publish/consume the {key, message} envelope on one topic +
    subscription.  `service_account` is the parsed key-file JSON
    (client_email / private_key / token_uri).  Endpoint overridable for
    emulators (the Pub/Sub emulator speaks the same REST surface)."""

    def __init__(self, project: str, topic: str,
                 subscription: str = "",
                 service_account: dict | None = None,
                 endpoint: str = "https://pubsub.googleapis.com"):
        self.project = project
        self.topic = topic
        self.subscription = subscription or f"{topic}.seaweedfs"
        self.sa = service_account
        self.endpoint = endpoint.rstrip("/")
        self._token = ""
        self._token_exp = 0.0
        self._token_lock = threading.Lock()

    # -- auth ----------------------------------------------------------------

    def _bearer(self) -> str:
        if self.sa is None:
            return ""  # emulator mode: no auth
        with self._token_lock:
            if time.time() < self._token_exp - 60:
                return self._token
            token_uri = self.sa.get(
                "token_uri", "https://oauth2.googleapis.com/token")
            assertion = make_service_account_jwt(self.sa, token_uri)
            body = ("grant_type=urn%3Aietf%3Aparams%3Aoauth%3A"
                    "grant-type%3Ajwt-bearer&assertion="
                    + assertion).encode()
            req = urllib.request.Request(
                token_uri, data=body, method="POST",
                headers={"Content-Type":
                         "application/x-www-form-urlencoded"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                doc = json.loads(resp.read())
            self._token = doc["access_token"]
            self._token_exp = time.time() + int(
                doc.get("expires_in", 3600))
            return self._token

    def _call(self, path: str, payload: dict) -> dict:
        headers = {"Content-Type": "application/json"}
        token = self._bearer()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(
            f"{self.endpoint}/v1/{path}",
            data=json.dumps(payload).encode(), method="POST",
            headers=headers)
        with urllib.request.urlopen(req, timeout=70) as resp:
            return json.loads(resp.read() or b"{}")

    # -- NotificationQueue ----------------------------------------------------

    def publish(self, key: str, message: dict) -> None:
        value = json.dumps({"key": key, "message": message},
                           separators=(",", ":")).encode()
        self._call(
            f"projects/{self.project}/topics/{self.topic}:publish",
            {"messages": [{"data": base64.b64encode(value).decode(),
                           "attributes": {"key": key}}]})

    def consume(self, fn) -> None:
        sub = f"projects/{self.project}/subscriptions/" \
              f"{self.subscription}"
        # returnImmediately pulls may return empty while a backlog
        # exists (why Google deprecated the flag): require consecutive
        # empty pulls before declaring the queue drained.
        empty = 0
        while True:
            out = self._call(f"{sub}:pull",
                             {"maxMessages": 10,
                              "returnImmediately": True})
            received = out.get("receivedMessages", [])
            if not received:
                empty += 1
                if empty >= 3:
                    return
                continue
            empty = 0
            ack_ids = []
            for rm in received:
                raw = base64.b64decode(
                    rm.get("message", {}).get("data", ""))
                try:
                    doc = json.loads(raw)
                except json.JSONDecodeError:
                    doc = None
                if isinstance(doc, dict) and "key" in doc \
                        and "message" in doc:
                    fn(doc["key"], doc["message"])
                # foreign/undecodable messages are acked too, or they
                # redeliver forever (same poison policy as SqsQueue)
                ack_ids.append(rm["ackId"])
            # Ack AFTER delivery: a crash mid-batch redelivers.
            self._call(f"{sub}:acknowledge", {"ackIds": ack_ids})
