"""Cross-cluster async replication shipper: tail `.rlog`s, ship batches.

The volume server owns one ReplicationShipper when `-replicate.peer`
names a standby cluster's master.  Each tick it walks the local
volumes (optionally filtered to `-replicate.collections`), enables the
durable change log on any that lack one, and ships the unacked tail of
each log as one batch:

- WRITE records carry the raw CRC-gated needle record bytes
  (Volume.read_needle_blob — the same blob `/admin/needle_raw` serves
  to the self-healing plane), so the standby stores byte-identical
  records.  A needle vacuumed or superseded since its log record was
  written ships blobless; the receiver no-ops it and a later record
  for the same needle converges the pair.
- DELETE records always ship: tombstones must propagate (a delete must
  never resurrect — the PR 4 repair rule, now cross-cluster).
- The batch POSTs to the standby volume server resolved through the
  peer master's `/dir/lookup` (falling back to any live peer node for
  a volume the standby doesn't host yet), on the low-priority internal
  lane, breaker-guarded and retry-policied like every other WAN-shaped
  path (cluster/resilience.py).  Safe to retry: the receiver applies
  idempotently by seq against its own durable applied watermark.
- Only after the standby acks `{"acked_seq": N}` does the local `.rwm`
  watermark advance — a kill -9 anywhere re-ships at most one batch,
  which the receiver no-ops.

WAN fault points on the ship path (`wan.partition`, `wan.delay`,
`wan.duplicate`, `wan.reorder` — fault/registry.py) shape the chaos
suite; the `wan.duplicate` hook makes the shipper send the SAME batch
twice and the `wan.reorder` hook delivers batch n+1 BEFORE batch n, so
duplicate and out-of-order delivery are first-class tested scenarios,
not accidents.

Geo active/active (replication/lease.py): when the owning volume
server carries a `-geo.cluster.id`, the shipper runs keyed by lease
ownership — it ships only volumes whose `.lease` sidecar names THIS
cluster (the peer's shipper covers the opposite direction), stamps
every batch with `(cluster_id, epoch)` so the receiver can fence stale
holders, and adopts the receiver's lease on a 409 (a fenced old holder
demotes itself on heal).  `-replicate.compress` zlib-compresses the
record list; the receiver acks with per-batch raw/wire byte counts and
the compressed bytes are what the `rlog.ship` flow purpose meters, so
`-flows.budget rlog.ship=...` governs actual WAN spend.
"""

from __future__ import annotations

import base64
import json
import threading
import time
import zlib

from ..cluster import resilience, rpc
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats import flows as _flows
from ..stats.metrics import (replication_lag_seconds,
                             replication_lag_seconds_total,
                             replication_resends_total,
                             replication_shipped_bytes_total)
from ..storage.volume import VolumeError
from ..trace import root_span
from .rlog import OP_WRITE

_TARGET_TTL = 60.0


class ReplicationShipper:
    """Background daemon tailing every mirrored volume's `.rlog`."""

    def __init__(self, store, peer: str, node: str = "",
                 collections: str = "", interval: float = 0.5,
                 batch_records: int = 128, cluster_id: str = "",
                 compress: bool = False, leases=None):
        self.store = store
        self.peer = peer if peer.startswith("http") else f"http://{peer}"
        self.node = node
        # Geo identity + the lease table that keys shipping direction
        # (replication/lease.py); both empty/None = PR 11
        # active/passive mode (ship everything, unfenced).
        self.cluster_id = cluster_id
        self.leases = leases
        self.compress = compress
        # Cumulative ship accounting (raw vs wire bytes): the
        # compressed-vs-raw WAN spend number /debug/replication and
        # the geo bench report.
        self.shipped = {"batches": 0, "records": 0,
                        "raw_bytes": 0, "wire_bytes": 0}
        # Per-collection opt-in: empty = mirror everything; the
        # default collection opts in as "" (spelled `default` too).
        names = {c.strip() for c in collections.split(",") if c.strip()}
        self.collections = {("" if c == "default" else c)
                            for c in names} or None
        self.interval = interval
        self.batch_records = batch_records
        self.paused = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # vid -> (expires_at, "host:port") standby target cache,
        # invalidated on send failure so a rebalanced standby re-resolves.
        self._targets: dict[int, tuple[float, str]] = {}
        self._lag: dict[int, dict] = {}
        self._lag_lock = threading.Lock()
        self._policy = resilience.RetryPolicy(
            max_attempts=3, per_attempt_timeout=10.0,
            total_deadline=20.0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="replication-shipper")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def kick(self) -> None:
        """Ship now instead of waiting out the tick (tests, cutover)."""
        self._wake.set()

    def _loop(self) -> None:
        # Flow identity for this daemon thread (several servers can
        # share a test process; outbound batches must attribute to
        # THIS volume server, not the process default).
        _flows.bind_thread(self.node, "volume")
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            # root_span: ship/ack/lag events journaled from this
            # daemon must carry the trace of the tick that caused them.
            with root_span("replication.tick", "replication"):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — peer down: the
                    pass           # watermark holds; next tick resumes

    # -- shipping ------------------------------------------------------------

    def _volumes(self):
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                if v.remote_file is not None:
                    continue  # tiered: readonly, nothing journals
                if self.collections is not None and \
                        (v.collection or "") not in self.collections:
                    continue
                yield v

    def tick(self) -> None:
        for v in self._volumes():
            if self.leases is not None and \
                    not self.leases.ships(v.vid):
                # The lease names the PEER as holder: its shipper
                # covers this volume in the opposite direction, and
                # shipping our (fenced, apply-only) copy back would
                # be rejected traffic at best.
                continue
            if v.rlog is None:
                v.enable_rlog()
            try:
                self._ship_volume(v)
            except (OSError, rpc.RpcError, VolumeError):
                continue  # per-volume isolation: one sick pair must
                #           not starve the others' shipping

    def _ship_volume(self, v) -> None:
        rlog = v.rlog
        self._observe_lag(v.vid, rlog)
        if self.paused:
            return
        while rlog.pending() > 0 and not self._stop.is_set():
            recs = rlog.read_from(rlog.acked_seq + 1, self.batch_records)
            if not recs:
                return
            body, nbytes = self._encode_batch(v, recs)
            target = self._resolve_target(v.vid)
            if target is None:
                return
            if _fault.ARMED:
                self._maybe_reorder(v, rlog, recs, target)
            t0 = time.perf_counter()
            try:
                out = self._post(target, v.vid, body)
            except rpc.RpcError as e:
                if e.status == 409:
                    # The receiver's fencing plane spoke: a holder
                    # with a newer epoch exists.  Adopt its lease (a
                    # partitioned old holder demotes on heal) and stop
                    # shipping this volume.
                    self._fence_from_peer(v.vid, target, e.message)
                    return
                self._targets.pop(v.vid, None)  # re-resolve next tick
                raise
            except Exception:
                self._targets.pop(v.vid, None)  # re-resolve next tick
                raise
            acked = int(out.get("acked_seq", 0))
            if acked > rlog.acked_seq:
                rlog.set_acked(acked)
            replication_shipped_bytes_total.inc(nbytes)
            raw_b = int(out.get("raw_bytes", 0) or 0)
            wire_b = int(out.get("wire_bytes", 0) or 0)
            self.shipped["batches"] += 1
            self.shipped["records"] += len(recs)
            self.shipped["raw_bytes"] += raw_b or nbytes
            self.shipped["wire_bytes"] += wire_b or nbytes
            emit_event("replication.ship", node=self.node, vid=v.vid,
                       peer=target, records=len(recs), bytes=nbytes,
                       first_seq=recs[0].seq, last_seq=recs[-1].seq,
                       seconds=round(time.perf_counter() - t0, 6))
            emit_event("replication.ack", node=self.node, vid=v.vid,
                       peer=target, acked_seq=acked,
                       applied=out.get("applied", 0),
                       skipped=out.get("skipped", 0),
                       raw_bytes=raw_b, wire_bytes=wire_b)
            self._observe_lag(v.vid, rlog)

    def _maybe_reorder(self, v, rlog, recs, target: str) -> None:
        """`wan.reorder` chaos hook: deliver batch n+1 BEFORE batch n.
        The receiver must refuse the gapped batch WITHOUT acking it —
        accepting would advance its watermark past batch n's seqs and
        those records would be skipped as duplicates forever.  The
        refusal is swallowed here; the normal loop then ships n and
        n+1 in order and everything converges."""
        try:
            _fault.hit("wan.reorder", peer=target, vid=v.vid)
        except _fault.FaultInjected:
            nxt = rlog.read_from(recs[-1].seq + 1, self.batch_records)
            if not nxt:
                return  # nothing after batch n: no reorder to inject
            nbody, _nb = self._encode_batch(v, nxt)
            replication_resends_total.inc(reason="reorder")
            try:
                self._post(target, v.vid, nbody)
            except rpc.RpcError:
                pass  # the receiver refused the gap — the invariant

    def _fence_from_peer(self, vid: int, target: str,
                         detail: str) -> None:
        """Adopt the receiver's lease after a 409: fetch its
        `.lease` row and fence our own table forward (monotonic, so a
        racing local acquire at a higher epoch still wins)."""
        row = None
        try:
            doc = rpc.call(
                f"http://{target}/admin/lease/status?volume={vid}")
            row = (doc.get("leases") or {}).get(str(vid))
        except Exception:  # noqa: BLE001 — peer gone mid-fence: the
            pass           # 409 will recur and we retry then
        if row and self.leases is not None:
            self.leases.fence(vid, str(row["cluster_id"]),
                              int(row["epoch"]))
            emit_event("lease.fence", node=self.node, severity="warn",
                       vid=vid, holder=str(row["cluster_id"]),
                       epoch=int(row["epoch"]), detail=detail)

    def _encode_batch(self, v, recs) -> tuple[dict, int]:
        out = []
        nbytes = 0
        for r in recs:
            rec = {"seq": r.seq, "op": r.op, "needle_id": r.needle_id,
                   "cookie": r.cookie, "size": r.size, "ts_ns": r.ts_ns}
            if r.op == OP_WRITE:
                try:
                    blob = v.read_needle_blob(r.needle_id)
                    rec["blob"] = base64.b64encode(blob).decode()
                    nbytes += len(blob)
                except VolumeError:
                    # Vacuumed, superseded, or locally rotten: nothing
                    # shippable for THIS seq; a later record for the
                    # needle (or the repair plane) converges the pair.
                    rec["blob"] = None
            out.append(rec)
        body = {"volume": v.vid, "collection": v.collection,
                "version": v.version,
                "replication": str(v.super_block.replica_placement),
                "ttl": str(v.super_block.ttl),
                "records": out}
        if self.cluster_id:
            # Geo fencing stamp: the receiver rejects this batch when
            # its own `.lease` knows a newer epoch for the volume.
            body["cluster_id"] = self.cluster_id
            body["epoch"] = self.leases.epoch(v.vid) \
                if self.leases is not None else 0
        if self.compress:
            # Delta-compressed shipping: the record list (blobs and
            # all) rides as one zlib stream; what goes on the WAN —
            # and what the `rlog.ship` flow purpose meters — is the
            # compressed payload.
            raw = json.dumps(out).encode()
            del body["records"]
            body["codec"] = "zlib"
            body["records_z"] = base64.b64encode(
                zlib.compress(raw)).decode()
            body["raw_bytes"] = len(raw)
        return body, nbytes

    def _post(self, target: str, vid: int, body: dict) -> dict:
        payload = json.dumps(body).encode()
        breaker = resilience.breaker_for(target)

        def send(attempt: int, timeout: float) -> dict:
            if attempt:
                replication_resends_total.inc(reason="retry")
            if not breaker.allow():
                raise resilience.BreakerOpen(target)
            try:
                if _fault.ARMED:
                    # WAN shaping on the ship path: delay models
                    # latency, partition fails the send (the batch
                    # never arrives; the watermark holds).
                    _fault.hit("wan.delay", peer=target, vid=vid)
                    _fault.hit("wan.partition", peer=target, vid=vid)
                out = rpc.call(
                    f"http://{target}/admin/replication/apply", "POST",
                    payload, timeout=timeout,
                    headers={**rpc.PRIORITY_LOW,
                             **_flows.tag("rlog.ship")})
            except Exception as e:  # noqa: BLE001 — classified below
                status = getattr(e, "status", None)
                if status is None or status >= 500:
                    breaker.record_failure()
                raise
            breaker.record_success()
            if _fault.ARMED:
                try:
                    _fault.hit("wan.duplicate", peer=target, vid=vid)
                except _fault.FaultInjected:
                    # Duplicate delivery, on purpose: the same batch
                    # lands twice and the receiver's applied watermark
                    # must no-op the replay.
                    replication_resends_total.inc(reason="duplicate")
                    rpc.call(f"http://{target}"
                             f"/admin/replication/apply", "POST",
                             payload, timeout=timeout,
                             headers={**rpc.PRIORITY_LOW,
                                      **_flows.tag("rlog.ship")})
            assert isinstance(out, dict)
            return out

        # idempotent=True: the receiver's seq watermark makes a resend
        # of bytes-that-maybe-landed a no-op, the one property plain
        # needle POSTs don't have.
        return self._policy.run(send, idempotent=True)

    # -- standby resolution --------------------------------------------------

    def _resolve_target(self, vid: int) -> str | None:
        hit = self._targets.get(vid)
        if hit and time.monotonic() < hit[0]:
            return hit[1]
        url = None
        try:
            # steered=1: ask for the peer's RAW placement.  Steering is
            # a client-read feature — a steering peer master would
            # prepend OUR region's replica the moment it sees our lag
            # cross the SLO, and the shipper would ship the backlog to
            # itself (self-apply gap-409s, shipping stalls forever).
            out = rpc.call(
                f"{self.peer}/dir/lookup?volumeId={vid}&steered=1")
            locs = out.get("locations") or []
            if locs:
                url = locs[0].get("url") or locs[0].get("publicUrl")
        except rpc.RpcError:
            pass  # standby doesn't host it yet: pick any live node
        except Exception:  # noqa: BLE001 — peer master unreachable
            return None
        if not url:
            try:
                out = rpc.call(f"{self.peer}/vol/list")
                nodes = [n["url"]
                         for dc in out.get("topology", {})
                                      .get("data_centers", [])
                         for rack in dc.get("racks", [])
                         for n in rack.get("nodes", [])
                         if n.get("url")]
                if nodes:
                    # Stable spread of new volumes across the standby;
                    # the receiver creates + heartbeats the volume, so
                    # the next resolve goes through /dir/lookup.
                    url = sorted(nodes)[vid % len(nodes)]
            except Exception:  # noqa: BLE001
                return None
        if not url:
            return None
        self._targets[vid] = (time.monotonic() + _TARGET_TTL, url)
        return url

    # -- lag accounting ------------------------------------------------------

    def _observe_lag(self, vid: int, rlog) -> None:
        lag_seq = rlog.pending()
        lag_seconds = 0.0
        if lag_seq:
            head = rlog.read_from(rlog.acked_seq + 1, 1)
            if head:
                lag_seconds = max(0.0, time.time()
                                  - head[0].ts_ns / 1e9)
        prev = self._lag.get(vid) or {}
        with self._lag_lock:
            self._lag[vid] = {
                "lag_seq": lag_seq,
                "lag_seconds": round(lag_seconds, 3),
                "last_seq": rlog.last_seq,
                "acked_seq": rlog.acked_seq,
                "paused": self.paused,
            }
        replication_lag_seconds.set(lag_seconds, volume=str(vid))
        if lag_seconds:
            replication_lag_seconds_total.inc(lag_seconds)
        # One journal row per lag episode (threshold-crossing, not
        # per-tick): the timeline shows WHEN a pair fell behind.
        if lag_seq and not prev.get("lag_seq"):
            emit_event("replication.lag", node=self.node, severity="warn",
                       vid=vid, lag_seq=lag_seq,
                       lag_seconds=round(lag_seconds, 3), peer=self.peer)

    # -- surfaces ------------------------------------------------------------

    def lag_view(self) -> dict:
        """Heartbeat payload: per-volume lag + the pairing config."""
        with self._lag_lock:
            vols = {str(vid): dict(row) for vid, row in
                    self._lag.items()}
        return {"peer": self.peer, "paused": self.paused,
                "volumes": vols}

    def status(self) -> dict:
        doc = self.lag_view()
        doc["interval"] = self.interval
        doc["batch_records"] = self.batch_records
        doc["collections"] = (sorted(c or "default"
                                     for c in self.collections)
                              if self.collections is not None else [])
        doc["cluster_id"] = self.cluster_id
        doc["compress"] = self.compress
        doc["shipped"] = dict(self.shipped)
        return doc
