"""Cross-cluster async replication shipper: tail `.rlog`s, ship batches.

The volume server owns one ReplicationShipper when `-replicate.peer`
names a standby cluster's master.  Each tick it walks the local
volumes (optionally filtered to `-replicate.collections`), enables the
durable change log on any that lack one, and ships the unacked tail of
each log as one batch:

- WRITE records carry the raw CRC-gated needle record bytes
  (Volume.read_needle_blob — the same blob `/admin/needle_raw` serves
  to the self-healing plane), so the standby stores byte-identical
  records.  A needle vacuumed or superseded since its log record was
  written ships blobless; the receiver no-ops it and a later record
  for the same needle converges the pair.
- DELETE records always ship: tombstones must propagate (a delete must
  never resurrect — the PR 4 repair rule, now cross-cluster).
- The batch POSTs to the standby volume server resolved through the
  peer master's `/dir/lookup` (falling back to any live peer node for
  a volume the standby doesn't host yet), on the low-priority internal
  lane, breaker-guarded and retry-policied like every other WAN-shaped
  path (cluster/resilience.py).  Safe to retry: the receiver applies
  idempotently by seq against its own durable applied watermark.
- Only after the standby acks `{"acked_seq": N}` does the local `.rwm`
  watermark advance — a kill -9 anywhere re-ships at most one batch,
  which the receiver no-ops.

WAN fault points on the ship path (`wan.partition`, `wan.delay`,
`wan.duplicate` — fault/registry.py) shape the chaos suite; the
`wan.duplicate` hook makes the shipper send the SAME batch twice, so
duplicate delivery is a first-class tested scenario, not an accident.
"""

from __future__ import annotations

import base64
import threading
import time

from ..cluster import resilience, rpc
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats import flows as _flows
from ..stats.metrics import (replication_lag_seconds,
                             replication_lag_seconds_total,
                             replication_resends_total,
                             replication_shipped_bytes_total)
from ..storage.volume import VolumeError
from ..trace import root_span
from .rlog import OP_WRITE

_TARGET_TTL = 60.0


class ReplicationShipper:
    """Background daemon tailing every mirrored volume's `.rlog`."""

    def __init__(self, store, peer: str, node: str = "",
                 collections: str = "", interval: float = 0.5,
                 batch_records: int = 128):
        self.store = store
        self.peer = peer if peer.startswith("http") else f"http://{peer}"
        self.node = node
        # Per-collection opt-in: empty = mirror everything; the
        # default collection opts in as "" (spelled `default` too).
        names = {c.strip() for c in collections.split(",") if c.strip()}
        self.collections = {("" if c == "default" else c)
                            for c in names} or None
        self.interval = interval
        self.batch_records = batch_records
        self.paused = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # vid -> (expires_at, "host:port") standby target cache,
        # invalidated on send failure so a rebalanced standby re-resolves.
        self._targets: dict[int, tuple[float, str]] = {}
        self._lag: dict[int, dict] = {}
        self._lag_lock = threading.Lock()
        self._policy = resilience.RetryPolicy(
            max_attempts=3, per_attempt_timeout=10.0,
            total_deadline=20.0)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="replication-shipper")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def kick(self) -> None:
        """Ship now instead of waiting out the tick (tests, cutover)."""
        self._wake.set()

    def _loop(self) -> None:
        # Flow identity for this daemon thread (several servers can
        # share a test process; outbound batches must attribute to
        # THIS volume server, not the process default).
        _flows.bind_thread(self.node, "volume")
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            # root_span: ship/ack/lag events journaled from this
            # daemon must carry the trace of the tick that caused them.
            with root_span("replication.tick", "replication"):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — peer down: the
                    pass           # watermark holds; next tick resumes

    # -- shipping ------------------------------------------------------------

    def _volumes(self):
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                if v.remote_file is not None:
                    continue  # tiered: readonly, nothing journals
                if self.collections is not None and \
                        (v.collection or "") not in self.collections:
                    continue
                yield v

    def tick(self) -> None:
        for v in self._volumes():
            if v.rlog is None:
                v.enable_rlog()
            try:
                self._ship_volume(v)
            except (OSError, rpc.RpcError, VolumeError):
                continue  # per-volume isolation: one sick pair must
                #           not starve the others' shipping

    def _ship_volume(self, v) -> None:
        rlog = v.rlog
        self._observe_lag(v.vid, rlog)
        if self.paused:
            return
        while rlog.pending() > 0 and not self._stop.is_set():
            recs = rlog.read_from(rlog.acked_seq + 1, self.batch_records)
            if not recs:
                return
            body, nbytes = self._encode_batch(v, recs)
            target = self._resolve_target(v.vid)
            if target is None:
                return
            t0 = time.perf_counter()
            try:
                out = self._post(target, v.vid, body)
            except Exception:
                self._targets.pop(v.vid, None)  # re-resolve next tick
                raise
            acked = int(out.get("acked_seq", 0))
            if acked > rlog.acked_seq:
                rlog.set_acked(acked)
            replication_shipped_bytes_total.inc(nbytes)
            emit_event("replication.ship", node=self.node, vid=v.vid,
                       peer=target, records=len(recs), bytes=nbytes,
                       first_seq=recs[0].seq, last_seq=recs[-1].seq,
                       seconds=round(time.perf_counter() - t0, 6))
            emit_event("replication.ack", node=self.node, vid=v.vid,
                       peer=target, acked_seq=acked,
                       applied=out.get("applied", 0),
                       skipped=out.get("skipped", 0))
            self._observe_lag(v.vid, rlog)

    def _encode_batch(self, v, recs) -> tuple[dict, int]:
        out = []
        nbytes = 0
        for r in recs:
            rec = {"seq": r.seq, "op": r.op, "needle_id": r.needle_id,
                   "cookie": r.cookie, "size": r.size, "ts_ns": r.ts_ns}
            if r.op == OP_WRITE:
                try:
                    blob = v.read_needle_blob(r.needle_id)
                    rec["blob"] = base64.b64encode(blob).decode()
                    nbytes += len(blob)
                except VolumeError:
                    # Vacuumed, superseded, or locally rotten: nothing
                    # shippable for THIS seq; a later record for the
                    # needle (or the repair plane) converges the pair.
                    rec["blob"] = None
            out.append(rec)
        return ({"volume": v.vid, "collection": v.collection,
                 "version": v.version,
                 "replication": str(v.super_block.replica_placement),
                 "ttl": str(v.super_block.ttl),
                 "records": out}, nbytes)

    def _post(self, target: str, vid: int, body: dict) -> dict:
        import json
        payload = json.dumps(body).encode()
        breaker = resilience.breaker_for(target)

        def send(attempt: int, timeout: float) -> dict:
            if attempt:
                replication_resends_total.inc(reason="retry")
            if not breaker.allow():
                raise resilience.BreakerOpen(target)
            try:
                if _fault.ARMED:
                    # WAN shaping on the ship path: delay models
                    # latency, partition fails the send (the batch
                    # never arrives; the watermark holds).
                    _fault.hit("wan.delay", peer=target, vid=vid)
                    _fault.hit("wan.partition", peer=target, vid=vid)
                out = rpc.call(
                    f"http://{target}/admin/replication/apply", "POST",
                    payload, timeout=timeout,
                    headers={**rpc.PRIORITY_LOW,
                             **_flows.tag("rlog.ship")})
            except Exception as e:  # noqa: BLE001 — classified below
                status = getattr(e, "status", None)
                if status is None or status >= 500:
                    breaker.record_failure()
                raise
            breaker.record_success()
            if _fault.ARMED:
                try:
                    _fault.hit("wan.duplicate", peer=target, vid=vid)
                except _fault.FaultInjected:
                    # Duplicate delivery, on purpose: the same batch
                    # lands twice and the receiver's applied watermark
                    # must no-op the replay.
                    replication_resends_total.inc(reason="duplicate")
                    rpc.call(f"http://{target}"
                             f"/admin/replication/apply", "POST",
                             payload, timeout=timeout,
                             headers={**rpc.PRIORITY_LOW,
                                      **_flows.tag("rlog.ship")})
            assert isinstance(out, dict)
            return out

        # idempotent=True: the receiver's seq watermark makes a resend
        # of bytes-that-maybe-landed a no-op, the one property plain
        # needle POSTs don't have.
        return self._policy.run(send, idempotent=True)

    # -- standby resolution --------------------------------------------------

    def _resolve_target(self, vid: int) -> str | None:
        hit = self._targets.get(vid)
        if hit and time.monotonic() < hit[0]:
            return hit[1]
        url = None
        try:
            out = rpc.call(f"{self.peer}/dir/lookup?volumeId={vid}")
            locs = out.get("locations") or []
            if locs:
                url = locs[0].get("url") or locs[0].get("publicUrl")
        except rpc.RpcError:
            pass  # standby doesn't host it yet: pick any live node
        except Exception:  # noqa: BLE001 — peer master unreachable
            return None
        if not url:
            try:
                out = rpc.call(f"{self.peer}/vol/list")
                nodes = [n["url"]
                         for dc in out.get("topology", {})
                                      .get("data_centers", [])
                         for rack in dc.get("racks", [])
                         for n in rack.get("nodes", [])
                         if n.get("url")]
                if nodes:
                    # Stable spread of new volumes across the standby;
                    # the receiver creates + heartbeats the volume, so
                    # the next resolve goes through /dir/lookup.
                    url = sorted(nodes)[vid % len(nodes)]
            except Exception:  # noqa: BLE001
                return None
        if not url:
            return None
        self._targets[vid] = (time.monotonic() + _TARGET_TTL, url)
        return url

    # -- lag accounting ------------------------------------------------------

    def _observe_lag(self, vid: int, rlog) -> None:
        lag_seq = rlog.pending()
        lag_seconds = 0.0
        if lag_seq:
            head = rlog.read_from(rlog.acked_seq + 1, 1)
            if head:
                lag_seconds = max(0.0, time.time()
                                  - head[0].ts_ns / 1e9)
        prev = self._lag.get(vid) or {}
        with self._lag_lock:
            self._lag[vid] = {
                "lag_seq": lag_seq,
                "lag_seconds": round(lag_seconds, 3),
                "last_seq": rlog.last_seq,
                "acked_seq": rlog.acked_seq,
                "paused": self.paused,
            }
        replication_lag_seconds.set(lag_seconds, volume=str(vid))
        if lag_seconds:
            replication_lag_seconds_total.inc(lag_seconds)
        # One journal row per lag episode (threshold-crossing, not
        # per-tick): the timeline shows WHEN a pair fell behind.
        if lag_seq and not prev.get("lag_seq"):
            emit_event("replication.lag", node=self.node, severity="warn",
                       vid=vid, lag_seq=lag_seq,
                       lag_seconds=round(lag_seconds, 3), peer=self.peer)

    # -- surfaces ------------------------------------------------------------

    def lag_view(self) -> dict:
        """Heartbeat payload: per-volume lag + the pairing config."""
        with self._lag_lock:
            vols = {str(vid): dict(row) for vid, row in
                    self._lag.items()}
        return {"peer": self.peer, "paused": self.paused,
                "volumes": vols}

    def status(self) -> dict:
        doc = self.lag_view()
        doc["interval"] = self.interval
        doc["batch_records"] = self.batch_records
        doc["collections"] = (sorted(c or "default"
                                     for c in self.collections)
                              if self.collections is not None else [])
        return doc
