"""Cross-cluster replication: sinks, the replicator pump, and sync.

Reference: weed/replication/ (replicator.go:17-72 routing meta events to
pluggable sinks, sink/{filersink,s3sink,localsink,...}, sub/ notification
inputs) and command/filer_sync.go:81-320 (active-active two-way sync with
per-signature offset checkpoints).
"""

from .notification import (FileQueue, MemoryQueue,  # noqa: F401
                           NotificationQueue, queue_for_spec)
from .replicator import Replicator  # noqa: F401
from .sink import FilerSink, LocalSink, ReplicationSink, S3Sink  # noqa: F401
from .sync import FilerSyncWorker, sync_once  # noqa: F401
