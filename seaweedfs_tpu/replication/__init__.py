"""Cross-cluster replication: the change-log mirror and the geo lease
plane.

The LIVE plane is volume-level async mirroring (rlog.py + shipper.py +
lease.py): every committed write/delete journals to a durable
per-volume change log (`<volume>.rlog`) and a background shipper tails
it to a peer cluster (`-replicate.peer`), idempotently applied and
watermarked on both sides so kill -9 anywhere loses nothing acked.
With `-geo.cluster.id` set, per-volume `.lease` sidecars key shipping
direction and epoch-fence writes so two regions can run active/active
(README "Disaster recovery > Geo active/active").  `__all__` is pinned
to exactly this plane.

QUARANTINED: the filer-event replication port (replicator.py + sink.py
+ notification.py — reference weed/replication/replicator.go and
sink/) predates the change-log shipper and is not wired into any
server role.  Its names (Replicator, FilerSink, LocalSink, S3Sink,
ReplicationSink, NotificationQueue, FileQueue, MemoryQueue,
queue_for_spec) stay importable for existing tooling via lazy
`__getattr__`, but they are deliberately OUT of `__all__`; new code
must not grow dependencies on them (tests/test_replication.py pins
the boundary).

The old mtime-diff `filer.sync` walker was superseded by the change-log
shipper and removed.
"""

from .lease import LeaseTable, VolumeLease  # noqa: F401
from .rlog import ReplicationLog, Watermark  # noqa: F401
from .shipper import ReplicationShipper  # noqa: F401

# The supported surface: the change-log mirror + geo leases, nothing
# from the quarantined filer-event plane.
__all__ = ["LeaseTable", "ReplicationLog", "ReplicationShipper",
           "VolumeLease", "Watermark"]

# Legacy filer-event names resolve lazily (PEP 562) so importing the
# live plane never pays for — or accidentally revives — the
# quarantined one.
_QUARANTINED = {
    "FileQueue": "notification",
    "MemoryQueue": "notification",
    "NotificationQueue": "notification",
    "queue_for_spec": "notification",
    "Replicator": "replicator",
    "FilerSink": "sink",
    "LocalSink": "sink",
    "ReplicationSink": "sink",
    "S3Sink": "sink",
}


def __getattr__(name: str):
    mod = _QUARANTINED.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
