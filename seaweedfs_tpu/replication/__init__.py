"""Cross-cluster replication: the change-log mirror, sinks, and the
replicator pump.

Two planes live here:

- Volume-level async mirroring (rlog.py + shipper.py): every committed
  write/delete journals to a durable per-volume change log
  (`<volume>.rlog`) and a background shipper tails it to a standby
  cluster (`-replicate.peer`), idempotently applied and watermarked on
  both sides so kill -9 anywhere loses nothing acked.  This is the
  disaster-recovery plane (README "Disaster recovery").
- Filer-event replication (replicator.py + sink.py): routes filer meta
  events to pluggable sinks (filer/local/s3/gcs/b2/azure), reference
  weed/replication/replicator.go:17-72 and sink/.

The old mtime-diff `filer.sync` walker was superseded by the change-log
shipper and removed.
"""

from .notification import (FileQueue, MemoryQueue,  # noqa: F401
                           NotificationQueue, queue_for_spec)
from .replicator import Replicator  # noqa: F401
from .rlog import ReplicationLog, Watermark  # noqa: F401
from .shipper import ReplicationShipper  # noqa: F401
from .sink import FilerSink, LocalSink, ReplicationSink, S3Sink  # noqa: F401
