"""Replicator: route one meta event to a replication sink.

Reference: weed/replication/replicator.go:17-72 — translate an
EventNotification under a source path prefix into sink
create/update/delete calls, fetching file content from the source
cluster when the sink needs bytes.
"""

from __future__ import annotations

from ..filer.client import FilerProxy
from .sink import ReplicationSink


class Replicator:
    def __init__(self, source_filer_url: str, source_dir: str,
                 sink: ReplicationSink):
        self.source = FilerProxy(source_filer_url)
        self.source_dir = "/" + source_dir.strip("/")
        self.sink = sink

    def _key(self, path: str) -> str | None:
        """Source path -> sink-relative key; None if outside the
        replicated prefix (replicator.go Replicate key check)."""
        root = self.source_dir.rstrip("/")
        if not (path + "/").startswith(root + "/"):
            return None
        return path[len(root):].lstrip("/") or "/"

    def _read(self, entry: dict) -> bytes | None:
        """Current content of the source file, or None if it has since
        vanished (the event is stale; a later delete event follows)."""
        if entry.get("is_directory") or not entry.get("chunks"):
            return b"" if not entry.get("is_directory") else None
        import urllib.error
        try:
            with self.source.get(entry["path"]) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def replicate(self, event: dict) -> bool:
        """Apply one EventNotification dict; True if it hit the sink."""
        old, new = event.get("old_entry"), event.get("new_entry")
        path = (new or old or {}).get("path", "")
        key = self._key(path)
        if key is None or key == "/":
            return False
        if new and not old:
            data = self._read(new)
            if data is None and not new.get("is_directory"):
                return False  # source file already gone; its delete
            self.sink.create_entry(key, new, data)  # event follows
        elif old and not new:
            self.sink.delete_entry(key, old.get("is_directory", False))
        elif old and new:
            if new.get("is_directory"):
                # Attribute-only change on a directory: re-create (an
                # idempotent mkdir).  Routing it through update_entry's
                # delete+create would wipe the subtree at the sink.
                self.sink.create_entry(key, new, None)
            else:
                data = self._read(new)
                if data is None:
                    return False  # stale update on a vanished file
                self.sink.update_entry(key, new, data)
        else:
            return False
        return True
