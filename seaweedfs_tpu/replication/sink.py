"""Replication sinks: where replicated entries land.

Reference: weed/replication/sink/ — `ReplicationSink` interface
(replicator consumes CreateEntry/UpdateEntry/DeleteEntry, sink.go), with
filer (filersink/filer_sink.go), local-FS, and S3 (s3sink/s3_sink.go)
targets.  Azure/GCS/B2 exist in the reference; they need cloud SDKs with
network egress, so here they are registry stubs that raise with a clear
message (the sink interface is the seam to add them).
"""

from __future__ import annotations

import os
import urllib.parse
import urllib.request
from typing import Callable

from ..filer.client import FilerProxy


class ReplicationSink:
    """One replication target (sink.go ReplicationSink)."""

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        """key is the sink-relative path; entry the source entry dict;
        data the file content (None for directories)."""
        raise NotImplementedError

    def update_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        self.delete_entry(key, is_directory=False)
        self.create_entry(key, entry, data)

    def delete_entry(self, key: str, is_directory: bool) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FilerSink(ReplicationSink):
    """Replicate into another filer cluster (filersink/filer_sink.go).

    Content is re-uploaded through the target filer so chunks get fresh
    file ids in the target cluster's blob space; `signatures` carries the
    origin chain so a sync loop in the other direction skips these."""

    def __init__(self, filer_url: str, directory: str = "/",
                 signatures: list[int] | None = None):
        self.proxy = FilerProxy(filer_url)
        self.dir = "/" + directory.strip("/")
        self.signatures = signatures or []

    def _path(self, key: str) -> str:
        return (self.dir.rstrip("/") + "/" + key.lstrip("/")) \
            .replace("//", "/")

    def _sig_q(self) -> str:
        if not self.signatures:
            return ""
        return "?signatures=" + ",".join(str(s) for s in self.signatures)

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        path = self._path(key)
        if entry.get("is_directory"):
            url = self.proxy.url + urllib.parse.quote(path) + \
                "?mkdir=true"
            if self.signatures:
                url += "&signatures=" + \
                    ",".join(str(s) for s in self.signatures)
            urllib.request.urlopen(urllib.request.Request(
                url, data=b"", method="POST"), timeout=60).read()
            return
        mime = entry.get("attributes", {}).get("mime", "")
        url = self.proxy.url + urllib.parse.quote(path) + self._sig_q()
        req = urllib.request.Request(url, data=data or b"",
                                     method="POST")
        if mime:
            req.add_header("Content-Type", mime)
        urllib.request.urlopen(req, timeout=600).read()

    def delete_entry(self, key: str, is_directory: bool) -> None:
        path = self._path(key)
        url = self.proxy.url + urllib.parse.quote(path) + \
            ("?recursive=true" if is_directory else "")
        if self.signatures:
            sep = "&" if "?" in url else "?"
            url += sep + "signatures=" + \
                ",".join(str(s) for s in self.signatures)
        req = urllib.request.Request(url, method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=60).read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class LocalSink(ReplicationSink):
    """Replicate to a local directory tree (localsink/local_sink.go)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.join(self.dir, key.lstrip("/"))
        # A replicated key must stay inside the sink root.
        root = os.path.realpath(self.dir)
        real = os.path.realpath(p)
        if not (real + os.sep).startswith(root + os.sep) and real != root:
            raise ValueError(f"replication key escapes sink root: {key}")
        return p

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        p = self._path(key)
        if entry.get("is_directory"):
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data or b"")

    def delete_entry(self, key: str, is_directory: bool) -> None:
        p = self._path(key)
        try:
            if is_directory:
                import shutil
                shutil.rmtree(p)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


class S3Sink(ReplicationSink):
    """Replicate into an S3-compatible endpoint (s3sink/s3_sink.go) —
    works against our own S3 gateway (seaweedfs_tpu/s3api)."""

    def __init__(self, endpoint: str, bucket: str, directory: str = "/",
                 access_key: str = "", secret_key: str = ""):
        from ..s3api.sigv4 import sign_request
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.dir = directory.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self._sign: Callable = sign_request

    def _url(self, key: str) -> str:
        k = (self.dir + "/" + key.lstrip("/")).lstrip("/")
        return f"{self.endpoint}/{self.bucket}/" + \
            urllib.parse.quote(k)

    def _request(self, url: str, method: str, data: bytes = b"",
                 content_type: str = "") -> None:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        if self.access_key:
            headers = self._sign(method, url, headers, data,
                                 self.access_key, self.secret_key)
        req = urllib.request.Request(url, data=data if method != "DELETE"
                                     else None, method=method,
                                     headers=headers)
        try:
            urllib.request.urlopen(req, timeout=600).read()
        except urllib.error.HTTPError as e:
            if not (method == "DELETE" and e.code == 404):
                raise

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        if entry.get("is_directory"):
            return  # S3 has no directories
        mime = entry.get("attributes", {}).get(
            "mime", "application/octet-stream")
        self._request(self._url(key), "PUT", data or b"", mime)

    def delete_entry(self, key: str, is_directory: bool) -> None:
        if is_directory:
            return
        self._request(self._url(key), "DELETE")


_STUB_SINKS = ("gcs", "azure", "b2")


def sink_for_spec(spec: str, **kw) -> ReplicationSink:
    """'filer://host:port/dir', 'local:///path', 's3://endpoint/bucket'."""
    scheme, _, rest = spec.partition("://")
    if scheme == "filer":
        host, _, d = rest.partition("/")
        return FilerSink("http://" + host, "/" + d, **kw)
    if scheme == "local":
        return LocalSink("/" + rest.lstrip("/"))
    if scheme == "s3":
        host, _, rest2 = rest.partition("/")
        bucket, _, d = rest2.partition("/")
        return S3Sink("http://" + host, bucket, "/" + d, **kw)
    if scheme in _STUB_SINKS:
        raise NotImplementedError(
            f"{scheme} sink needs a cloud SDK + egress; add it behind "
            f"ReplicationSink (see weed/replication/sink/{scheme}sink)")
    raise ValueError(f"unknown sink spec: {spec}")
