"""Replication sinks: where replicated entries land.

Reference: weed/replication/sink/ — `ReplicationSink` interface
(replicator consumes CreateEntry/UpdateEntry/DeleteEntry, sink.go), with
filer (filersink/filer_sink.go), local-FS, S3 (s3sink/s3_sink.go),
GCS, B2 and Azure targets.  No cloud SDKs here: GCS and B2 ride their
S3-compatible endpoints through the in-repo sig v4 signer, and Azure
speaks its Blob REST API with stdlib SharedKey signing.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable

from ..filer.client import FilerProxy


class ReplicationSink:
    """One replication target (sink.go ReplicationSink)."""

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        """key is the sink-relative path; entry the source entry dict;
        data the file content (None for directories)."""
        raise NotImplementedError

    def update_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        self.delete_entry(key, is_directory=False)
        self.create_entry(key, entry, data)

    def delete_entry(self, key: str, is_directory: bool) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FilerSink(ReplicationSink):
    """Replicate into another filer cluster (filersink/filer_sink.go).

    Content is re-uploaded through the target filer so chunks get fresh
    file ids in the target cluster's blob space; `signatures` carries the
    origin chain so a sync loop in the other direction skips these."""

    def __init__(self, filer_url: str, directory: str = "/",
                 signatures: list[int] | None = None):
        self.proxy = FilerProxy(filer_url)
        self.dir = "/" + directory.strip("/")
        self.signatures = signatures or []

    def _path(self, key: str) -> str:
        return (self.dir.rstrip("/") + "/" + key.lstrip("/")) \
            .replace("//", "/")

    def _sig_q(self) -> str:
        if not self.signatures:
            return ""
        return "?signatures=" + ",".join(str(s) for s in self.signatures)

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        path = self._path(key)
        if entry.get("is_directory"):
            url = self.proxy.url + urllib.parse.quote(path) + \
                "?mkdir=true"
            if self.signatures:
                url += "&signatures=" + \
                    ",".join(str(s) for s in self.signatures)
            urllib.request.urlopen(urllib.request.Request(
                url, data=b"", method="POST"), timeout=60).read()
            return
        mime = entry.get("attributes", {}).get("mime", "")
        url = self.proxy.url + urllib.parse.quote(path) + self._sig_q()
        req = urllib.request.Request(url, data=data or b"",
                                     method="POST")
        if mime:
            req.add_header("Content-Type", mime)
        urllib.request.urlopen(req, timeout=600).read()

    def delete_entry(self, key: str, is_directory: bool) -> None:
        path = self._path(key)
        url = self.proxy.url + urllib.parse.quote(path) + \
            ("?recursive=true" if is_directory else "")
        if self.signatures:
            sep = "&" if "?" in url else "?"
            url += sep + "signatures=" + \
                ",".join(str(s) for s in self.signatures)
        req = urllib.request.Request(url, method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=60).read()
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class LocalSink(ReplicationSink):
    """Replicate to a local directory tree (localsink/local_sink.go)."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        p = os.path.join(self.dir, key.lstrip("/"))
        # A replicated key must stay inside the sink root.
        root = os.path.realpath(self.dir)
        real = os.path.realpath(p)
        if not (real + os.sep).startswith(root + os.sep) and real != root:
            raise ValueError(f"replication key escapes sink root: {key}")
        return p

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        p = self._path(key)
        if entry.get("is_directory"):
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data or b"")

    def delete_entry(self, key: str, is_directory: bool) -> None:
        p = self._path(key)
        try:
            if is_directory:
                import shutil
                shutil.rmtree(p)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


def _join_key(directory: str, key: str) -> str:
    """dir + key -> bucket/container-relative blob name (shared by
    every object-store sink so the layouts can't drift apart)."""
    return (directory + "/" + key.lstrip("/")).lstrip("/")


def _http(url: str, method: str, data: bytes,
          headers: dict[str, str]) -> None:
    """One blob-store request.  DELETE-404 is success (the entry is
    already gone — replays and races are normal in replication)."""
    req = urllib.request.Request(
        url, data=data if method != "DELETE" else None,
        method=method, headers=headers)
    try:
        urllib.request.urlopen(req, timeout=600).read()
    except urllib.error.HTTPError as e:
        if not (method == "DELETE" and e.code == 404):
            raise


class S3Sink(ReplicationSink):
    """Replicate into an S3-compatible endpoint (s3sink/s3_sink.go) —
    works against our own S3 gateway (seaweedfs_tpu/s3api)."""

    def __init__(self, endpoint: str, bucket: str, directory: str = "/",
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-east-1"):
        from ..s3api.sigv4 import sign_request
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.dir = directory.strip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        # Signed into the credential scope — region-validating
        # endpoints (B2, real AWS) reject a mismatch.
        self.region = region
        self._sign: Callable = sign_request

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/{self.bucket}/" + \
            urllib.parse.quote(_join_key(self.dir, key))

    def _request(self, url: str, method: str, data: bytes = b"",
                 content_type: str = "") -> None:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        if self.access_key:
            headers = self._sign(method, url, headers, data,
                                 self.access_key, self.secret_key,
                                 region=self.region)
        _http(url, method, data, headers)

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        if entry.get("is_directory"):
            return  # S3 has no directories
        mime = entry.get("attributes", {}).get(
            "mime", "application/octet-stream")
        self._request(self._url(key), "PUT", data or b"", mime)

    def delete_entry(self, key: str, is_directory: bool) -> None:
        if is_directory:
            return
        self._request(self._url(key), "DELETE")


class GcsSink(S3Sink):
    """Google Cloud Storage through its S3-interoperable XML API
    (HMAC keys) — no SDK needed (weed/replication/sink/gcssink).
    Default endpoint is GCS's interop host; override for tests."""

    def __init__(self, bucket: str, directory: str = "/",
                 access_key: str = "", secret_key: str = "",
                 endpoint: str = "https://storage.googleapis.com",
                 region: str = "auto"):
        # GCS's interop endpoint accepts any scope region; "auto" is
        # the documented default for sig v4 against storage.googleapis.
        super().__init__(endpoint, bucket, directory,
                         access_key, secret_key, region=region)


class B2Sink(S3Sink):
    """Backblaze B2 through its S3-compatible endpoint
    (weed/replication/sink/b2sink).  `region` forms the default
    endpoint host; override `endpoint` for tests."""

    def __init__(self, bucket: str, directory: str = "/",
                 access_key: str = "", secret_key: str = "",
                 region: str = "us-west-004", endpoint: str = ""):
        super().__init__(
            endpoint or f"https://s3.{region}.backblazeb2.com",
            bucket, directory, access_key, secret_key,
            region=region)


class AzureSink(ReplicationSink):
    """Azure Blob Storage over its REST API with SharedKey auth —
    stdlib hmac/base64, no SDK (weed/replication/sink/azuresink).
    The account key is the base64 string from the portal."""

    API_VERSION = "2019-12-12"

    def __init__(self, account: str, container: str,
                 directory: str = "/", account_key: str = "",
                 endpoint: str = ""):
        self.account = account
        self.container = container
        self.dir = directory.strip("/")
        self.key = base64.b64decode(account_key) if account_key else b""
        self.endpoint = (endpoint or
                         f"https://{account}.blob.core.windows.net"
                         ).rstrip("/")

    def _auth(self, method: str, encoded_blob: str,
              headers: dict[str, str]) -> str:
        """SharedKey canonical string (Azure docs: 'Authorize with
        Shared Key', 2015-02-21+ rules: empty Content-Length for 0).
        The canonicalized resource uses the ENCODED URI path — the
        service signs what it receives on the wire, so signing the raw
        blob name breaks on any key needing percent-encoding."""
        ms = sorted((k.lower(), v) for k, v in headers.items()
                    if k.lower().startswith("x-ms-"))
        canon_headers = "".join(f"{k}:{v}\n" for k, v in ms)
        length = headers.get("Content-Length", "")
        if length == "0":
            length = ""
        canon = "\n".join([
            method,
            "",                                  # Content-Encoding
            "",                                  # Content-Language
            length,                              # Content-Length
            "",                                  # Content-MD5
            headers.get("Content-Type", ""),     # Content-Type
            "",                                  # Date (x-ms-date used)
            "", "", "", "",                      # If-*
            "",                                  # Range
        ]) + "\n" + canon_headers + \
            f"/{self.account}/{self.container}/{encoded_blob}"
        sig = base64.b64encode(
            hmac.new(self.key, canon.encode(),
                     hashlib.sha256).digest()).decode()
        return f"SharedKey {self.account}:{sig}"

    def _request(self, method: str, blob: str, data: bytes = b"",
                 content_type: str = "") -> None:
        headers = {
            "x-ms-date": time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                       time.gmtime()),
            "x-ms-version": self.API_VERSION,
            "Content-Length": str(len(data)),
        }
        if method == "PUT":
            headers["x-ms-blob-type"] = "BlockBlob"
        if content_type:
            headers["Content-Type"] = content_type
        encoded = urllib.parse.quote(blob)
        if self.key:
            headers["Authorization"] = self._auth(method, encoded,
                                                  headers)
        _http(f"{self.endpoint}/{self.container}/{encoded}",
              method, data, headers)

    def create_entry(self, key: str, entry: dict,
                     data: bytes | None) -> None:
        if entry.get("is_directory"):
            return  # blob stores have no directories
        mime = entry.get("attributes", {}).get(
            "mime", "application/octet-stream")
        self._request("PUT", _join_key(self.dir, key), data or b"",
                      mime)

    def delete_entry(self, key: str, is_directory: bool) -> None:
        if is_directory:
            return
        self._request("DELETE", _join_key(self.dir, key))


def sink_for_spec(spec: str, **kw) -> ReplicationSink:
    """'filer://host:port/dir', 'local:///path', 's3://endpoint/bucket',
    'gcs://bucket/dir', 'b2://bucket/dir',
    'azure://account/container/dir' (credentials via keyword args)."""
    scheme, _, rest = spec.partition("://")
    if scheme == "filer":
        host, _, d = rest.partition("/")
        return FilerSink("http://" + host, "/" + d, **kw)
    if scheme == "local":
        return LocalSink("/" + rest.lstrip("/"))
    if scheme == "s3":
        host, _, rest2 = rest.partition("/")
        bucket, _, d = rest2.partition("/")
        return S3Sink("http://" + host, bucket, "/" + d, **kw)
    if scheme == "gcs":
        bucket, _, d = rest.partition("/")
        return GcsSink(bucket, "/" + d, **kw)
    if scheme == "b2":
        bucket, _, d = rest.partition("/")
        return B2Sink(bucket, "/" + d, **kw)
    if scheme == "azure":
        account, _, rest2 = rest.partition("/")
        container, _, d = rest2.partition("/")
        return AzureSink(account, container, "/" + d, **kw)
    raise ValueError(f"unknown sink spec: {spec}")
