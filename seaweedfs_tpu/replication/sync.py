"""filer.sync: continuous active-active synchronization of two filers.

Reference: command/filer_sync.go:81-320 — two independent directions
(A→B and B→A), each tailing the source filer's meta stream and replaying
mutations on the target.  Loop prevention: every replayed mutation
carries the origin chain of filer signatures, and each direction asks
the source to exclude events already signed by the target
(`exclude_signature`).  Resume: the per-direction offset checkpoint is
persisted in the *target* filer's KV keyed by the source's signature
(filer_sync.go:285-320 getOffset/setOffset), so a restarted syncer
continues where it left off.
"""

from __future__ import annotations

import threading

from ..filer.client import FilerProxy
from .replicator import Replicator
from .sink import FilerSink


def _offset_key(source_signature: int) -> str:
    return f"sync.offset.{source_signature:x}"


def sync_once(source_url: str, target_url: str,
              source_dir: str = "/", target_dir: str = "/") -> int:
    """Drain one direction until caught up; returns events applied."""
    source = FilerProxy(source_url)
    target = FilerProxy(target_url)
    src_sig = source.meta_info()["signature"]
    tgt_sig = target.meta_info()["signature"]
    raw = target.kv_get(_offset_key(src_sig))
    offset = int(raw) if raw else 0
    sink = FilerSink(target_url, target_dir)
    repl = Replicator(source_url, source_dir, sink)
    applied = 0
    while True:
        out = source.meta_events(since_ns=offset,
                                 exclude_signature=tgt_sig,
                                 prefix=source_dir)
        for ev in out["events"]:
            # The replayed mutation carries every signature already on
            # the event plus the source's — the other direction's
            # exclude_signature then skips it, breaking the loop.
            sigs = list(ev.get("signatures", []))
            if src_sig not in sigs:
                sigs.append(src_sig)
            sink.signatures = sigs
            if repl.replicate(ev):
                applied += 1
        new_offset = out["last_ns"]
        if new_offset <= offset:
            break
        offset = new_offset
        target.kv_put(_offset_key(src_sig), str(offset).encode())
    return applied


class FilerSyncWorker:
    """Bidirectional continuous sync (the `weed filer.sync` daemon)."""

    def __init__(self, filer_a: str, filer_b: str,
                 dir_a: str = "/", dir_b: str = "/",
                 interval: float = 0.5):
        self.a, self.b = filer_a, filer_b
        self.dir_a, self.dir_b = dir_a, dir_b
        self.interval = interval
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _loop(self, src: str, dst: str, sdir: str, ddir: str) -> None:
        while not self._stop.is_set():
            try:
                sync_once(src, dst, sdir, ddir)
            except Exception:  # noqa: BLE001 — peer down; retry
                pass
            self._stop.wait(self.interval)

    def start(self) -> None:
        for args in ((self.a, self.b, self.dir_a, self.dir_b),
                     (self.b, self.a, self.dir_b, self.dir_a)):
            t = threading.Thread(target=self._loop, args=args,
                                 daemon=True, name="filer-sync")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
