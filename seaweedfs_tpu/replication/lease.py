"""Epoch-fenced per-volume write leases: the `.lease` sidecar.

Geo active/active needs exactly one answer, per volume, to "who may
commit writes right now?" that survives partitions, crashes, and lease
movement.  This module is that answer: a tiny durable sidecar next to
the volume's `.dat` (the `.rwm`/`.qrt` atomic tmp+rename idiom)
recording

    {cluster_id, epoch, acquired_ts}

- `cluster_id` names the HOLDING cluster (the `-geo.cluster.id` of the
  region whose writes are authoritative for this volume).  A write
  arriving at a non-holder forwards to the holder — it never commits
  locally.
- `epoch` is a fencing token, bumped exactly once per lease transfer.
  Every shipped rlog batch carries `(cluster_id, epoch)`; a receiver
  rejects any batch whose epoch is behind its own sidecar, so a
  partitioned old holder that kept committing at a stale epoch fails
  closed on heal instead of silently diverging the pair.
- Transfer order is the safety argument: the old holder DEMOTES
  (writes the new holder's id at epoch+1 into its own sidecar, so it
  fences itself) strictly BEFORE the new holder acquires.  A partition
  between the two steps leaves the volume with NO holder — writes 503
  everywhere until heal — which is fail-closed: unavailable, never
  split-brained.  Two clusters can never both hold epoch E.

A volume with no `.lease` sidecar is in the PR 11 active/passive mode:
the shipper ships everything, applies are unfenced, writes commit
locally.  Leases opt a volume into geo semantics one volume at a time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass

LEASE_SUFFIX = ".lease"


@dataclass(frozen=True)
class VolumeLease:
    """One volume's durable lease row."""
    cluster_id: str
    epoch: int
    acquired_ts: float

    def to_doc(self) -> dict:
        return asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "VolumeLease | None":
        try:
            return cls(cluster_id=str(doc["cluster_id"]),
                       epoch=int(doc["epoch"]),
                       acquired_ts=float(doc.get("acquired_ts", 0.0)))
        except (KeyError, TypeError, ValueError):
            return None


def load_lease(path: str) -> VolumeLease | None:
    try:
        with open(path) as f:
            return VolumeLease.from_doc(json.load(f))
    except (OSError, ValueError):
        return None


def store_lease(path: str, lease: VolumeLease) -> None:
    """Durable write, atomic like the Watermark: a torn lease file must
    never demote OR promote anybody by accident."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(lease.to_doc(), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class LeaseTable:
    """The volume server's view of every local volume's lease.

    Keyed by vid; rows are cached in memory and persisted through the
    `.lease` sidecar next to the volume files.  All transitions go
    through `fence()` — the single monotonic-epoch gate — so no code
    path can regress an epoch."""

    def __init__(self, store, cluster_id: str):
        self.store = store
        self.cluster_id = cluster_id
        self._lock = threading.Lock()
        self._cache: dict[int, VolumeLease] = {}
        # vids mid-transfer: writes refuse while the old holder drains.
        self._moving: set[int] = set()

    # -- sidecar I/O ---------------------------------------------------------

    def _path(self, vid: int) -> str | None:
        v = self.store.find_volume(vid)
        return None if v is None else v.file_name() + LEASE_SUFFIX

    def get(self, vid: int) -> VolumeLease | None:
        with self._lock:
            hit = self._cache.get(vid)
            if hit is not None:
                return hit
            path = self._path(vid)
            if path is None:
                return None
            lease = load_lease(path)
            if lease is not None:
                self._cache[vid] = lease
            return lease

    # -- predicates ----------------------------------------------------------

    def is_holder(self, vid: int) -> bool:
        """True when the LOCAL cluster may commit writes for `vid`.
        No sidecar = active/passive legacy mode = writable."""
        lease = self.get(vid)
        if lease is None:
            return True
        if vid in self._moving:
            return False
        return lease.cluster_id == self.cluster_id

    def ships(self, vid: int) -> bool:
        """True when the LOCAL shipper should ship this volume: we
        hold the lease (or no lease exists — legacy mode).  Unlike
        is_holder, a mid-move volume still ships: the transfer's
        drain step depends on it."""
        lease = self.get(vid)
        return lease is None or lease.cluster_id == self.cluster_id

    def holder(self, vid: int) -> str | None:
        lease = self.get(vid)
        return None if lease is None else lease.cluster_id

    def epoch(self, vid: int) -> int:
        lease = self.get(vid)
        return 0 if lease is None else lease.epoch

    def check_batch(self, vid: int, cluster_id: str,
                    epoch: int) -> str | None:
        """Fencing gate for an incoming rlog batch stamped
        `(cluster_id, epoch)`.  Returns None to admit the batch or a
        human-readable reason to reject it with 409.  Side effect: an
        epoch AHEAD of ours is the new-holder announcement riding the
        data path — we adopt it (demoting ourselves if we held)."""
        lease = self.get(vid)
        if lease is None:
            # First contact: learn the sender's lease so later stale
            # epochs are fenced even before any explicit acquire.
            self.fence(vid, cluster_id, epoch)
            return None
        if epoch < lease.epoch:
            return (f"stale epoch {epoch} < {lease.epoch} "
                    f"(holder {lease.cluster_id})")
        if epoch == lease.epoch and cluster_id != lease.cluster_id:
            return (f"epoch {epoch} held by {lease.cluster_id}, "
                    f"not {cluster_id}")
        if epoch > lease.epoch:
            self.fence(vid, cluster_id, epoch)
        return None

    # -- transitions (all monotonic in epoch) --------------------------------

    def fence(self, vid: int, cluster_id: str, epoch: int) -> VolumeLease:
        """Record `cluster_id` as holder at `epoch` iff that does not
        regress our epoch; persist through the sidecar.  This is
        acquire (cluster_id == ours), demote (cluster_id != ours), and
        heal-time fencing in one primitive."""
        with self._lock:
            cur = self._cache.get(vid)
            path = self._path(vid)
            if cur is None and path is not None:
                cur = load_lease(path)
            if cur is not None and epoch < cur.epoch:
                return cur  # monotonic: a stale fence is a no-op
            if cur is not None and epoch == cur.epoch and \
                    cur.cluster_id == cluster_id:
                return cur
            lease = VolumeLease(cluster_id=cluster_id, epoch=epoch,
                                acquired_ts=time.time())
            if path is not None:
                store_lease(path, lease)
            self._cache[vid] = lease
            self._moving.discard(vid)
            return lease

    def acquire(self, vid: int, epoch: int | None = None) -> VolumeLease:
        """Become the holder.  Default epoch: one past whatever we
        know, so a fresh acquire always fences prior holders."""
        if epoch is None:
            epoch = self.epoch(vid) + 1
        return self.fence(vid, self.cluster_id, epoch)

    def begin_move(self, vid: int) -> None:
        """Refuse local writes while the transfer drains the rlog."""
        with self._lock:
            self._moving.add(vid)

    def abort_move(self, vid: int) -> None:
        with self._lock:
            self._moving.discard(vid)

    # -- surfaces ------------------------------------------------------------

    def snapshot(self) -> dict:
        """Per-volume lease rows for heartbeats and /debug: only
        volumes that actually have a sidecar appear."""
        out: dict[str, dict] = {}
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                lease = self.get(vid)
                if lease is None:
                    continue
                row = lease.to_doc()
                row["holder_is_local"] = \
                    lease.cluster_id == self.cluster_id
                row["moving"] = vid in self._moving
                out[str(vid)] = row
        return out
