"""Topology root: the master's view of the cluster.

Port of weed/topology/topology.go + topology_ec.go: collections of
VolumeLayouts keyed by (replica placement, ttl), heartbeat-driven
registration with full and incremental sync, EC shard map, dead-node
sweeps, and volume id / file key issuance.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from ..core.replica_placement import ReplicaPlacement
from ..core.ttl import TTL
from ..ec.shard_bits import ShardBits
from .node import DataCenter, DataNode, Node, Rack
from .sequence import MemorySequencer
from .volume_layout import VolumeLayout


@dataclass
class VolumeGrowOption:
    collection: str = ""
    replica_placement: str = "000"
    ttl: str = ""
    data_center: str = ""
    rack: str = ""
    data_node: str = ""


@dataclass
class EcShardLocations:
    collection: str = ""
    locations: dict[int, list[DataNode]] = field(default_factory=dict)
    # Erasure codec the volume's shards were generated with ("rs",
    # "lrc", ...) — learned from shard-holder heartbeats so rebuild
    # planning and health math use the right shard counts per volume
    # in a mixed-codec cluster.
    codec: str = "rs"

    def add(self, shard_id: int, dn: DataNode) -> None:
        lst = self.locations.setdefault(shard_id, [])
        if dn not in lst:
            lst.append(dn)

    def remove(self, shard_id: int, dn: DataNode) -> None:
        lst = self.locations.get(shard_id, [])
        if dn in lst:
            lst.remove(dn)


class Collection:
    def __init__(self, name: str, volume_size_limit: int):
        self.name = name
        self.volume_size_limit = volume_size_limit
        self.layouts: dict[str, VolumeLayout] = {}
        self._lock = threading.RLock()

    def get_or_create_layout(self, rp: ReplicaPlacement,
                             ttl: TTL) -> VolumeLayout:
        key = f"{rp}{ttl}"
        with self._lock:
            vl = self.layouts.get(key)
            if vl is None:
                vl = VolumeLayout(rp, ttl, self.volume_size_limit)
                self.layouts[key] = vl
            return vl

    def lookup(self, vid: int):
        for vl in list(self.layouts.values()):
            locs = vl.lookup(vid)
            if locs:
                return locs
        return []


class Topology(Node):
    node_type = "Topology"

    def __init__(self, id_: str = "topo",
                 volume_size_limit: int = 30 * 1024 * 1024 * 1024,
                 sequencer: MemorySequencer | None = None,
                 pulse_seconds: int = 5,
                 vid_stride: int = 1, vid_offset: int = 0):
        super().__init__(id_)
        self.volume_size_limit = volume_size_limit
        self.collections: dict[str, Collection] = {}
        self.ec_shard_map: dict[int, EcShardLocations] = {}
        self.sequencer = sequencer or MemorySequencer()
        self.pulse_seconds = pulse_seconds
        self._max_volume_id = 0
        # Geo id-space partitioning: with stride > 1 this master only
        # mints volume ids ≡ offset (mod stride), so two active/active
        # regions can never allocate the same id for different volumes
        # (a collision would make their lease planes fence each other).
        self.vid_stride = max(1, int(vid_stride))
        self.vid_offset = int(vid_offset) % self.vid_stride
        self._lock = threading.RLock()

    # -- tree helpers --------------------------------------------------------

    def get_or_create_data_center(self, id_: str) -> DataCenter:
        return self.get_or_create(id_, DataCenter)  # type: ignore

    # -- id issuance ---------------------------------------------------------

    # Set by a clustered master: volume-id issuance goes through raft
    # (topology/cluster_commands.go MaxVolumeIdCommand) so every master
    # agrees on the high-water mark.
    next_volume_id_hook = None

    def stride_align(self, vid: int) -> int:
        """Smallest id >= vid in this master's residue class (identity
        when unstrided).  Learned ids from heartbeats or mirrored
        volumes raise the high-water mark across BOTH classes, so the
        classes stay disjoint even as each region hosts the other's
        volumes."""
        if self.vid_stride <= 1:
            return vid
        return vid + (self.vid_offset - vid) % self.vid_stride

    def next_volume_id(self) -> int:
        if self.next_volume_id_hook is not None:
            return self.next_volume_id_hook()
        with self._lock:
            self._max_volume_id = self.stride_align(
                max(self._max_volume_id, self.max_volume_id) + 1)
            self.up_adjust_max_volume_id(self._max_volume_id)
            return self._max_volume_id

    def set_max_volume_id(self, vid: int) -> None:
        """Raft state-machine apply: raise the cluster-wide max."""
        with self._lock:
            self._max_volume_id = max(self._max_volume_id, vid)
            self.up_adjust_max_volume_id(self._max_volume_id)

    def next_file_key(self, count: int = 1) -> int:
        return self.sequencer.next_file_id(count)

    # -- collections / layouts ----------------------------------------------

    def get_or_create_layout(self, collection: str, rp: ReplicaPlacement,
                             ttl: TTL) -> VolumeLayout:
        with self._lock:
            col = self.collections.get(collection)
            if col is None:
                col = Collection(collection, self.volume_size_limit)
                self.collections[collection] = col
            return col.get_or_create_layout(rp, ttl)

    def delete_collection(self, name: str) -> None:
        with self._lock:
            self.collections.pop(name, None)

    def lookup(self, collection: str, vid: int) -> list[DataNode]:
        if collection:
            col = self.collections.get(collection)
            return col.lookup(vid) if col else []
        for col in list(self.collections.values()):
            locs = col.lookup(vid)
            if locs:
                return locs
        return []

    def lookup_ec_shards(self, vid: int) -> EcShardLocations | None:
        return self.ec_shard_map.get(vid)

    def ec_codec(self, vid: int) -> str:
        locs = self.ec_shard_map.get(vid)
        return locs.codec if locs is not None else "rs"

    # -- heartbeat sync ------------------------------------------------------

    def _layout_for(self, v) -> VolumeLayout:
        rp = ReplicaPlacement.from_byte(v.replica_placement)
        ttl = TTL.from_uint32(v.ttl)
        return self.get_or_create_layout(v.collection, rp, ttl)

    def register_volume(self, v, dn: DataNode) -> None:
        # Tree counters (up_adjust_counts walks shared ancestors) need
        # the topology lock: heartbeats from different volume servers
        # apply concurrently and '+=' would lose updates.
        with self._lock:
            self.sequencer.set_max(v.max_file_key)
            old = dn.volumes.get(v.id)
            if old is not None and (
                    old.replica_placement != v.replica_placement
                    or old.ttl != v.ttl
                    or old.collection != v.collection):
                # volume.configure.replication (or a ttl/collection
                # change) moved the volume to a different layout key:
                # the stale registration must go, or lookups keep
                # resolving through the old layout and never see
                # replicas registered under the new one.
                self._layout_for(old).unregister_volume(old, dn)
            dn.add_or_update_volume(v)
            self._layout_for(v).register_volume(v, dn)

    def unregister_volume(self, v, dn: DataNode) -> None:
        with self._lock:
            self._layout_for(v).unregister_volume(v, dn)
            dn.delete_volume(v.id)

    def sync_data_node_registration(self, volumes: list,
                                    dn: DataNode) -> tuple[list, list]:
        """Full-state heartbeat: returns (new, deleted) volume infos."""
        with self._lock:
            incoming = {v.id: v for v in volumes}
            existing = dict(dn.volumes)
            new, deleted = [], []
            for vid, v in incoming.items():
                self.register_volume(v, dn)
                if vid not in existing:
                    new.append(v)
            for vid, v in existing.items():
                if vid not in incoming:
                    self.unregister_volume(v, dn)
                    deleted.append(v)
            dn.last_seen = time.time()
            return new, deleted

    def incremental_sync(self, new_volumes: list, deleted_volumes: list,
                         dn: DataNode) -> None:
        with self._lock:
            for v in new_volumes:
                self.register_volume(v, dn)
            for v in deleted_volumes:
                self.unregister_volume(v, dn)
            dn.last_seen = time.time()

    # -- EC shards -----------------------------------------------------------

    def sync_data_node_ec_shards(self, shard_infos: list[tuple],
                                 dn: DataNode) -> None:
        """Full EC state: list of (vid, collection, shard_bits[, codec])."""
        incoming: dict[int, int] = {}
        for vid, collection, bits, *rest in shard_infos:
            incoming[vid] = bits
            self.register_ec_shards(vid, collection, bits, dn,
                                    codec=rest[0] if rest else None)
        for vid in list(dn.ec_shards):
            if vid not in incoming:
                self.unregister_ec_shards(vid, dn)

    def register_ec_shards(self, vid: int, collection: str, bits: int,
                           dn: DataNode, codec: str | None = None) -> None:
        with self._lock:
            locs = self.ec_shard_map.setdefault(
                vid, EcShardLocations(collection))
            if codec:
                locs.codec = codec
            old_bits = ShardBits(dn.ec_shards.get(vid, 0))
            new_bits = ShardBits(bits)
            for sid in new_bits.shard_ids():
                locs.add(sid, dn)
            for sid in old_bits.minus(new_bits).shard_ids():
                locs.remove(sid, dn)
            delta = new_bits.shard_id_count() - old_bits.shard_id_count()
            if delta:
                dn.up_adjust_counts(ec_delta=delta)
            dn.ec_shards[vid] = int(new_bits)

    def unregister_ec_shards(self, vid: int, dn: DataNode) -> None:
        with self._lock:
            bits = ShardBits(dn.ec_shards.pop(vid, 0))
            locs = self.ec_shard_map.get(vid)
            if locs is not None:
                for sid in bits.shard_ids():
                    locs.remove(sid, dn)
                if not any(locs.locations.values()):
                    self.ec_shard_map.pop(vid, None)
            if bits.shard_id_count():
                dn.up_adjust_counts(ec_delta=-bits.shard_id_count())

    # -- liveness ------------------------------------------------------------

    def register_data_node(self, dc: str, rack: str, ip: str, port: int,
                           public_url: str = "",
                           max_volume_count: int = 7) -> DataNode:
        with self._lock:
            dc_node = self.get_or_create_data_center(dc)
            rack_node = dc_node.get_or_create_rack(rack)
            dn = rack_node.get_or_create_data_node(
                f"{ip}:{port}", ip, port, public_url, max_volume_count)
        dn.last_seen = time.time()
        return dn

    def unregister_data_node(self, dn: DataNode) -> None:
        for v in list(dn.volumes.values()):
            self._layout_for(v).set_volume_unavailable(v.id, dn)
        for vid in list(dn.ec_shards):
            self.unregister_ec_shards(vid, dn)
        active = sum(1 for v in dn.volumes.values() if not v.read_only)
        dn.up_adjust_counts(volume_delta=-len(dn.volumes),
                            active_delta=-active,
                            max_delta=-dn.max_volume_count)
        rack = dn.get_rack()
        if rack is not None:
            rack.children.pop(dn.id, None)
        dn.parent = None

    def collect_dead_nodes(self, fresh_threshold: float | None = None
                           ) -> list[DataNode]:
        threshold = fresh_threshold if fresh_threshold is not None else \
            time.time() - 2 * self.pulse_seconds
        dead = [dn for dn in self.leaves() if dn.last_seen < threshold]
        return dead

    # -- writability ---------------------------------------------------------

    def layout_for(self, option: VolumeGrowOption) -> "VolumeLayout":
        """Resolve the layout for an assign option once — /dir/assign
        used to resolve it three times (writability check + pick),
        each with two ReplicaPlacement/TTL parses."""
        return self.get_or_create_layout(
            option.collection,
            ReplicaPlacement.parse(option.replica_placement),
            TTL.parse(option.ttl))

    def has_writable_volume(self, option: VolumeGrowOption) -> bool:
        return self.layout_for(option).active_volume_count(option) > 0

    def pick_for_write(self, count: int, option: VolumeGrowOption,
                       layout: "VolumeLayout | None" = None,
                       exclude=None) -> tuple[str, int, list[DataNode]]:
        """Returns (fid, count, locations) — the Assign core.
        `exclude(locations)` vetoes volumes (draining/low-disk
        steering, cluster/master.py)."""
        vl = layout if layout is not None else self.layout_for(option)
        vid, locs = vl.pick_for_write(option, exclude=exclude)
        if not locs:
            raise ValueError(f"volume {vid} has no locations")
        file_key = self.next_file_key(count)
        # math/rand cookie like the reference (topology.go:137) — the
        # cookie is a read-guessing deterrent, not a crypto secret.
        cookie = random.getrandbits(32)
        from ..core.types import format_file_id
        return format_file_id(vid, file_key, cookie), count, locs
