"""Cluster metadata & scheduling: the master's topology tree, volume
layouts, placement, and sequencers (reference: weed/topology/)."""
