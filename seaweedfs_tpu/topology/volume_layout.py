"""VolumeLayout: per-(collection, replica placement, ttl) volume state.

Port of weed/topology/volume_layout.go: tracks vid -> location list,
writable/readonly/oversized vid sets, and the state machine driven by
heartbeat registrations (a volume is writable only when enough replicas
are present, it isn't oversized, and no replica is read-only).
"""

from __future__ import annotations

import random
import threading

from ..core.replica_placement import ReplicaPlacement
from .node import DataNode


class VolumeLayout:
    def __init__(self, rp: ReplicaPlacement, ttl, volume_size_limit: int):
        self.rp = rp
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.vid2location: dict[int, list[DataNode]] = {}
        self.writables: list[int] = []
        self.readonly_volumes: set[int] = set()
        self.oversized_volumes: set[int] = set()
        self._lock = threading.RLock()

    # -- registration (heartbeat-driven) ------------------------------------

    def register_volume(self, v, dn: DataNode) -> None:
        with self._lock:
            locs = self.vid2location.setdefault(v.id, [])
            if dn not in locs:
                locs.append(dn)
            for vinfo in [dn.volumes.get(v.id, v)]:
                if vinfo.read_only:
                    self.readonly_volumes.add(v.id)
                else:
                    self.readonly_volumes.discard(v.id)
            if self._is_oversized(v):
                self.oversized_volumes.add(v.id)
            self._remember_oversized(v)
            if len(locs) == self.rp.copy_count() and self._is_writable(v):
                if v.id not in self.oversized_volumes:
                    self._set_writable(v.id)
            else:
                self._remove_writable(v.id)

    def _is_near_expiry(self, v) -> bool:
        """TTL layout steering: past half the TTL since the volume's
        newest write, new assignments go to a fresher volume so this
        one drains toward whole-volume retirement (the holder-side
        sweeper deletes it once fully expired) instead of being kept
        alive by a trickle of writes."""
        from ..storage import expiry as _expiry
        return _expiry.volume_near_expiry(
            self.ttl, getattr(v, "modified_at", 0))

    def unregister_volume(self, v, dn: DataNode) -> None:
        with self._lock:
            locs = self.vid2location.get(v.id, [])
            if dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid2location.pop(v.id, None)
                self._remove_writable(v.id)
                self.readonly_volumes.discard(v.id)
                self.oversized_volumes.discard(v.id)
            elif len(locs) < self.rp.copy_count():
                self._remove_writable(v.id)

    def _remember_oversized(self, v) -> None:
        if not self._is_oversized(v):
            self.oversized_volumes.discard(v.id)

    def _is_oversized(self, v) -> bool:
        return v.size >= self.volume_size_limit

    def _is_writable(self, v) -> bool:
        return not self._is_oversized(v) and not v.read_only \
            and not self._is_near_expiry(v)

    def _set_writable(self, vid: int) -> None:
        if vid not in self.writables:
            self.writables.append(vid)

    def _remove_writable(self, vid: int) -> None:
        if vid in self.writables:
            self.writables.remove(vid)

    def set_volume_unavailable(self, vid: int, dn: DataNode) -> bool:
        """Node died: drop its replica; unwritable if under-replicated."""
        with self._lock:
            locs = self.vid2location.get(vid)
            if locs and dn in locs:
                locs.remove(dn)
                if len(locs) < self.rp.copy_count():
                    self._remove_writable(vid)
                    return True
        return False

    def set_volume_capacity_full(self, vid: int) -> bool:
        with self._lock:
            self.oversized_volumes.add(vid)
            was = vid in self.writables
            self._remove_writable(vid)
            return was

    # -- queries -------------------------------------------------------------

    def pick_for_write(self, option=None,
                       rng: random.Random | None = None,
                       exclude=None) -> tuple[int, list[DataNode]]:
        """Random writable vid (+locations); optional DC/rack/node
        filter.  `exclude(locations) -> bool` vetoes candidate volumes
        (the master passes its draining/low-disk steering predicate:
        a replicated write to a vetoed volume would fail at fan-out)."""
        rng = rng or random
        with self._lock:
            if not self.writables:
                raise ValueError("no more writable volumes!")
            if option is None or not option.data_center:
                if exclude is None:
                    vid = self.writables[
                        rng.randrange(len(self.writables))]
                    return vid, list(self.vid2location.get(vid, []))
                candidates = [
                    v for v in self.writables
                    if not exclude(self.vid2location.get(v, []))]
                if not candidates:
                    raise ValueError(
                        "no writable volumes outside excluded nodes")
                vid = candidates[rng.randrange(len(candidates))]
                return vid, list(self.vid2location.get(vid, []))
            # Reservoir-sample a writable replica in the preferred place.
            counter = 0
            chosen = None
            for v in self.writables:
                if exclude is not None and \
                        exclude(self.vid2location.get(v, [])):
                    continue
                for dn in self.vid2location.get(v, []):
                    dc = dn.get_data_center()
                    if dc is None or dc.id != option.data_center:
                        continue
                    rack = dn.get_rack()
                    if option.rack and (rack is None or
                                        rack.id != option.rack):
                        continue
                    if option.data_node and dn.id != option.data_node:
                        continue
                    counter += 1
                    if rng.randrange(counter) < 1:
                        chosen = v
            if chosen is None:
                raise ValueError(
                    f"no writable volumes in {option.data_center}")
            return chosen, list(self.vid2location.get(chosen, []))

    def lookup(self, vid: int) -> list[DataNode]:
        with self._lock:
            return list(self.vid2location.get(vid, []))

    def active_volume_count(self, option=None) -> int:
        with self._lock:
            if option is None or not option.data_center:
                return len(self.writables)
            count = 0
            for v in self.writables:
                for dn in self.vid2location.get(v, []):
                    dc = dn.get_data_center()
                    if dc is None or dc.id != option.data_center:
                        continue
                    rack = dn.get_rack()
                    if option.rack and (rack is None or
                                        rack.id != option.rack):
                        continue
                    if option.data_node and dn.id != option.data_node:
                        continue
                    count += 1
            return count

    def stats(self) -> dict:
        with self._lock:
            return {
                "writables": list(self.writables),
                "readonly": sorted(self.readonly_volumes),
                "oversized": sorted(self.oversized_volumes),
                "volume_count": len(self.vid2location),
            }
