"""Topology node tree: Topology -> DataCenter -> Rack -> DataNode.

Behavioral port of weed/topology/node.go with up-propagated counters and
the weighted random placement picker (`PickNodesByWeight`,
node.go:65-125): candidates are weighted by free volume slots, drawn
without replacement, and the first node must additionally satisfy a
filter; earlier draws get priority.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Iterable


class Node:
    node_type = "Node"

    def __init__(self, id_: str):
        self.id = id_
        self.parent: Node | None = None
        self.children: dict[str, Node] = {}
        self.volume_count = 0
        self.active_volume_count = 0
        self.ec_shard_count = 0
        self.max_volume_count = 0
        self.max_volume_id = 0
        self._lock = threading.RLock()

    # -- counters ----------------------------------------------------------

    def free_space(self) -> int:
        # Matches FreeSpace(): EC shards consume slots at ~1/10 volume.
        free = self.max_volume_count - self.volume_count
        if self.ec_shard_count > 0:
            free -= self.ec_shard_count // 10 + 1
        return free

    def up_adjust_counts(self, volume_delta: int = 0, active_delta: int = 0,
                         ec_delta: int = 0, max_delta: int = 0) -> None:
        node: Node | None = self
        while node is not None:
            node.volume_count += volume_delta
            node.active_volume_count += active_delta
            node.ec_shard_count += ec_delta
            node.max_volume_count += max_delta
            node = node.parent

    def up_adjust_max_volume_id(self, vid: int) -> None:
        node: Node | None = self
        while node is not None:
            node.max_volume_id = max(node.max_volume_id, vid)
            node = node.parent

    # -- tree --------------------------------------------------------------

    def link_child(self, child: "Node") -> None:
        with self._lock:
            if child.id not in self.children:
                self.children[child.id] = child
                child.parent = self
                self.up_adjust_counts(
                    volume_delta=child.volume_count,
                    active_delta=child.active_volume_count,
                    ec_delta=child.ec_shard_count,
                    max_delta=child.max_volume_count)
                self.up_adjust_max_volume_id(child.max_volume_id)

    def unlink_child(self, child_id: str) -> None:
        with self._lock:
            child = self.children.pop(child_id, None)
            if child is not None:
                child.parent = None
                self.up_adjust_counts(
                    volume_delta=-child.volume_count,
                    active_delta=-child.active_volume_count,
                    ec_delta=-child.ec_shard_count,
                    max_delta=-child.max_volume_count)

    def get_or_create(self, id_: str, factory) -> "Node":
        with self._lock:
            node = self.children.get(id_)
            if node is None:
                node = factory(id_)
                self.link_child(node)
            return node

    def leaves(self) -> Iterable["DataNode"]:
        if isinstance(self, DataNode):
            yield self
            return
        for child in list(self.children.values()):
            yield from child.leaves()

    # -- placement ---------------------------------------------------------

    def pick_nodes_by_weight(self, number_of_nodes: int,
                             filter_first_fn: Callable[["Node"], str | None],
                             rng: random.Random | None = None,
                             ) -> tuple["Node", list["Node"]]:
        """Weighted random pick of `number_of_nodes` children.

        filter_first_fn returns None if the node qualifies as the first
        (main) node, else an error string.  Raises ValueError otherwise.
        """
        rng = rng or random
        candidates: list[Node] = []
        weights: list[int] = []
        for node in self.children.values():
            fs = node.free_space()
            if fs <= 0:
                continue
            candidates.append(node)
            weights.append(fs)
        if len(candidates) < number_of_nodes:
            raise ValueError(
                f"{self.id}: only {len(candidates)} candidates with free "
                f"space, need {number_of_nodes}")

        # Draw without replacement, probability proportional to free slots.
        total = sum(weights)
        sorted_candidates: list[Node] = []
        w = weights[:]
        for _ in range(len(candidates)):
            point = rng.randrange(total) if total > 0 else 0
            acc = 0
            for k, wk in enumerate(w):
                if wk and acc <= point < acc + wk:
                    sorted_candidates.append(candidates[k])
                    total -= wk
                    w[k] = 0
                    break
                acc += wk

        errs = []
        for k, node in enumerate(sorted_candidates):
            err = filter_first_fn(node)
            if err is None:
                if k >= number_of_nodes - 1:
                    rest = sorted_candidates[:number_of_nodes - 1]
                else:
                    rest = (sorted_candidates[:k] +
                            sorted_candidates[k + 1:number_of_nodes])
                return node, rest
            errs.append(f"{node.id}: {err}")
        raise ValueError("no matching node found!\n" + "\n".join(errs))

    def is_data_node(self) -> bool:
        return isinstance(self, DataNode)


class DataNode(Node):
    node_type = "DataNode"

    def __init__(self, id_: str, ip: str = "", port: int = 0,
                 public_url: str = "", max_volume_count: int = 7):
        super().__init__(id_)
        self.ip = ip
        self.port = port
        self.public_url = public_url or f"{ip}:{port}"
        self.max_volume_count = max_volume_count
        self.volumes: dict[int, object] = {}  # vid -> VolumeInfo
        self.ec_shards: dict[int, int] = {}   # vid -> ShardBits
        self.last_seen = 0.0
        # Lifecycle/capacity flags fed by heartbeats: a draining node
        # is leaving gracefully (rolling restart), a low_disk node has
        # breached its free-space reserve — neither takes new volumes
        # or write assignments (volume_growth / master._assign).
        self.draining = False
        self.low_disk = False

    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    def add_or_update_volume(self, v) -> bool:
        """Returns True if new."""
        is_new = v.id not in self.volumes
        if is_new:
            self.volumes[v.id] = v
            self.up_adjust_counts(volume_delta=1,
                                  active_delta=0 if v.read_only else 1)
            self.up_adjust_max_volume_id(v.id)
        else:
            old = self.volumes[v.id]
            if old.read_only != v.read_only:
                self.up_adjust_counts(
                    active_delta=-1 if v.read_only else 1)
            self.volumes[v.id] = v
        return is_new

    def delete_volume(self, vid: int):
        v = self.volumes.pop(vid, None)
        if v is not None:
            self.up_adjust_counts(volume_delta=-1,
                                  active_delta=0 if v.read_only else -1)
        return v

    def get_data_center(self) -> "DataCenter":
        node = self
        while node is not None and not isinstance(node, DataCenter):
            node = node.parent
        return node

    def get_rack(self) -> "Rack":
        node = self
        while node is not None and not isinstance(node, Rack):
            node = node.parent
        return node


class Rack(Node):
    node_type = "Rack"

    def get_or_create_data_node(self, id_: str, ip: str, port: int,
                                public_url: str = "",
                                max_volume_count: int = 7) -> DataNode:
        dn = self.children.get(id_)
        if dn is None:
            dn = DataNode(id_, ip, port, public_url, max_volume_count)
            self.link_child(dn)  # propagates counters incl. max slots
        else:
            if dn.max_volume_count != max_volume_count:
                dn.up_adjust_counts(
                    max_delta=max_volume_count - dn.max_volume_count)
                dn.max_volume_count = max_volume_count
        return dn  # type: ignore[return-value]


class DataCenter(Node):
    node_type = "DataCenter"

    def get_or_create_rack(self, id_: str) -> Rack:
        return self.get_or_create(id_, Rack)  # type: ignore[return-value]
