"""VolumeGrowth: find placement slots honoring replica placement.

Port of weed/topology/volume_growth.go findEmptySlotsForOneVolume: a
three-level weighted random search — pick DiffDataCenterCount+1 data
centers (the main one must have enough racks/free slots), then
DiffRackCount+1 racks in the main DC, then SameRackCount+1 servers in the
main rack — followed by one server from each other rack / other DC.
"""

from __future__ import annotations

import random

from ..core.replica_placement import ReplicaPlacement
from .node import DataCenter, DataNode, Rack
from .topology import Topology, VolumeGrowOption

# grow-by count per copy count (volume_growth.go:51-68)
_GROW_COUNTS = {1: 7, 2: 6, 3: 3}


def target_count_per_grow(copy_count: int) -> int:
    return _GROW_COUNTS.get(copy_count, 1)


class VolumeGrowth:
    def __init__(self, rng: random.Random | None = None):
        self.rng = rng or random.Random()

    @staticmethod
    def _node_eligible(n) -> bool:
        """A data node that may take a new volume replica: has a free
        slot and is neither draining (rolling restart) nor below its
        free-space reserve.  The rack/DC free-node COUNTS must apply
        the same veto as the server-level pick, or growth can commit
        to a rack whose only free nodes are all draining and fail."""
        return (n.free_space() >= 1
                and not getattr(n, "draining", False)
                and not getattr(n, "low_disk", False))

    def find_empty_slots_for_one_volume(
            self, topo: Topology,
            option: VolumeGrowOption) -> list[DataNode]:
        rp = ReplicaPlacement.parse(option.replica_placement)

        def dc_filter(node) -> str | None:
            if option.data_center and isinstance(node, DataCenter) and \
                    node.id != option.data_center:
                return f"not preferred data center {option.data_center}"
            if len(node.children) < rp.diff_rack_count + 1:
                return (f"only {len(node.children)} racks, need "
                        f"{rp.diff_rack_count + 1}")
            needed = rp.diff_rack_count + rp.same_rack_count + 1
            if node.free_space() < needed:
                return f"free {node.free_space()} < expected {needed}"
            possible_racks = 0
            for rack in node.children.values():
                free_nodes = sum(1 for n in rack.children.values()
                                 if self._node_eligible(n))
                if free_nodes >= rp.same_rack_count + 1:
                    possible_racks += 1
            if possible_racks < rp.diff_rack_count + 1:
                return (f"only {possible_racks} racks with >="
                        f"{rp.same_rack_count + 1} free nodes")
            return None

        main_dc, other_dcs = topo.pick_nodes_by_weight(
            rp.diff_data_center_count + 1, dc_filter, self.rng)

        def rack_filter(node) -> str | None:
            if option.rack and isinstance(node, Rack) and \
                    node.id != option.rack:
                return f"not preferred rack {option.rack}"
            if node.free_space() < rp.same_rack_count + 1:
                return (f"free {node.free_space()} < "
                        f"{rp.same_rack_count + 1}")
            if len(node.children) < rp.same_rack_count + 1:
                return (f"only {len(node.children)} data nodes")
            free_nodes = sum(1 for n in node.children.values()
                             if self._node_eligible(n))
            if free_nodes < rp.same_rack_count + 1:
                return f"only {free_nodes} eligible data nodes"
            return None

        main_rack, other_racks = main_dc.pick_nodes_by_weight(
            rp.diff_rack_count + 1, rack_filter, self.rng)

        def replica_filter(node) -> str | None:
            """Shared node veto: full, draining (rolling restart), or
            below its free-space reserve — none may take a new
            volume."""
            if node.free_space() < 1:
                return "no free slot"
            if getattr(node, "draining", False):
                return "draining"
            if getattr(node, "low_disk", False):
                return "below disk reserve"
            return None

        def server_filter(node) -> str | None:
            if option.data_node and isinstance(node, DataNode) and \
                    node.id != option.data_node:
                return f"not preferred data node {option.data_node}"
            return replica_filter(node)

        main_server, other_servers = main_rack.pick_nodes_by_weight(
            rp.same_rack_count + 1, server_filter, self.rng)

        servers: list[DataNode] = [main_server]  # type: ignore[list-item]
        servers.extend(other_servers)  # same rack
        for rack in other_racks:
            r, _ = rack.pick_nodes_by_weight(1, replica_filter,
                                             self.rng)
            servers.append(r)
        for dc in other_dcs:
            # One server anywhere in the other DC with a free slot
            # (same eligibility veto as the rack-level picks).
            candidates = [n for n in dc.leaves()
                          if self._node_eligible(n)]
            if not candidates:
                raise ValueError(f"no free server in data center {dc.id}")
            servers.append(self.rng.choice(candidates))
        return servers  # type: ignore[return-value]

    def grow_by_type(self, topo: Topology, option: VolumeGrowOption,
                     allocate_fn) -> int:
        """Grow target_count volumes; allocate_fn(vid, option, server) does
        the actual volume-server RPC.  Returns #volumes grown."""
        rp = ReplicaPlacement.parse(option.replica_placement)
        target = target_count_per_grow(rp.copy_count())
        grown = 0
        for _ in range(target):
            try:
                servers = self.find_empty_slots_for_one_volume(topo, option)
            except ValueError:
                break
            vid = topo.next_volume_id()
            ok = True
            for server in servers:
                try:
                    allocate_fn(vid, option, server)
                except Exception:  # noqa: BLE001
                    ok = False
                    break
            if ok:
                grown += 1
        return grown
