"""File-id sequencers (weed/sequence/): monotonically increasing needle keys.

MemorySequencer mirrors the reference's default: in-memory counter,
optionally checkpointed to a metadata file in steps of 100 so a restart
never reissues keys (sequence.go / memory_sequencer.go).
"""

from __future__ import annotations

import os
import threading

STEP = 100


class MemorySequencer:
    def __init__(self, meta_path: str | None = None):
        self._lock = threading.Lock()
        self.meta_path = meta_path
        self.counter = 1
        self._ceiling = 0
        if meta_path and os.path.exists(meta_path):
            with open(meta_path) as f:
                try:
                    self.counter = int(f.read().strip() or 1)
                except ValueError:
                    self.counter = 1
        self._maybe_checkpoint()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self.counter
            self.counter += count
            self._maybe_checkpoint()
            return start

    def set_max(self, seen: int) -> None:
        """Raise the counter past ids observed in heartbeats."""
        with self._lock:
            if seen >= self.counter:
                self.counter = seen + 1
                self._maybe_checkpoint()

    def peek(self) -> int:
        with self._lock:
            return self.counter

    def _maybe_checkpoint(self) -> None:
        if self.meta_path and self.counter >= self._ceiling:
            self._ceiling = self.counter + STEP
            tmp = self.meta_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self._ceiling))
            os.replace(tmp, self.meta_path)
