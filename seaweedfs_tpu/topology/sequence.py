"""File-id sequencers (weed/sequence/): monotonically increasing needle keys.

MemorySequencer mirrors the reference's default: in-memory counter,
optionally checkpointed to a metadata file in steps of 100 so a restart
never reissues keys (sequence.go / memory_sequencer.go).
"""

from __future__ import annotations

import os
import threading

STEP = 100


class MemorySequencer:
    def __init__(self, meta_path: str | None = None):
        self._lock = threading.Lock()
        self.meta_path = meta_path
        self.counter = 1
        self._ceiling = 0
        if meta_path and os.path.exists(meta_path):
            with open(meta_path) as f:
                try:
                    self.counter = int(f.read().strip() or 1)
                except ValueError:
                    self.counter = 1
        self._maybe_checkpoint()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self.counter
            self.counter += count
            self._maybe_checkpoint()
            return start

    def set_max(self, seen: int) -> None:
        """Raise the counter past ids observed in heartbeats."""
        with self._lock:
            if seen >= self.counter:
                self.counter = seen + 1
                self._maybe_checkpoint()

    def peek(self) -> int:
        with self._lock:
            return self.counter

    def _maybe_checkpoint(self) -> None:
        if self.meta_path and self.counter >= self._ceiling:
            self._ceiling = self.counter + STEP
            tmp = self.meta_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self._ceiling))
            os.replace(tmp, self.meta_path)


class RaftSequencer:
    """Consensus-replicated block sequencer — the HA analog of the
    reference's etcd sequencer (weed/sequence/etcd_sequencer.go: a
    shared counter advanced in blocks through etcd).  Here the blocks
    ride the master's own raft log: the leader commits a new ceiling
    before handing out ids below it, so after a failover no committed
    id range can ever be re-issued — even before the first heartbeat's
    max_file_key arrives to raise the floor.

    `alloc_fn(min_start, n) -> start` must commit `start + n` as the new
    cluster ceiling (with `start >= min_start`) through consensus and
    return the block start; only the raft leader can succeed.
    """

    BLOCK = 10_000

    def __init__(self, alloc_fn, block: int = BLOCK):
        self._alloc = alloc_fn
        self.block = block
        self._lock = threading.Lock()
        self._alloc_lock = threading.Lock()
        self._lo = 0    # next id to hand out
        self._hi = 0    # end of the committed block (exclusive)
        self._floor = 1  # ids at/below floor-1 are burned (heartbeats)

    def next_file_id(self, count: int = 1) -> int:
        # _lock is only ever held for field flips, NEVER across the
        # consensus call: set_max is called from the heartbeat path
        # while topo._lock is held, and the raft applier needs
        # topo._lock — holding _lock through alloc_fn's barrier would
        # close that loop into a three-way deadlock.
        while True:
            with self._lock:
                if self._lo < self._floor:
                    self._lo = min(self._floor, self._hi)
                if self._lo + count <= self._hi:
                    out = self._lo
                    self._lo += count
                    return out
                floor = max(self._floor, self._lo)
            with self._alloc_lock:  # one allocation in flight
                with self._lock:
                    if self._lo + count <= self._hi:
                        continue  # another thread refilled meanwhile
                n = max(self.block, count)
                start = self._alloc(floor, n)
                with self._lock:
                    self._lo, self._hi = start, start + n
                # Loop: the floor may have risen during the alloc; the
                # re-check clamps before handing anything out.

    def set_max(self, seen: int) -> None:
        """Heartbeat floor (topology.go adopting max_file_key): ids up
        to `seen` exist somewhere in the cluster."""
        with self._lock:
            if seen + 1 > self._floor:
                self._floor = seen + 1

    def peek(self) -> int:
        with self._lock:
            return max(self._lo, self._floor)
