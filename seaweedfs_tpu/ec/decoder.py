"""EC decode: shard files -> `.dat` + `.idx` (ec.decode reverse path).

Port of weed/storage/erasure_coding/ec_decoder.go.
"""

from __future__ import annotations

import os
import shutil
import time

from . import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext
from ..stats.metrics import observe_ec_stage
from ..trace import span as trace_span
from ..core import idx as idx_mod
from ..core import types as t
from ..core.needle import get_actual_size
from ..core.super_block import SuperBlock


def iterate_ecj_file(base_file_name: str):
    """Yield deleted needle ids from the `.ecj` journal."""
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        while True:
            buf = f.read(t.NEEDLE_ID_SIZE)
            if len(buf) != t.NEEDLE_ID_SIZE:
                return
            yield t.get_uint64(buf)


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """.ecx + .ecj -> .idx (WriteIdxFileFromEcIndex): copy then tombstones."""
    shutil.copyfile(base_file_name + ".ecx", base_file_name + ".idx")
    with open(base_file_name + ".idx", "ab") as out:
        for key in iterate_ecj_file(base_file_name):
            idx_mod.append_entry(out, key, 0, t.TOMBSTONE_FILE_SIZE)


def read_ec_volume_version(base_file_name: str) -> int:
    """Volume version from the superblock at the head of .ec00."""
    with open(base_file_name + to_ext(0), "rb") as f:
        return SuperBlock.from_bytes(f.read(64 * 1024)).version


def find_dat_file_size(base_file_name: str) -> int:
    """Max (offset + record size) over live .ecx entries (FindDatFileSize)."""
    version = read_ec_volume_version(base_file_name)
    dat_size = 0
    with open(base_file_name + ".ecx", "rb") as f:
        for e in idx_mod.iter_index(f):
            if t.size_is_deleted(e.size):
                continue
            stop = e.offset + get_actual_size(e.size, version)
            dat_size = max(dat_size, stop)
    return dat_size


def write_dat_file(base_file_name: str, dat_file_size: int,
                   large_block_size: int = LARGE_BLOCK_SIZE,
                   small_block_size: int = SMALL_BLOCK_SIZE) -> None:
    """Interleave-copy .ec00-.ec09 back into a .dat of the given size.
    Host-side stage of ec.to_volume — timed into the EC stage histogram
    (and spanned on a trace) alongside the device stages, so decode
    cost is attributable next to kernel and fan-out cost."""
    t0 = time.perf_counter()
    with trace_span("ec.dat_rebuild", bytes=dat_file_size):
        _write_dat_file(base_file_name, dat_file_size,
                        large_block_size, small_block_size)
    observe_ec_stage("dat_rebuild", time.perf_counter() - t0,
                     dat_file_size)


def _write_dat_file(base_file_name: str, dat_file_size: int,
                    large_block_size: int,
                    small_block_size: int) -> None:
    ins = [open(base_file_name + to_ext(i), "rb")
           for i in range(DATA_SHARDS)]
    try:
        with open(base_file_name + ".dat", "wb") as out:
            remaining = dat_file_size
            while remaining >= DATA_SHARDS * large_block_size:
                for f in ins:
                    buf = f.read(large_block_size)
                    if len(buf) != large_block_size:
                        raise ValueError("short large-block read")
                    out.write(buf)
                    remaining -= large_block_size
            while remaining > 0:
                for f in ins:
                    to_read = min(remaining, small_block_size)
                    if to_read <= 0:
                        break
                    buf = f.read(to_read)
                    if len(buf) != to_read:
                        raise ValueError("short small-block read")
                    out.write(buf)
                    remaining -= to_read
    finally:
        for f in ins:
            f.close()
