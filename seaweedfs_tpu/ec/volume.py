"""EcVolume: runtime access to an erasure-coded volume's local shards.

Port of the read path in weed/storage/erasure_coding/ec_volume.go and
store_ec.go: binary-search the `.ecx` for the needle, map its byte range to
shard intervals, read each interval from a local shard — and when a shard
is missing, reconstruct exactly that interval from >= 10 surviving shards
(the degraded-read path that the TPU batches into one GF matmul).

In the clustered setting the "fetch other shards" step goes over the wire
(cluster layer); here the EcVolume handles whatever shards are local and
exposes the same reconstruction hook.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from . import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext
from ..codecs import get_codec
from ..core import types as t
from ..core.needle import Needle, get_actual_size
from ..ops.erasure import ErasureCoder, new_coder
from ..stats.metrics import ec_repair_read_bytes_total
from .locate import Interval, locate_data
from .volume_info import ec_codec_name


class NeedleNotFound(Exception):
    pass


class ShardsUnavailable(Exception):
    pass


class EcVolumeShard:
    """One local `.ec??` file."""

    def __init__(self, base_file_name: str, shard_id: int):
        self.shard_id = shard_id
        self.path = base_file_name + to_ext(shard_id)
        self._f = open(self.path, "rb")
        self.size = os.path.getsize(self.path)

    def read_at(self, offset: int, size: int) -> bytes:
        return os.pread(self._f.fileno(), size, offset)

    def close(self) -> None:
        self._f.close()


class EcVolume:
    def __init__(self, base_file_name: str, vid: int = 0,
                 coder: ErasureCoder | None = None,
                 version: int | None = None,
                 large_block_size: int = LARGE_BLOCK_SIZE,
                 small_block_size: int = SMALL_BLOCK_SIZE,
                 codec=None):
        self.base_file_name = base_file_name
        self.vid = vid
        self.large_block_size = large_block_size
        self.small_block_size = small_block_size
        # The codec rides the .vif sidecar (like the needle version):
        # an explicit coder wins, then an explicit codec name, then
        # whatever the shards were generated with.
        if coder is not None:
            self.coder = coder
            self.codec = getattr(coder, "codec", None) or get_codec("rs")
        else:
            self.codec = get_codec(codec or ec_codec_name(base_file_name))
            self.coder = new_coder(codec=self.codec)
        self.shards: dict[int, EcVolumeShard] = {}
        self._ecx = open(base_file_name + ".ecx", "r+b")
        self.ecx_size = os.path.getsize(base_file_name + ".ecx")
        self._ecj_lock = threading.Lock()
        self.load_local_shards()
        # Version detection is lazy: a server holding only parity shards
        # can still mount and serve raw shard bytes without knowing it.
        self._version = version

    @property
    def version(self) -> int:
        if self._version is None:
            self._version = self._detect_version()
        return self._version

    def _detect_version(self) -> int:
        """Volume version: .vif sidecar, else shard 0's superblock, else
        reconstruct the superblock bytes from >=10 survivors.

        A wrong version mis-sizes every record, so no silent default.
        """
        from ..core.super_block import SuperBlock
        from .decoder import read_ec_volume_version
        from .volume_info import load_volume_info
        info = load_volume_info(self.base_file_name)
        if info and "version" in info:
            return int(info["version"])
        try:
            return read_ec_volume_version(self.base_file_name)
        except FileNotFoundError:
            pass
        head = self._reconstruct_interval(0, 0, 64)
        return SuperBlock.from_bytes(head).version

    # -- shard registry ----------------------------------------------------

    def load_local_shards(self) -> list[int]:
        found = []
        for sid in range(self.codec.total_shards):
            if sid in self.shards:
                continue
            if os.path.exists(self.base_file_name + to_ext(sid)):
                self.shards[sid] = EcVolumeShard(self.base_file_name, sid)
                found.append(sid)
        return found

    def shard_size(self) -> int:
        if not self.shards:
            return 0
        return next(iter(self.shards.values())).size

    # -- .ecx search --------------------------------------------------------

    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        """Binary search the sorted index. Returns (offset, size)."""
        entry, _pos = self._search_ecx(needle_id)
        if entry is None:
            raise NeedleNotFound(f"needle {needle_id:x} not in ecx")
        if t.size_is_deleted(entry.size):
            raise NeedleNotFound(f"needle {needle_id:x} deleted")
        return entry.offset, entry.size

    def _search_ecx(self, needle_id: int):
        lo, hi = 0, self.ecx_size // t.NEEDLE_MAP_ENTRY_SIZE
        fd = self._ecx.fileno()
        while lo < hi:
            mid = (lo + hi) // 2
            buf = os.pread(fd, t.NEEDLE_MAP_ENTRY_SIZE,
                           mid * t.NEEDLE_MAP_ENTRY_SIZE)
            e = t.NeedleMapEntry.from_bytes(buf)
            if e.key == needle_id:
                return e, mid
            if e.key < needle_id:
                lo = mid + 1
            else:
                hi = mid
        return None, -1

    # -- reads ---------------------------------------------------------------

    def locate_needle(self, needle_id: int) -> tuple[int, int, list[Interval]]:
        offset, size = self.find_needle_from_ecx(needle_id)
        total = get_actual_size(size, self.version)
        dat_size = DATA_SHARDS * self.shard_size()
        intervals = locate_data(self.large_block_size, self.small_block_size,
                                dat_size, offset, total)
        return offset, size, intervals

    def read_interval(self, interval: Interval) -> bytes:
        sid, off = interval.to_shard_id_and_offset(self.large_block_size,
                                                   self.small_block_size)
        shard = self.shards.get(sid)
        if shard is not None:
            buf = shard.read_at(off, interval.size)
            if len(buf) == interval.size:
                return buf
        return self._reconstruct_interval(sid, off, interval.size)

    def _reconstruct_interval(self, missing_sid: int, offset: int,
                              size: int) -> bytes:
        """Degraded read: rebuild one shard interval from survivors.

        Reference: store_ec.go:322 recoverOneRemoteEcShardInterval — there
        the survivors are fetched over gRPC; locally we use whatever shard
        files exist.  The read set follows the codec's repair plan —
        local group first (5 reads for LRC), global fallback — and a
        shard that comes up short is excluded and the plan re-solved,
        so one truncated file degrades the read cost, never the read.
        """
        excluded: set[int] = set()
        while True:
            usable = tuple(s for s in self.shards
                           if s != missing_sid and s not in excluded)
            try:
                plan = self.codec.repair_plan(usable, [missing_sid])
            except ValueError:
                raise ShardsUnavailable(
                    f"cannot reconstruct shard {missing_sid}: only "
                    f"{len(usable)} survivors") from None
            have: dict[int, np.ndarray] = {}
            for sid in plan[0].reads:
                buf = self.shards[sid].read_at(offset, size)
                if len(buf) != size:
                    excluded.add(sid)
                    break
                have[sid] = np.frombuffer(buf, dtype=np.uint8)
            if len(have) == len(plan[0].reads):
                break
        ec_repair_read_bytes_total.inc(size * len(have),
                                       codec=self.codec.name)
        rec = self.coder.reconstruct(have, wanted=[missing_sid])
        return np.asarray(rec[missing_sid]).tobytes()

    def read_needle(self, needle_id: int) -> Needle:
        _offset, size, intervals = self.locate_needle(needle_id)
        blob = b"".join(self.read_interval(iv) for iv in intervals)
        return Needle.from_bytes(blob, self.version)

    # -- deletes -------------------------------------------------------------

    def delete_needle(self, needle_id: int) -> None:
        """Tombstone the .ecx entry in place + append id to the .ecj."""
        entry, pos = self._search_ecx(needle_id)
        if entry is None:
            return
        size_off = (pos * t.NEEDLE_MAP_ENTRY_SIZE + t.NEEDLE_ID_SIZE +
                    t.OFFSET_SIZE)
        os.pwrite(self._ecx.fileno(),
                  t.size_to_bytes(t.TOMBSTONE_FILE_SIZE), size_off)
        with self._ecj_lock:
            with open(self.base_file_name + ".ecj", "ab") as f:
                f.write(t.put_uint64(needle_id))

    def close(self) -> None:
        self._ecx.close()
        for s in self.shards.values():
            s.close()
        self.shards.clear()
