"""ShardBits: bitmask of shard ids held per (server, volume).

Port of weed/storage/erasure_coding/ec_volume_info.go:61-113.
"""

from __future__ import annotations

from . import DATA_SHARDS, TOTAL_SHARDS


class ShardBits(int):
    def add_shard_id(self, sid: int) -> "ShardBits":
        return ShardBits(self | (1 << sid))

    def remove_shard_id(self, sid: int) -> "ShardBits":
        return ShardBits(self & ~(1 << sid))

    def has_shard_id(self, sid: int) -> bool:
        return bool(self & (1 << sid))

    def shard_ids(self, total_shards: int = TOTAL_SHARDS) -> list[int]:
        """Held shard ids; `total_shards` bounds the scan for codecs
        whose shard count differs from RS(10,4)'s 14."""
        return [sid for sid in range(max(total_shards, self.bit_length()))
                if self.has_shard_id(sid)]

    def shard_id_count(self) -> int:
        return bin(self).count("1")

    def plus(self, other: "ShardBits | int") -> "ShardBits":
        return ShardBits(self | other)

    def minus(self, other: "ShardBits | int") -> "ShardBits":
        return ShardBits(self & ~other)

    def minus_parity_shards(self) -> "ShardBits":
        out = self
        for sid in range(DATA_SHARDS, TOTAL_SHARDS):
            out = out.remove_shard_id(sid)
        return out
