"""Shard-level integrity sidecar: per-block CRC32-C checksums (`.ecc`).

Needle records carry their own CRC, but an EC shard file is opaque
striped bytes — a flipped bit in a parity shard corrupts nothing a
needle read would ever check until a rebuild silently propagates it.
The `.ecc` sidecar closes that gap: one CRC32-C per `BLOCK`-sized block
of each shard file, computed from the bytes the encoder *intended* to
write (before they hit the disk), so anything that diverges later —
bit-rot, a torn write, a bad cable — is detectable by the background
scrubber (storage/scrub.py) without reading any other shard.

Format (JSON, atomic tmp+rename like the other sidecars):

    {"block": 1048576, "shards": {"0": ["9ae1f203", ...], ...}}

Only locally-present shards need entries; a shard that arrives without
one (e.g. pulled by VolumeEcShardsCopy) is checksummed on its first
scrub (trust-on-first-scrub), after which divergence is detected.
"""

from __future__ import annotations

import json
import os
import threading

from . import SMALL_BLOCK_SIZE
from ..core.crc import crc32c
from ..stats.contention import MeteredLock

# Sidecar updates are load-modify-save: every writer (encode, shard
# receive, delete, the scrub's trust-on-first-scrub fingerprinting)
# must serialize per volume base or concurrent savers lose each
# other's entries.  Metered (stats/contention.py): a scrub sweep
# racing shard receives convoys exactly here, and that wait must show
# in SeaweedFS_lock_wait_seconds{lock="integrity.ecc"} — one shared
# label for every volume's lock, so cardinality stays flat.
_ECC_LOCKS: dict[str, MeteredLock] = {}
_ECC_LOCKS_GUARD = threading.Lock()


def ecc_lock(base_file_name: str) -> MeteredLock:
    """The process-wide lock guarding one volume's `.ecc` sidecar."""
    with _ECC_LOCKS_GUARD:
        return _ECC_LOCKS.setdefault(base_file_name,
                                     MeteredLock("integrity.ecc"))

# Checksum granularity: one CRC per small-block row keeps the sidecar
# tiny (8 hex chars per MB) while localizing damage to a single
# reconstructable interval.
BLOCK = SMALL_BLOCK_SIZE

ECC_EXT = ".ecc"


class BlockCrcAccumulator:
    """Streaming per-block CRC32-C: feed() arbitrary write-sized
    buffers, get one CRC per BLOCK bytes out.  Used by the encoder to
    checksum shard bytes as they stream past — no second read pass."""

    def __init__(self, block: int = BLOCK):
        self.block = block
        self._crcs: list[int] = []
        self._cur = 0
        self._fill = 0

    def feed(self, buf: bytes) -> None:
        mv = memoryview(buf)
        while len(mv):
            take = min(self.block - self._fill, len(mv))
            self._cur = crc32c(bytes(mv[:take]), self._cur)
            self._fill += take
            mv = mv[take:]
            if self._fill == self.block:
                self._crcs.append(self._cur)
                self._cur = 0
                self._fill = 0

    def finalize(self) -> list[int]:
        if self._fill:
            self._crcs.append(self._cur)
            self._cur = 0
            self._fill = 0
        return list(self._crcs)


def file_block_crcs(path: str, block: int = BLOCK,
                    limiter=None) -> list[int]:
    """Per-block CRCs of an existing shard file (the TOFU path and the
    verifier's reread).  `limiter` is an optional RateLimiter whose
    take(nbytes) throttles the disk reads."""
    acc = BlockCrcAccumulator(block)
    with open(path, "rb") as f:
        while True:
            buf = f.read(block)
            if not buf:
                break
            if limiter is not None:
                limiter.take(len(buf))
            acc.feed(buf)
    return acc.finalize()


class ShardChecksums:
    """The `.ecc` sidecar of one EC volume base name."""

    def __init__(self, base_file_name: str, block: int = BLOCK,
                 shards: dict[int, list[int]] | None = None):
        self.base = base_file_name
        self.block = block
        self.shards: dict[int, list[int]] = shards or {}

    @property
    def path(self) -> str:
        return self.base + ECC_EXT

    @classmethod
    def load(cls, base_file_name: str) -> "ShardChecksums":
        """Load the sidecar; a missing or unparseable file yields an
        empty set (every shard falls back to trust-on-first-scrub)."""
        path = base_file_name + ECC_EXT
        try:
            with open(path) as f:
                doc = json.load(f)
            shards = {int(sid): [int(c, 16) for c in crcs]
                      for sid, crcs in doc.get("shards", {}).items()}
            return cls(base_file_name, block=int(doc.get("block", BLOCK)),
                       shards=shards)
        except (OSError, ValueError, KeyError):
            return cls(base_file_name)

    def get(self, sid: int) -> list[int] | None:
        return self.shards.get(sid)

    def set_shard(self, sid: int, crcs: list[int]) -> None:
        self.shards[sid] = list(crcs)

    def set_block(self, sid: int, block_index: int, crc: int) -> None:
        crcs = self.shards.get(sid)
        if crcs is not None and 0 <= block_index < len(crcs):
            crcs[block_index] = crc

    def drop_shard(self, sid: int) -> None:
        self.shards.pop(sid, None)

    def save(self) -> None:
        doc = {"block": self.block,
               "shards": {str(sid): [f"{c:08x}" for c in crcs]
                          for sid, crcs in sorted(self.shards.items())}}
        # Unique temp per writer: even under ecc_lock, a crashed
        # writer's stale staging file must never be renamed over by
        # (or collide with) a later one.
        tmp = f"{self.path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        finally:
            try:
                os.remove(tmp)
            except FileNotFoundError:
                pass

    def verify_file(self, sid: int, path: str,
                    limiter=None) -> list[int]:
        """Compare a shard file against its recorded CRCs.  Returns the
        list of corrupt block indices (a length mismatch marks the
        shorter/garbled tail blocks corrupt too)."""
        want = self.shards.get(sid)
        if want is None:
            return []
        bad: list[int] = []
        i = 0
        with open(path, "rb") as f:
            while True:
                buf = f.read(self.block)
                if not buf:
                    break
                if limiter is not None:
                    limiter.take(len(buf))
                if i >= len(want) or crc32c(buf) != want[i]:
                    bad.append(i)
                i += 1
        # Blocks the record promises but the file no longer has.
        bad.extend(range(i, len(want)))
        return bad
