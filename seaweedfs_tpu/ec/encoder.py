"""EC encode / rebuild: `.dat` -> `.ec00`-`.ec13`, `.idx` -> `.ecx`.

Behavioral port of weed/storage/erasure_coding/ec_encoder.go with the byte
crunching routed through the pluggable ErasureCoder (numpy / XLA / Pallas
MXU kernel).  Two TPU-minded deviations from the reference's mechanics that
keep outputs byte-identical:

- the reference streams 10 x 256KB buffers per encoder call
  (encodeDataOneBatch); we read much larger contiguous chunks per shard row
  and feed the whole (10, chunk) matrix to one kernel launch — same bytes,
  ~chunk/256KB fewer launches;
- rebuild ignores the block layout entirely: byte column p across shard
  files is one RS codeword, so reconstruction is a flat column-parallel
  matmul over any chunk size.
"""

from __future__ import annotations

import os

import numpy as np

from . import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext
from .integrity import BlockCrcAccumulator, ShardChecksums, ecc_lock
from .volume_info import ec_codec_name, update_volume_info
from ..codecs import get_codec
from ..fault import registry as _fault
from ..ops.erasure import ErasureCoder, new_coder
from ..stats.metrics import ec_repair_read_bytes_total
from ..storage.needle_map import MemDb

# Per-shard contiguous bytes handed to one coder call. Must divide
# LARGE_BLOCK_SIZE and be a multiple of SMALL_BLOCK_SIZE.
DEFAULT_CHUNK = 4 * 1024 * 1024


def write_sorted_file_from_idx(base_file_name: str,
                               ext: str = ".ecx") -> None:
    """Generate the sorted `.ecx` from the `.idx` (WriteSortedFileFromIdx)."""
    with open(base_file_name + ".idx", "rb") as f:
        db = MemDb.from_idx(f)
    with open(base_file_name + ext, "wb") as out:
        out.write(db.to_sorted_bytes())


def _shard_write(f, sid: int, buf: bytes, accs) -> None:
    """One shard-file write: feed the integrity accumulator with the
    TRUE bytes first, then write — possibly through the volume.corrupt
    bit-rot injector — so the recorded `.ecc` checksums describe what
    the encoder intended and any on-disk divergence is detectable."""
    if accs is not None:
        accs[sid].feed(buf)
    if _fault.ARMED and buf:
        try:
            _fault.hit("volume.corrupt", shard=sid)
        except _fault.FaultInjected:
            b = bytearray(buf)
            b[0] ^= 0xFF
            buf = bytes(b)
    f.write(buf)


def write_ec_files(base_file_name: str, coder: ErasureCoder | None = None,
                   large_block_size: int = LARGE_BLOCK_SIZE,
                   small_block_size: int = SMALL_BLOCK_SIZE,
                   chunk_size: int = DEFAULT_CHUNK,
                   codec=None) -> None:
    """Generate the shard files from the .dat (WriteEcFiles), plus the
    `.ecc` per-block checksum sidecar the background scrub verifies
    shards against (ec/integrity.py).  `codec` selects the erasure
    codec ("rs" default, "lrc", ...); shard-file count, parity rows
    and the recorded `.vif` codec id all derive from it."""
    if coder is None:
        coder = new_coder(codec=codec)
    cd = getattr(coder, "codec", None) or get_codec("rs")
    if codec is not None and get_codec(codec).name != cd.name:
        raise ValueError(
            f"coder carries codec {cd.name!r} but {get_codec(codec).name!r} "
            "was requested")
    if cd.data_shards != DATA_SHARDS:
        # The shard-file block layout (locate.py) row-stripes over
        # exactly DATA_SHARDS columns; codecs may vary parity shape
        # freely but not the data stripe width.
        raise ValueError(
            f"codec {cd.name!r}: data shards must be {DATA_SHARDS} for "
            "the weed shard layout")
    dat_size = os.path.getsize(base_file_name + ".dat")
    outputs = [open(base_file_name + to_ext(i), "wb")
               for i in range(cd.total_shards)]
    # Fused path: the device coder emits every shard's per-block
    # CRC32-C alongside the parity (ops/crc_fold.py) — no CPU pass over
    # the shard bytes.  Requires the DEFAULT block geometry: only then
    # are `_chunk_reader` widths 1MB-block multiples (except the final
    # tail), which keeps the kernel partials block-aligned.  Custom
    # large/small block sizes (or the SEAWEEDFS_TPU_EC_FUSED_CRC=0
    # kill switch) fall back to the byte accumulators — a mid-stream
    # unaligned chunk would abort the encode in feed_tiles.
    from ..ops.crc_fold import fused_crc_enabled
    fused = (fused_crc_enabled()
             and getattr(coder, "fused_crc_ok", False)
             and chunk_size % SMALL_BLOCK_SIZE == 0
             and small_block_size == SMALL_BLOCK_SIZE
             and large_block_size % SMALL_BLOCK_SIZE == 0)
    accs = None if fused \
        else [BlockCrcAccumulator() for _ in range(cd.total_shards)]
    try:
        with open(base_file_name + ".dat", "rb") as dat:
            crc_map = _encode_dat_file(
                dat, dat_size, coder, outputs,
                large_block_size, small_block_size, chunk_size,
                accs=accs)
    finally:
        for f in outputs:
            f.close()
    # The codec id travels in the .vif like the needle version: any
    # server that later mounts these shards must pick the matching
    # decode matrices.
    update_volume_info(base_file_name, codec=cd.name)
    with ecc_lock(base_file_name):
        ecc = ShardChecksums(base_file_name)
        for sid in range(cd.total_shards):
            ecc.set_shard(sid, crc_map[sid] if crc_map is not None
                          else accs[sid].finalize())
        ecc.save()


def _encode_dat_file(dat, dat_size: int, coder: ErasureCoder, outputs,
                     large: int, small: int, chunk_size: int,
                     accs=None):
    chunks = _chunk_reader(dat, dat_size, large, small, chunk_size)
    return _pipelined_encode(chunks, coder, outputs, accs=accs)


def _chunk_reader(dat, dat_size: int, large: int, small: int,
                  chunk_size: int):
    """Yield (DATA_SHARDS, n) uint8 stripe chunks in shard-file order —
    the read side of the pipeline, byte-identical chunking to the
    previous serial encoder."""
    fd = dat.fileno()
    remaining = dat_size
    processed = 0
    # Large-block rows while more than one full large row remains
    # (strictly greater, like the reference encodeDatFile loop).
    chunk = min(chunk_size, large)
    if large % chunk != 0:
        raise ValueError(f"chunk {chunk} must divide block size {large}")
    while remaining > large * DATA_SHARDS:
        for b in range(0, large, chunk):
            data = np.zeros((DATA_SHARDS, chunk), dtype=np.uint8)
            for i in range(DATA_SHARDS):
                raw = os.pread(fd, chunk, processed + i * large + b)
                if raw:
                    data[i, :len(raw)] = np.frombuffer(raw,
                                                       dtype=np.uint8)
            yield data
        remaining -= large * DATA_SHARDS
        processed += large * DATA_SHARDS
    # Small-block rows, many per coder call: a volume under 10GB is
    # ENTIRELY 1MB small rows, and a (10, 1MB) kernel launch is
    # dispatch-bound on TPU (~13ms fixed cost over the tunnel).  Rows
    # are column-independent, so K consecutive rows stack into one
    # (10, K*small) call — same bytes, K fewer launches; each shard's
    # blocks from consecutive rows are consecutive in its shard file.
    rows_per_call = max(1, chunk_size // small)
    while remaining > 0:
        row_bytes = small * DATA_SHARDS
        nrows = min(rows_per_call, -(-remaining // row_bytes))
        data = np.zeros((DATA_SHARDS, nrows * small), dtype=np.uint8)
        for r in range(nrows):
            base = processed + r * row_bytes
            col = r * small
            for i in range(DATA_SHARDS):
                raw = os.pread(fd, small, base + i * small)
                if raw:
                    data[i, col:col + len(raw)] = \
                        np.frombuffer(raw, dtype=np.uint8)
        yield data
        remaining -= row_bytes * nrows
        processed += row_bytes * nrows


def _pipelined_encode(chunks, coder: ErasureCoder, outputs,
                      depth: int = 2, accs=None):
    """Double-buffered encode pipeline (SURVEY §2.3 'double-buffered
    host→HBM DMA + batched kernel launches'):

      reader thread:  pread chunk k+1          (overlaps everything)
      main thread:    dispatch encode(k)       (async on device coders)
                      write data shards of k   (independent of parity)
                      force + write parity of k-depth+1

    Device coders dispatch asynchronously, so up to `depth` encodes are
    in flight while the next chunk is being read — pread, host→device,
    kernel, device→host, and shard writes all overlap instead of
    serializing (the round-2/3 verdict's weak spot #3).

    When ``accs is None`` the coder must support fused CRC
    (`encode_with_crc`): the kernel emits every shard's `.ecc` tile
    partials as a second output and this function returns the
    per-shard CRC lists (crc_fold.FusedCrcAccumulator folds them,
    including CPU fallback for a ragged tail chunk).  With byte
    accumulators passed, behavior is unchanged and None is returned."""
    import collections
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    cancelled = threading.Event()
    error: list[BaseException] = []

    def read_loop() -> None:
        try:
            for data in chunks:
                # Bounded puts with a cancel check: if the main thread
                # dies (device failure, ENOSPC) while this thread is
                # blocked on a full queue, a plain q.put would deadlock
                # the final join forever.
                delivered = False
                while not cancelled.is_set():
                    try:
                        q.put(data, timeout=0.2)
                        delivered = True
                        break
                    except queue.Full:
                        continue
                if not delivered:
                    # The chunk never reached the consumer.  Normally
                    # the consumer cancelled because it already has its
                    # own exception in flight (which wins below); if it
                    # somehow finishes "cleanly", this error surfaces
                    # instead of silently truncated shard files.
                    error.append(RuntimeError(
                        "ec encode cancelled with a chunk undelivered"))
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced below
            error.append(e)
        finally:
            # The end-of-stream sentinel must actually arrive (a full
            # queue would silently drop put_nowait and deadlock the
            # consumer); same bounded-put-with-cancel as the data path.
            while not cancelled.is_set():
                try:
                    q.put(None, timeout=0.2)
                    break
                except queue.Full:
                    continue
    t = threading.Thread(target=read_loop, daemon=True,
                         name="ec-read-ahead")
    t.start()
    inflight: "collections.deque" = collections.deque()

    data_shards = coder.data_shards
    parity_shards = coder.parity_shards
    fused = accs is None
    faccs = None
    block = SMALL_BLOCK_SIZE
    if fused:
        from ..ops.crc_fold import FusedCrcAccumulator
        faccs = [FusedCrcAccumulator(coder.block_n)
                 for _ in range(data_shards + parity_shards)]

    def flush_one() -> None:
        if not fused:
            parity = np.asarray(inflight.popleft())
            for p in range(parity_shards):
                _shard_write(outputs[data_shards + p], data_shards + p,
                             parity[p].tobytes(), accs)
            return
        handle, crc_handle, width, data_tail = inflight.popleft()
        parity = np.asarray(handle)
        crc_np = np.asarray(crc_handle)
        full = width // block * block
        for i in range(data_shards):
            faccs[i].feed_tiles(crc_np[i], full)
            if width > full:
                faccs[i].feed_bytes(data_tail[i].tobytes())
        for p in range(parity_shards):
            sid = data_shards + p
            faccs[sid].feed_tiles(crc_np[sid], full)
            if width > full:
                faccs[sid].feed_bytes(parity[p, full:width].tobytes())
            _shard_write(outputs[sid], sid, parity[p].tobytes(), None)

    try:
        while True:
            data = q.get()
            if data is None:
                break
            # Dispatch first: device coders return an async handle and
            # the kernel runs while we write the data shards and read
            # the next chunk.
            if fused:
                handle, crc_handle = coder.encode_with_crc(data)
                width = data.shape[1]
                full = width // block * block
                # Ragged tail (non-block-multiple chunk_size): keep the
                # tail bytes for the CPU fallback fold in flush_one.
                tail = data[:, full:].copy() if width > full else None
                inflight.append((handle, crc_handle, width, tail))
            else:
                inflight.append(coder.encode(data))
            for i in range(data_shards):
                _shard_write(outputs[i], i, data[i].tobytes(),
                             None if fused else accs)
            if len(inflight) >= depth:
                flush_one()
        while inflight:
            flush_one()
    finally:
        cancelled.set()
        while True:  # unblock a reader stuck on a full queue
            try:
                q.get_nowait()
            except queue.Empty:
                break
        t.join()
    if error:
        raise error[0]
    if fused:
        return {sid: faccs[sid].finalize()
                for sid in range(data_shards + parity_shards)}
    return None


def rebuild_ec_files(base_file_name: str,
                     coder: ErasureCoder | None = None,
                     chunk_size: int = DEFAULT_CHUNK) -> list[int]:
    """Recreate missing .ec?? files from survivors (RebuildEcFiles).

    Returns the list of generated shard ids.  Layout-agnostic: operates
    on flat shard-file columns.  Codec-aware: the codec comes from the
    `.vif` sidecar, the shard count from the codec, and only the
    codec's planned minimal read set is read from disk — an LRC
    in-group rebuild reads 5 shard files, not every survivor.
    """
    if coder is None:
        coder = new_coder(codec=ec_codec_name(base_file_name))
    cd = getattr(coder, "codec", None) or get_codec("rs")
    present: dict[int, str] = {}
    missing: list[int] = []
    for sid in range(cd.total_shards):
        path = base_file_name + to_ext(sid)
        if os.path.exists(path):
            present[sid] = path
        else:
            missing.append(sid)
    if not missing:
        return []
    try:
        plan = cd.repair_plan(tuple(present), missing)
    except ValueError as e:
        raise ValueError(
            f"too few shards to rebuild: {len(present)} survive "
            f"({cd.name}): {e}") from None
    needed = sorted({sid for p in plan for sid in p.reads})

    shard_size = os.path.getsize(next(iter(present.values())))
    for sid, path in present.items():
        if os.path.getsize(path) != shard_size:
            raise ValueError(f"ec shard size mismatch on {path}")

    ins = {sid: open(present[sid], "rb") for sid in needed}
    outs = {sid: open(base_file_name + to_ext(sid), "wb") for sid in missing}
    accs = {sid: BlockCrcAccumulator() for sid in missing}
    try:
        for off in range(0, shard_size, chunk_size):
            take = min(chunk_size, shard_size - off)
            have = {}
            for sid, f in ins.items():
                buf = os.pread(f.fileno(), take, off)
                if len(buf) != take:
                    raise ValueError(f"short read on shard {sid}")
                have[sid] = np.frombuffer(buf, dtype=np.uint8)
            ec_repair_read_bytes_total.inc(take * len(have),
                                           codec=cd.name)
            rec = coder.reconstruct(have, wanted=missing)
            for sid in missing:
                _shard_write(outs[sid], sid,
                             np.asarray(rec[sid]).tobytes(), accs)
    finally:
        for f in ins.values():
            f.close()
        for f in outs.values():
            f.close()
    # Load-modify-save of the shared sidecar: serialize with the other
    # writers (shard receive, scrub TOFU) or concurrent savers lose
    # each other's entries.
    with ecc_lock(base_file_name):
        ecc = ShardChecksums.load(base_file_name)
        for sid in missing:
            ecc.set_shard(sid, accs[sid].finalize())
        ecc.save()
    return missing
