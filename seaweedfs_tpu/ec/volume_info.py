"""`.vif` VolumeInfo sidecar.

The reference stores a protobuf VolumeInfo next to volume/shard files
(weed/pb/volume_info.go, maybeLoadVolumeInfo) carrying the needle version
and tiering info; EC shard copies bring it along so a server holding only
parity shards still knows how to size records.  Ours carries the same
fields as JSON (the sidecar is operational metadata, not part of the
byte-compat surface) plus the erasure codec id ("codec": "rs" | "lrc"),
which is how a mounted EC volume knows which generator matrix produced
its shards — the codec travels with every shard copy exactly like the
needle version does.
"""

from __future__ import annotations

import json
import os


def save_volume_info(base_file_name: str, version: int,
                     files: list[dict] | None = None,
                     codec: str | None = None) -> None:
    payload = {"version": version}
    if files:
        payload["files"] = files
    if codec and codec != "rs":
        # rs is the implied default: absent-field compatibility with
        # every .vif written before codecs existed.
        payload["codec"] = codec
    tmp = base_file_name + ".vif.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, base_file_name + ".vif")


def update_volume_info(base_file_name: str, **fields) -> None:
    """Merge fields into an existing .vif (or create one): lets the
    encoder record the codec without clobbering version/tier info a
    caller wrote earlier."""
    existing = load_volume_info(base_file_name)
    info = dict(existing or {})
    for k, v in fields.items():
        if v is None or (k == "codec" and v == "rs"):
            info.pop(k, None)
        else:
            info[k] = v
    if not info and existing is None:
        return  # nothing to record; don't create an empty sidecar
    tmp = base_file_name + ".vif.tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, base_file_name + ".vif")


def load_volume_info(base_file_name: str) -> dict | None:
    try:
        with open(base_file_name + ".vif") as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def ec_codec_name(base_file_name: str) -> str:
    """The codec an EC volume's shards were generated with ("rs" when
    the sidecar is absent or predates codecs)."""
    info = load_volume_info(base_file_name)
    if info:
        return str(info.get("codec", "rs"))
    return "rs"
