"""`.vif` VolumeInfo sidecar.

The reference stores a protobuf VolumeInfo next to volume/shard files
(weed/pb/volume_info.go, maybeLoadVolumeInfo) carrying the needle version
and tiering info; EC shard copies bring it along so a server holding only
parity shards still knows how to size records.  Ours carries the same
fields as JSON (the sidecar is operational metadata, not part of the
byte-compat surface).
"""

from __future__ import annotations

import json
import os


def save_volume_info(base_file_name: str, version: int,
                     files: list[dict] | None = None) -> None:
    payload = {"version": version}
    if files:
        payload["files"] = files
    tmp = base_file_name + ".vif.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, base_file_name + ".vif")


def load_volume_info(base_file_name: str) -> dict | None:
    try:
        with open(base_file_name + ".vif") as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None
