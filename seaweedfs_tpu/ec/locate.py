"""Map volume byte ranges to shard-file intervals.

Port of weed/storage/erasure_coding/ec_locate.go (semantics preserved
exactly, including the rows-count derivation that lets a shard file size
stand in for the dat size).
"""

from __future__ import annotations

from dataclasses import dataclass

from . import DATA_SHARDS


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(self, large_block_size: int,
                               small_block_size: int) -> tuple[int, int]:
        offset = self.inner_block_offset
        row_index = self.block_index // DATA_SHARDS
        if self.is_large_block:
            offset += row_index * large_block_size
        else:
            offset += (self.large_block_rows_count * large_block_size +
                       row_index * small_block_size)
        return self.block_index % DATA_SHARDS, offset


def _locate_within_blocks(block_length: int, offset: int) -> tuple[int, int]:
    return offset // block_length, offset % block_length


def _locate_offset(large: int, small: int, dat_size: int,
                   offset: int) -> tuple[int, bool, int]:
    large_row_size = large * DATA_SHARDS
    n_large_rows = dat_size // large_row_size
    if offset < n_large_rows * large_row_size:
        idx, inner = _locate_within_blocks(large, offset)
        return idx, True, inner
    offset -= n_large_rows * large_row_size
    idx, inner = _locate_within_blocks(small, offset)
    return idx, False, inner


def locate_data(large: int, small: int, dat_size: int, offset: int,
                size: int) -> list[Interval]:
    """All shard intervals covering [offset, offset+size) of the volume."""
    block_index, is_large, inner = _locate_offset(large, small, dat_size,
                                                  offset)
    # Rows-count derivation per the reference: padding by a full small row
    # makes the count recoverable from a rounded-up dat size.
    n_large_rows = (dat_size + DATA_SHARDS * small) // (large * DATA_SHARDS)

    intervals: list[Interval] = []
    while size > 0:
        block_remaining = (large if is_large else small) - inner
        take = min(size, block_remaining)
        intervals.append(Interval(
            block_index=block_index, inner_block_offset=inner, size=take,
            is_large_block=is_large, large_block_rows_count=n_large_rows))
        size -= take
        if size <= 0:
            break
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return intervals
