"""Erasure-coding pipeline: volume files <-> RS(10,4) shard files.

Layout compatible with the reference (weed/storage/erasure_coding):
`.ec00`-`.ec13` shard files (row-striped: 10x1GB large blocks then 10x1MB
small blocks), `.ecx` sorted needle index, `.ecj` deletion journal.
A key property the TPU path exploits: byte column p across the 14 shard
files is one RS codeword, so encode/rebuild are pure column-parallel GF
matmuls regardless of the block layout — the layout only matters for
mapping needle offsets to shard positions (locate.py).
"""

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
SMALL_BLOCK_SIZE = 1024 * 1024         # 1MB


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"
