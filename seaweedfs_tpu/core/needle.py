"""Needle record codec — the on-disk object format of a volume `.dat` file.

Byte-exact reimplementation of the reference wire format
(weed/storage/needle/needle.go:24-44, needle_read_write.go:31-120):

Version 1:  [cookie 4][id 8][size 4][data size][checksum 4][padding]
Version 2:  [cookie 4][id 8][size 4][dataSize 4][data][flags 1]
            [nameSize 1 name][mimeSize 1 mime][lastModified 5][ttl 2]
            [pairsSize 2 pairs][checksum 4][padding]
Version 3:  v2 + [appendAtNs 8] before padding.

`size` for v2/v3 is the *body* length (4 + dataSize + 1 + optional
sections); records are padded so the next record starts at a multiple of 8.

Compatibility quirk, reproduced deliberately: the reference builds records
by reusing one 24-byte scratch header, so the padding bytes appended after
the checksum are not zeros — for v2 they are the leading bytes of the
big-endian needle id (scratch[4:12]), for v3 the leading bytes of the
big-endian size field followed by zeros (scratch[12:24]).  Reproducing this
makes our `.dat` files byte-identical to reference-written ones for the
same inputs, which in turn makes EC shard files byte-identical.

Padding is 1..8 bytes (a fully-aligned record still gets 8 — Go's
`NeedlePaddingSize - (x % NeedlePaddingSize)` is 8 when x%8==0).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from . import crc as crc_mod
from . import types as t
from .ttl import TTL

# cookie(4) + id(8) + size(4), big-endian — the fixed needle header.
_HEADER = struct.Struct(">IQI")

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED_DATE = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2


def padding_length(needle_size: int, version: int) -> int:
    """1..8 bytes of padding; version 3 includes the 8-byte timestamp."""
    if version == VERSION3:
        return t.NEEDLE_PADDING_SIZE - (
            (t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE +
             t.TIMESTAMP_SIZE) % t.NEEDLE_PADDING_SIZE)
    return t.NEEDLE_PADDING_SIZE - (
        (t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE) %
        t.NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    if version == VERSION3:
        return (needle_size + t.NEEDLE_CHECKSUM_SIZE + t.TIMESTAMP_SIZE +
                padding_length(needle_size, version))
    return (needle_size + t.NEEDLE_CHECKSUM_SIZE +
            padding_length(needle_size, version))


def get_actual_size(size: int, version: int) -> int:
    """Total on-disk bytes of a record with payload Size `size`."""
    return t.NEEDLE_HEADER_SIZE + needle_body_length(size, version)


@dataclass
class Needle:
    """One stored object.  Field names mirror the reference struct."""

    cookie: int = 0
    id: int = 0
    size: int = 0          # body size (set by encode)

    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""     # serialized extra headers (JSON in reference)
    last_modified: int = 0  # unix seconds, stored as low 5 bytes
    ttl: TTL = field(default_factory=TTL)

    checksum: int = 0      # masked CRC32-C of data (set on encode/decode)
    append_at_ns: int = 0  # v3 only

    # -- flag helpers ------------------------------------------------------

    def has_name(self) -> bool:
        return bool(self.flags & FLAG_HAS_NAME)

    def has_mime(self) -> bool:
        return bool(self.flags & FLAG_HAS_MIME)

    def has_last_modified_date(self) -> bool:
        return bool(self.flags & FLAG_HAS_LAST_MODIFIED_DATE)

    def has_ttl(self) -> bool:
        return bool(self.flags & FLAG_HAS_TTL)

    def has_pairs(self) -> bool:
        return bool(self.flags & FLAG_HAS_PAIRS)

    def is_compressed(self) -> bool:
        return bool(self.flags & FLAG_IS_COMPRESSED)

    def is_chunked_manifest(self) -> bool:
        return bool(self.flags & FLAG_IS_CHUNK_MANIFEST)

    def set_is_compressed(self) -> None:
        self.flags |= FLAG_IS_COMPRESSED

    def set_name(self, name: bytes) -> None:
        self.name = name[:255]
        self.flags |= FLAG_HAS_NAME

    def set_mime(self, mime: bytes) -> None:
        self.mime = mime
        self.flags |= FLAG_HAS_MIME

    def set_last_modified(self, ts: int) -> None:
        self.last_modified = ts
        self.flags |= FLAG_HAS_LAST_MODIFIED_DATE

    def set_ttl(self, ttl: TTL) -> None:
        self.ttl = ttl
        if ttl.count:
            self.flags |= FLAG_HAS_TTL

    def set_pairs(self, pairs: bytes) -> None:
        self.pairs = pairs
        self.flags |= FLAG_HAS_PAIRS

    # -- encode ------------------------------------------------------------

    def _body_size_v2(self) -> int:
        if len(self.data) == 0:
            return 0
        size = 4 + len(self.data) + 1
        if self.has_name():
            size += 1 + min(len(self.name), 255)
        if self.has_mime():
            size += 1 + len(self.mime)
        if self.has_last_modified_date():
            size += LAST_MODIFIED_BYTES_LENGTH
        if self.has_ttl():
            size += TTL_BYTES_LENGTH
        if self.has_pairs():
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = CURRENT_VERSION) -> bytes:
        """prepareWriteBuffer equivalent; sets self.size/self.checksum."""
        self.checksum = crc_mod.needle_checksum(self.data)
        if version == VERSION1:
            self.size = len(self.data)
            out = bytearray()
            out += t.put_uint32(self.cookie)
            out += t.put_uint64(self.id)
            out += t.put_uint32(self.size)
            out += self.data
            out += t.put_uint32(self.checksum)
            # v1 padding quirk: scratch header[4:] after the checksum write
            # still holds id(8)+size(4); padding reads from there.
            pad = padding_length(self.size, version)
            scratch = t.put_uint32(self.checksum) + t.put_uint64(self.id) + \
                t.put_uint32(self.size)
            out += scratch[4:4 + pad]
            return bytes(out)
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        self.size = self._body_size_v2()
        # One precompiled pack for the fixed header + join instead of
        # eight helper calls and bytearray growth: to_bytes is the
        # hottest function on the volume write path.
        parts = [_HEADER.pack(self.cookie & 0xFFFFFFFF,
                              self.id & 0xFFFFFFFFFFFFFFFF,
                              self.size & 0xFFFFFFFF)]
        if len(self.data) > 0:
            parts.append(t.put_uint32(len(self.data)))
            parts.append(self.data)
            parts.append(bytes((self.flags & 0xFF,)))
            if self.has_name():
                name = self.name[:255]
                parts.append(bytes((len(name),)))
                parts.append(name)
            if self.has_mime():
                parts.append(bytes((len(self.mime) & 0xFF,)))
                parts.append(self.mime)
            if self.has_last_modified_date():
                parts.append(t.put_uint64(self.last_modified)
                             [8 - LAST_MODIFIED_BYTES_LENGTH:])
            if self.has_ttl():
                parts.append(self.ttl.to_bytes())
            if self.has_pairs():
                parts.append(t.put_uint16(len(self.pairs)))
                parts.append(self.pairs)
        pad = padding_length(self.size, version)
        parts.append(t.put_uint32(self.checksum))
        if version == VERSION2:
            # scratch[4:12] = big-endian id; padding reads from there.
            parts.append(t.put_uint64(self.id)[:pad])
        else:
            parts.append(t.put_uint64(self.append_at_ns))
            # scratch[12:16] = big-endian size, then zeros.
            tail = t.put_uint32(self.size) + bytes(8)
            parts.append(tail[:pad])
        return b"".join(parts)

    # -- decode ------------------------------------------------------------

    @classmethod
    def parse_header(cls, b: bytes, off: int = 0) -> "Needle":
        n = cls()
        n.cookie = t.get_uint32(b, off)
        n.id = t.get_uint64(b, off + t.COOKIE_SIZE)
        n.size = t.size_from_bytes(b, off + t.COOKIE_SIZE + t.NEEDLE_ID_SIZE)
        return n

    def _read_body_v2(self, b: bytes) -> None:
        idx, end = 0, len(b)
        if idx < end:
            data_size = t.get_uint32(b, idx)
            idx += 4
            if data_size + idx > end:
                raise ValueError("needle data_size out of range")
            self.data = b[idx:idx + data_size]
            idx += data_size
            self.flags = b[idx]
            idx += 1
        if idx < end and self.has_name():
            name_size = b[idx]
            idx += 1
            self.name = b[idx:idx + name_size]
            idx += name_size
        if idx < end and self.has_mime():
            mime_size = b[idx]
            idx += 1
            self.mime = b[idx:idx + mime_size]
            idx += mime_size
        if idx < end and self.has_last_modified_date():
            self.last_modified = int.from_bytes(
                b[idx:idx + LAST_MODIFIED_BYTES_LENGTH], "big")
            idx += LAST_MODIFIED_BYTES_LENGTH
        if idx < end and self.has_ttl():
            self.ttl = TTL.from_bytes(b[idx:idx + TTL_BYTES_LENGTH])
            idx += TTL_BYTES_LENGTH
        if idx < end and self.has_pairs():
            pairs_size = t.get_uint16(b, idx)
            idx += 2
            self.pairs = b[idx:idx + pairs_size]
            idx += pairs_size

    @classmethod
    def from_bytes(cls, blob: bytes, version: int = CURRENT_VERSION,
                   check_crc: bool = True) -> "Needle":
        """Parse a full record blob (header + body + padding) — ReadBytes."""
        n = cls.parse_header(blob)
        size = n.size
        if version == VERSION1:
            n.data = blob[t.NEEDLE_HEADER_SIZE:t.NEEDLE_HEADER_SIZE + size]
        elif version in (VERSION2, VERSION3):
            n._read_body_v2(blob[t.NEEDLE_HEADER_SIZE:
                                 t.NEEDLE_HEADER_SIZE + size])
        else:
            raise ValueError(f"unsupported needle version {version}")
        if size > 0:
            stored = t.get_uint32(blob, t.NEEDLE_HEADER_SIZE + size)
            if check_crc:
                actual = crc_mod.needle_checksum(n.data)
                if stored != actual:
                    raise ValueError("CRC error! Data On Disk Corrupted")
                n.checksum = actual
            else:
                n.checksum = stored
        if version == VERSION3:
            ts_off = t.NEEDLE_HEADER_SIZE + size + t.NEEDLE_CHECKSUM_SIZE
            n.append_at_ns = t.get_uint64(blob, ts_off)
        return n

    def disk_size(self, version: int = CURRENT_VERSION) -> int:
        return get_actual_size(self.size, version)
