"""On-disk formats: needle records, indexes, superblocks, CRC, TTL."""
