"""Fixed-width storage types: needle ids, offsets, sizes, cookies.

Byte-layout compatible with the reference (all big-endian):
- NeedleId: 8 bytes (weed/storage/types/needle_id_type.go)
- Offset:   4 bytes, stored in units of NEEDLE_PADDING_SIZE (8) =>
            32GB max volume (weed/storage/types/offset_4bytes.go)
- Size:     4 bytes signed; -1 is the tombstone
            (weed/storage/types/needle_types.go:15-22,39)
- Cookie:   4 bytes random, guards against guessed ids
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

NEEDLE_ID_SIZE = 8
OFFSET_SIZE = 4
SIZE_SIZE = 4
COOKIE_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16
NEEDLE_CHECKSUM_SIZE = 4

TOMBSTONE_FILE_SIZE = -1  # Size(-1)

MAX_POSSIBLE_VOLUME_SIZE = 4 * 1024 * 1024 * 1024 * 8  # 32GB (4-byte offsets)


def set_offset_flavor(nbytes: int) -> None:
    """Select the offset width, the analog of the reference's
    5BytesOffset BUILD flavor (weed/storage/types/offset_5bytes.go:9-16
    vs offset_4bytes.go — a compile tag there, a process-wide config
    here; `weed ... -offsetBytes=5` or WEED_OFFSET_BYTES=5).

    4 bytes: 32GB max volume (the default).  5 bytes: the stored form
    grows to 4 big-endian low bytes + 1 high byte (the reference's b4),
    widening `.idx`/`.ecx` records to 17 bytes and raising the cap to
    8TB.  Like the reference's build flavors, the two layouts are not
    cross-readable — pick one per deployment.
    """
    global OFFSET_SIZE, NEEDLE_MAP_ENTRY_SIZE, MAX_POSSIBLE_VOLUME_SIZE
    if nbytes not in (4, 5):
        raise ValueError(f"offset flavor must be 4 or 5, got {nbytes}")
    OFFSET_SIZE = nbytes
    NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE
    MAX_POSSIBLE_VOLUME_SIZE = (
        4 * 1024 * 1024 * 1024 * 8 * (256 if nbytes == 5 else 1))


def size_is_deleted(size: int) -> bool:
    return size < 0 or size == TOMBSTONE_FILE_SIZE


def size_is_valid(size: int) -> bool:
    return size > 0 and size != TOMBSTONE_FILE_SIZE


# -- scalar codecs (big-endian, like weed/util/bytes.go) --------------------

_U64 = struct.Struct(">Q")
_U32 = struct.Struct(">I")
_U16 = struct.Struct(">H")


def put_uint64(v: int) -> bytes:
    return _U64.pack(v & 0xFFFFFFFFFFFFFFFF)


def get_uint64(b: bytes, off: int = 0) -> int:
    return _U64.unpack_from(b, off)[0]


def put_uint32(v: int) -> bytes:
    return _U32.pack(v & 0xFFFFFFFF)


def get_uint32(b: bytes, off: int = 0) -> int:
    return _U32.unpack_from(b, off)[0]


def put_uint16(v: int) -> bytes:
    return _U16.pack(v & 0xFFFF)


def get_uint16(b: bytes, off: int = 0) -> int:
    return _U16.unpack_from(b, off)[0]


# -- Offset: stored /8, 4 bytes ---------------------------------------------


def offset_to_bytes(actual_offset: int) -> bytes:
    """Actual byte offset (multiple of 8) -> stored form.

    4-byte flavor: big-endian u32 of the /8 units.  5-byte flavor:
    the same 4 bytes followed by the high byte (bits 32-39 of the
    units) — offset_5bytes.go OffsetToBytes puts b4 LAST."""
    units = actual_offset // NEEDLE_PADDING_SIZE
    if OFFSET_SIZE == 4:
        return put_uint32(units)
    return put_uint32(units & 0xFFFFFFFF) + bytes(((units >> 32) & 0xFF,))


def offset_from_bytes(b: bytes, off: int = 0) -> int:
    """Stored form -> actual byte offset."""
    units = get_uint32(b, off)
    if OFFSET_SIZE == 5:
        units |= b[off + 4] << 32
    return units * NEEDLE_PADDING_SIZE


def offset_is_zero(actual_offset: int) -> bool:
    return actual_offset == 0


# -- Size: int32, may be negative (tombstone) -------------------------------

_I32 = struct.Struct(">i")


def size_to_bytes(size: int) -> bytes:
    return _I32.pack(size)


def size_from_bytes(b: bytes, off: int = 0) -> int:
    return _I32.unpack_from(b, off)[0]


# -- Needle map entry (the 16-byte .idx / .ecx record) ----------------------


@dataclass(frozen=True)
class NeedleMapEntry:
    key: int          # needle id
    offset: int       # actual byte offset in .dat (already *8)
    size: int         # payload Size (int32; -1 = tombstone)

    def to_bytes(self) -> bytes:
        return put_uint64(self.key) + offset_to_bytes(self.offset) + \
            size_to_bytes(self.size)

    @classmethod
    def from_bytes(cls, b: bytes, off: int = 0) -> "NeedleMapEntry":
        return cls(key=get_uint64(b, off),
                   offset=offset_from_bytes(b, off + NEEDLE_ID_SIZE),
                   size=size_from_bytes(b, off + NEEDLE_ID_SIZE + OFFSET_SIZE))


# -- public file ids: "vid,needleIdHexCookieHex" ----------------------------


def format_file_id(volume_id: int, key: int, cookie: int) -> str:
    """Matches needle.Needle.String(): trimmed hex key + 8-hex cookie."""
    key_hex = f"{key:x}"
    if key == 0:
        key_hex = "0"
    return f"{volume_id},{key_hex}{cookie:08x}"


def parse_file_id(fid: str) -> tuple[int, int, int]:
    """'3,01637037d6' -> (volume_id, key, cookie).

    Mirrors ParseNeedleIdCookie (weed/storage/needle/needle.go:144-161):
    the last 8 hex chars are the cookie, the rest is the id.
    """
    comma = fid.find(",")
    if comma < 0:
        raise ValueError(f"invalid file id {fid!r}: missing comma")
    volume_id = int(fid[:comma])
    key_cookie = fid[comma + 1:]
    if len(key_cookie) <= 8:
        raise ValueError(f"invalid file id {fid!r}: key+cookie too short")
    key = int(key_cookie[:-8], 16)
    cookie = int(key_cookie[-8:], 16)
    return volume_id, key, cookie
