"""`.idx` file walker/writer: 16-byte (key, offset, size) entries.

Reference: weed/storage/idx/walk.go.  The same record encodes `.ecx` sorted
indexes (weed/storage/erasure_coding/ec_encoder.go:27-54).
"""

from __future__ import annotations

import io
from typing import Callable, Iterator

from . import types as t

ROWS_TO_READ = 1024


def __getattr__(name):
    # ENTRY_SIZE tracks the configured offset flavor (16 bytes for
    # 4-byte offsets, 17 for the 5-byte/8TB flavor) — resolved at
    # access time so set_offset_flavor() takes effect everywhere.
    if name == "ENTRY_SIZE":
        return t.NEEDLE_MAP_ENTRY_SIZE
    raise AttributeError(name)


def iter_index(readable) -> Iterator[t.NeedleMapEntry]:
    """Yield entries from a binary file object or bytes."""
    if isinstance(readable, (bytes, bytearray, memoryview)):
        readable = io.BytesIO(readable)
    entry_size = t.NEEDLE_MAP_ENTRY_SIZE
    while True:
        chunk = readable.read(entry_size * ROWS_TO_READ)
        if not chunk:
            return
        usable = len(chunk) - (len(chunk) % entry_size)
        for off in range(0, usable, entry_size):
            yield t.NeedleMapEntry.from_bytes(chunk, off)
        if usable != len(chunk):
            return  # trailing partial entry: stop like the reference walker


def walk_index(readable, fn: Callable[[int, int, int], None]) -> None:
    """WalkIndexFile equivalent: fn(key, actual_offset, size) per entry."""
    for e in iter_index(readable):
        fn(e.key, e.offset, e.size)


def append_entry(writable, key: int, actual_offset: int, size: int) -> None:
    writable.write(t.NeedleMapEntry(key, actual_offset, size).to_bytes())
