"""CRC32-C (Castagnoli) with seaweedfs's masked `Value()` transform.

Reference: weed/storage/needle/crc.go — running CRC32-C via klauspost's SIMD
fork, and `Value() = rot17(crc) + 0xa282ead8` (the snappy-style mask) which
is what actually lands on disk after each needle's data.

Backends, fastest first:
1. native C++ (SSE4.2 hardware CRC / slice-by-8) via ctypes — see native/
2. numpy table-driven slice-by-4 (vectorized enough for tests)
Both produce identical values; `crc32c()` picks automatically.
"""

from __future__ import annotations

import numpy as np

CASTAGNOLI_POLY = 0x82F63B78  # reversed representation


def _build_tables(num: int = 8) -> np.ndarray:
    t = np.zeros((num, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (CASTAGNOLI_POLY if crc & 1 else 0)
        t[0, i] = crc
    for k in range(1, num):
        for i in range(256):
            t[k, i] = (t[k - 1, i] >> 8) ^ t[0, t[k - 1, i] & 0xFF]
    return t


_TABLES = _build_tables()


def _crc32c_py(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Slice-by-8 software CRC32-C (update form, pre/post inverted)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8) if not isinstance(
        data, np.ndarray) else data.astype(np.uint8, copy=False)
    crc = (~crc) & 0xFFFFFFFF
    n = len(buf)
    i = 0
    t = _TABLES
    # Process 8 bytes at a time via table composition.
    main = n - (n % 8)
    if main:
        b = buf[:main].reshape(-1, 8)
        for row in b:
            crc ^= int(row[0]) | int(row[1]) << 8 | int(row[2]) << 16 | \
                int(row[3]) << 24
            crc = (int(t[7, crc & 0xFF]) ^ int(t[6, (crc >> 8) & 0xFF]) ^
                   int(t[5, (crc >> 16) & 0xFF]) ^ int(t[4, (crc >> 24) & 0xFF]) ^
                   int(t[3, row[4]]) ^ int(t[2, row[5]]) ^
                   int(t[1, row[6]]) ^ int(t[0, row[7]]))
        i = main
    while i < n:
        crc = (crc >> 8) ^ int(t[0, (crc ^ int(buf[i])) & 0xFF])
        i += 1
    return (~crc) & 0xFFFFFFFF


_native = None
_native_checked = False


def _native_crc():
    global _native, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from ..utils import native as native_mod
            lib = native_mod.load()
            if lib is not None and hasattr(lib, "sw_crc32c"):
                _native = native_mod.crc32c_fn(lib)
        except Exception:
            _native = None
    return _native


def crc32c(data: bytes, crc: int = 0) -> int:
    """Running CRC32-C update (matches crc32.Update with Castagnoli table)."""
    fn = _native_crc()
    if fn is not None:
        return fn(data, crc)
    return _crc32c_py(data, crc)


def masked_value(crc: int) -> int:
    """needle.CRC.Value(): rotate-right by 15 then add the snappy constant."""
    crc &= 0xFFFFFFFF
    return ((crc >> 15) | (crc << 17) & 0xFFFFFFFF) + 0xA282EAD8 & 0xFFFFFFFF


def needle_checksum(data: bytes) -> int:
    """The 4-byte checksum stored after needle data on disk."""
    return masked_value(crc32c(data))
