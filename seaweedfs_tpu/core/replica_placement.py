"""Replica placement: 'XYZ' digit string / single byte — reference:
weed/storage/super_block/replica_placement.go."""

from __future__ import annotations

from dataclasses import dataclass

_PARSE_CACHE: dict = {}


@dataclass(frozen=True)
class ReplicaPlacement:
    diff_data_center_count: int = 0
    diff_rack_count: int = 0
    same_rack_count: int = 0

    @classmethod
    def parse(cls, t: str) -> "ReplicaPlacement":
        # Memoized: parse runs twice per /dir/assign on the master hot
        # path and the distinct placement strings are few ("000",
        # "001", ...).  The instance is frozen, so sharing is safe.
        hit = _PARSE_CACHE.get(t)
        if hit is not None:
            return hit
        vals = [0, 0, 0]
        for i, c in enumerate(t):
            count = ord(c) - ord("0")
            if not 0 <= count <= 2:
                raise ValueError(f"unknown replication type {t!r}")
            if i < 3:
                vals[i] = count
        rp = cls(diff_data_center_count=vals[0], diff_rack_count=vals[1],
                 same_rack_count=vals[2])
        if len(_PARSE_CACHE) < 1024:  # bounded (strings are attacker-
            _PARSE_CACHE[t] = rp      # influenced via the query param)
        return rp

    @classmethod
    def from_byte(cls, b: int) -> "ReplicaPlacement":
        return cls.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return (self.diff_data_center_count * 100 +
                self.diff_rack_count * 10 + self.same_rack_count)

    def copy_count(self) -> int:
        return (self.diff_data_center_count + self.diff_rack_count +
                self.same_rack_count + 1)

    def __str__(self) -> str:
        return (f"{self.diff_data_center_count}"
                f"{self.diff_rack_count}{self.same_rack_count}")
