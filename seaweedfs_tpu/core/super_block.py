"""Volume superblock: the 8-byte header of every `.dat` file.

Reference: weed/storage/super_block/super_block.go:12-31.
Byte 0 version, byte 1 replica placement, bytes 2-3 TTL, bytes 4-5
compaction revision, bytes 6-7 length of an optional protobuf extra.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import types as t
from .needle import CURRENT_VERSION
from .replica_placement import ReplicaPlacement
from .ttl import TTL

SUPER_BLOCK_SIZE = 8


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compaction_revision: int = 0
    extra: bytes = b""  # serialized SuperBlockExtra protobuf, if any

    def block_size(self) -> int:
        if self.version >= 2 and self.extra:
            return SUPER_BLOCK_SIZE + len(self.extra)
        return SUPER_BLOCK_SIZE

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        header[4:6] = t.put_uint16(self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError("super block extra too large")
            header[6:8] = t.put_uint16(len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @classmethod
    def from_bytes(cls, b: bytes) -> "SuperBlock":
        if len(b) < SUPER_BLOCK_SIZE:
            raise ValueError("superblock too short")
        version = b[0]
        if not 1 <= version <= 3:
            raise ValueError(f"unsupported superblock version {version}")
        sb = cls(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(b[1]),
            ttl=TTL.from_bytes(b[2:4]),
            compaction_revision=t.get_uint16(b, 4),
        )
        extra_size = t.get_uint16(b, 6)
        if extra_size:
            sb.extra = bytes(b[SUPER_BLOCK_SIZE:SUPER_BLOCK_SIZE + extra_size])
        return sb
