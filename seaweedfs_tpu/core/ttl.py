"""2-byte TTL encoding (count, unit) — weed/storage/needle/volume_ttl.go."""

from __future__ import annotations

from dataclasses import dataclass

EMPTY = 0
MINUTE = 1
HOUR = 2
DAY = 3
WEEK = 4
MONTH = 5
YEAR = 6

_UNIT_FROM_CHAR = {"m": MINUTE, "h": HOUR, "d": DAY, "w": WEEK,
                   "M": MONTH, "y": YEAR}
_CHAR_FROM_UNIT = {v: k for k, v in _UNIT_FROM_CHAR.items()}

_UNIT_MINUTES = {EMPTY: 0, MINUTE: 1, HOUR: 60, DAY: 24 * 60,
                 WEEK: 7 * 24 * 60, MONTH: 31 * 24 * 60,
                 YEAR: 365 * 24 * 60}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = EMPTY

    @classmethod
    def parse(cls, s: str) -> "TTL":
        """'3m', '4h', '5d', '6w', '7M', '8y'; bare digits mean minutes."""
        if not s:
            return EMPTY_TTL
        unit_ch = s[-1]
        if unit_ch.isdigit():
            count, unit = int(s), MINUTE
        else:
            if unit_ch not in _UNIT_FROM_CHAR:
                raise ValueError(f"unknown TTL unit {unit_ch!r}")
            count, unit = int(s[:-1]), _UNIT_FROM_CHAR[unit_ch]
        return cls(count=count, unit=unit)

    @classmethod
    def from_bytes(cls, b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return EMPTY_TTL
        return cls(count=b[0], unit=b[1])

    @classmethod
    def from_uint32(cls, v: int) -> "TTL":
        return cls.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return (self.count << 8) | self.unit

    def minutes(self) -> int:
        return self.count * _UNIT_MINUTES.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0 or self.unit == EMPTY:
            return ""
        return f"{self.count}{_CHAR_FROM_UNIT[self.unit]}"


EMPTY_TTL = TTL()
