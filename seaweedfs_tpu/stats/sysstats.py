"""Disk and memory statistics (reference: weed/stats/disk.go, memory.go).
"""

from __future__ import annotations

import os


def disk_status(path: str) -> dict:
    """Filesystem usage for the volume holding `path`
    (disk.go fillInDiskStatus via syscall.Statfs)."""
    st = os.statvfs(path)
    total = st.f_blocks * st.f_frsize
    free = st.f_bavail * st.f_frsize
    used = total - st.f_bfree * st.f_frsize
    return {"dir": path, "all": total, "used": used, "free": free,
            "percent_free": (free / total * 100.0) if total else 0.0,
            "percent_used": (used / total * 100.0) if total else 0.0}


def proc_cpu_seconds() -> float:
    """CPU seconds (user+system) consumed by this process so far.
    Exposed by every server's status endpoint so `weed benchmark
    -cpu=true` can sample server-side cost around a load phase and
    report requests per core-second — the hardware-independent number
    the multi-core reference baseline is compared against."""
    import resource
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_utime + ru.ru_stime


def memory_status() -> dict:
    """Process memory from /proc/self/status (memory.go), falling back
    to getrusage off-Linux so the volume server's RSS gauge and /debug
    status stay meaningful on macOS (no procfs there; ru_maxrss is the
    peak RSS — bytes on macOS, kilobytes on Linux)."""
    out = {"rss": 0, "vms": 0}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
                elif line.startswith("VmSize:"):
                    out["vms"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    if not out["rss"]:
        import resource
        import sys
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        out["rss"] = peak if sys.platform == "darwin" else peak * 1024
    return out
