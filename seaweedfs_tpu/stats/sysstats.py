"""Disk and memory statistics (reference: weed/stats/disk.go, memory.go).
"""

from __future__ import annotations

import os


def disk_status(path: str) -> dict:
    """Filesystem usage for the volume holding `path`
    (disk.go fillInDiskStatus via syscall.Statfs)."""
    st = os.statvfs(path)
    total = st.f_blocks * st.f_frsize
    free = st.f_bavail * st.f_frsize
    used = total - st.f_bfree * st.f_frsize
    return {"dir": path, "all": total, "used": used, "free": free,
            "percent_free": (free / total * 100.0) if total else 0.0,
            "percent_used": (used / total * 100.0) if total else 0.0}


def memory_status() -> dict:
    """Process memory from /proc/self/status (memory.go)."""
    out = {"rss": 0, "vms": 0}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
                elif line.startswith("VmSize:"):
                    out["vms"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    return out
