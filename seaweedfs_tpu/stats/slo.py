"""Workload SLO plane: live quantiles with trace exemplars + multi-window
burn-rate health for every server role.

The request histograms (cluster/rpc.py via stats/metrics.py) are
cumulative — fine for Prometheus, useless for "is the p99 burning RIGHT
NOW".  This module sits behind the rpc middleware's per-request
observation and keeps, per (endpoint-family, status-class):

- a sliding-window quantile sketch (stats/sketch.py — log-bucketed,
  mergeable, bounded memory, documented alpha relative-error bound),
  exported as `SeaweedFS_request_quantile_seconds{q="0.5|0.95|0.99"}`
  on /metrics and aggregated cluster-wide on /cluster/healthz (volume
  servers ship their read/write sketches in every heartbeat; the
  master merges them — merge is exact bucket addition);
- trace EXEMPLARS: every observation slower than the SLO threshold
  records {ts, family, status, seconds, trace_id} in a bounded ring,
  served by /debug/slow — a p99 spike links directly to its
  /debug/traces spans instead of being a number with no story;
- a multi-window BURN-RATE engine over declared objectives
  (-slo.read.p99 latency target, -slo.availability): error budget
  consumption measured over a short (5m) and long (1h) window, Google
  SRE-workbook style — fast burn (>= 14.4x budget in both windows)
  degrades /cluster/healthz and emits the `slo.burn` event; slow burn
  (>= 6x) is reported without degrading.

Objectives are OPT-IN: with no -slo.* flags the tracker still measures
quantiles and records exemplars (threshold defaults to 250ms, the
tracer's slow-span default), but never computes burn or degrades
healthz — a cluster that declared no objective cannot violate one.

This module must not import cluster.rpc (rpc imports it); route
handlers return plain (status, dict) tuples like trace/fault/events
routes do.
"""

from __future__ import annotations

import threading
import time

from ..trace import tracer as _tracer
from ..utils import env_float as _env_float
from .sketch import QuantileSketch, WindowedSketch

# Exemplar threshold when no latency objective is declared: matches the
# tracer's always-sample-slow default (SEAWEEDFS_TPU_TRACE_SLOW_MS).
DEFAULT_EXEMPLAR_THRESHOLD = 0.25

# Burn-rate thresholds (SRE workbook: 14.4x burns a 30-day budget in
# ~2 days — page; 6x in ~5 days — ticket).
FAST_BURN = 14.4
SLOW_BURN = 6.0

QUANTILES = (0.5, 0.95, 0.99)

# Phase-sketch subsampling: every K-th request feeds the per-phase
# windowed sketches (slow requests always do) — see observe().
PHASE_SAMPLE_EVERY = 8

# Families that are cluster control/introspection traffic, excluded
# from the aggregate read/write sketches and the burn windows: a
# failing admin call is an operator's problem, not an SLO violation,
# and healthz polling must not dilute the data-plane tail.
_CONTROL_PREFIXES = ("/debug", "/admin", "/cluster", "/heartbeat",
                     "/metrics", "/vol/", "/col/", "/.meta", "/.kv",
                     "/.ui", "/ui")


def data_plane(family: str) -> bool:
    return not family.startswith(_CONTROL_PREFIXES)


class SloObjectives:
    """Declared objectives for one role.  `availability` is a fraction
    (0.999) — values > 1 are treated as percent (99.9 -> 0.999) so the
    flag reads naturally either way.  `read_p99` is seconds."""

    __slots__ = ("read_p99", "availability")

    def __init__(self, read_p99: float | None = None,
                 availability: float | None = None):
        if availability is not None and availability > 1.0:
            availability = availability / 100.0
        if availability is not None and not 0.0 < availability < 1.0:
            raise ValueError(
                f"-slo.availability {availability} must be in (0, 1) "
                f"(or a percent like 99.9)")
        if read_p99 is not None and read_p99 <= 0:
            raise ValueError(f"-slo.read.p99 {read_p99} must be > 0")
        self.read_p99 = read_p99
        self.availability = availability

    @property
    def declared(self) -> bool:
        return self.read_p99 is not None or self.availability is not None

    def to_dict(self) -> dict:
        return {"read_p99": self.read_p99,
                "availability": self.availability}


class _CounterRing:
    """Sliding-window counters (total/bad/slow/reads/shed) with the
    same whole-slice expiry and injected clock as WindowedSketch.
    `total` counts EXECUTED data-plane requests; sheds (429 refused
    before execution) ride their own column so they neither dilute the
    error rate nor masquerade as fast requests."""

    __slots__ = ("window", "slices", "slice_seconds", "clock", "_ring",
                 "_lock")

    def __init__(self, window: float, slices: int = 6,
                 clock=time.monotonic):
        self.window = window
        self.slices = slices
        self.slice_seconds = window / slices
        self.clock = clock
        # [epoch, total, bad, slow, reads, shed]
        self._ring: list[list | None] = [None] * slices
        self._lock = threading.Lock()

    def _slot(self) -> list:
        epoch = int(self.clock() // self.slice_seconds)
        idx = epoch % self.slices
        slot = self._ring[idx]
        if slot is None or slot[0] != epoch:
            slot = self._ring[idx] = [epoch, 0, 0, 0, 0, 0]
        return slot

    def add(self, bad: bool, slow: bool, read: bool) -> None:
        with self._lock:
            slot = self._slot()
            slot[1] += 1
            if bad:
                slot[2] += 1
            if slow:
                slot[3] += 1
            if read:
                slot[4] += 1

    def add_shed(self) -> None:
        with self._lock:
            self._slot()[5] += 1

    def totals(self) -> tuple[int, int, int, int, int]:
        """(total, bad, slow, reads, shed) over the live window."""
        newest = int(self.clock() // self.slice_seconds)
        out = [0, 0, 0, 0, 0]
        with self._lock:
            for slot in self._ring:
                if slot is not None and newest - slot[0] < self.slices:
                    for i in range(5):
                        out[i] += slot[i + 1]
        return tuple(out)


class SloTracker:
    """Per-role request SLO state: windowed quantile sketches keyed by
    (endpoint-family, status-class), aggregate read/write sketches for
    cross-process aggregation, slow-request exemplars, and the
    burn-rate engine.  One instance per JsonHttpServer, created by
    enable_metrics; servers declare objectives with set_objectives()."""

    # Burn is meaningless on a handful of requests: below this many
    # data-plane requests in the short window the engine reports
    # rates but never flips fast/slow burn.
    MIN_WINDOW_REQUESTS = 10

    def __init__(self, role: str, node: str = "",
                 objectives: SloObjectives | None = None,
                 clock=time.monotonic,
                 short_window: float | None = None,
                 long_window: float | None = None,
                 slices: int = 6,
                 exemplar_capacity: int = 256,
                 alpha: float = 0.01):
        from collections import deque
        # The canonical SRE windows (5m fast / 1h slow), overridable by
        # env for harnesses that must drive a burn inside seconds
        # (bench_load.py) — never something a test sleeps through.
        if short_window is None:
            short_window = _env_float(
                "SEAWEEDFS_TPU_SLO_SHORT_WINDOW", 300.0)
        if long_window is None:
            long_window = _env_float(
                "SEAWEEDFS_TPU_SLO_LONG_WINDOW", 3600.0)
        self.role = role
        self.node = node
        self.objectives = objectives or SloObjectives()
        self.clock = clock
        self.short_window = short_window
        self.long_window = long_window
        self.slices = slices
        self.alpha = alpha
        self._lock = threading.Lock()
        # (family, status_class) -> WindowedSketch over the short window
        self._sketches: dict[tuple[str, str], WindowedSketch] = {}
        # Time-attribution plane (stats/phases.py): (family, phase) ->
        # WindowedSketch of that phase's per-request seconds.  Bounded:
        # families are bounded by the route table, phases by
        # phases.PHASES.  Fed by a 1-in-PHASE_SAMPLE_EVERY subsample
        # (slow requests always included), so the sketches skew toward
        # the tail they exist to explain while the per-request cost
        # stays flat.
        self._phase_sketches: dict[tuple[str, str], WindowedSketch] = {}
        self._phase_tick = 0
        # Aggregate data-plane sketches by op class — what heartbeats
        # ship and healthz merges.
        self._agg = {op: WindowedSketch(alpha=alpha, window=short_window,
                                        slices=slices, clock=clock)
                     for op in ("read", "write")}
        self._burn_short = _CounterRing(short_window, slices, clock)
        self._burn_long = _CounterRing(long_window, slices, clock)
        self._exemplars: "deque[dict]" = deque(maxlen=exemplar_capacity)
        self.exemplars_recorded = 0
        self._burning = False

    # -- configuration -------------------------------------------------------

    def set_objectives(self, read_p99: float | None = None,
                       availability: float | None = None) -> None:
        self.objectives = SloObjectives(read_p99, availability)
        self._burning = False

    def exemplar_threshold(self) -> float:
        return self.objectives.read_p99 or DEFAULT_EXEMPLAR_THRESHOLD

    # -- observation (rpc middleware hot path) -------------------------------

    def observe(self, family: str, method: str, status: int,
                seconds: float, trace_id: str = "",
                phases: dict | None = None) -> None:
        sc = f"{status // 100}xx"
        key = (family, sc)
        sk = self._sketches.get(key)
        if sk is None:
            with self._lock:
                sk = self._sketches.setdefault(
                    key, WindowedSketch(alpha=self.alpha,
                                        window=self.short_window,
                                        slices=self.slices,
                                        clock=self.clock))
        sk.observe(seconds)
        # Hoisted once: the threshold feeds both the phase-sketch
        # sample condition and the exemplar branch below.  (Distinct
        # from the burn engine's read-SLO `slow` flag computed in the
        # data-plane block.)
        exemplar_slow = seconds > (self.objectives.read_p99
                                   or DEFAULT_EXEMPLAR_THRESHOLD)
        phase_dict = None
        if phases is not None:
            # `phases` is a stats.phases.Ledger (rpc middleware) or a
            # plain dict (tests / direct callers); the Ledger is
            # materialized LAZILY — only for the consumers below.
            # Phase sketches are fed from a deterministic 1-in-K
            # uniform subsample: quantiles of a uniform subsample are
            # unbiased, and at per-request rates the 3-4 extra sketch
            # observes would be the plane's single biggest tax.  Slow
            # exemplars and trace spans carry FULL budgets regardless
            # — only the aggregate quantile feed is thinned.
            self._phase_tick += 1
            if exemplar_slow or \
                    self._phase_tick >= PHASE_SAMPLE_EVERY:
                self._phase_tick = 0
                phase_dict = phases.to_dict() \
                    if hasattr(phases, "to_dict") else phases
                for phase, p_seconds in phase_dict.items():
                    pkey = (family, phase)
                    psk = self._phase_sketches.get(pkey)
                    if psk is None:
                        with self._lock:
                            psk = self._phase_sketches.setdefault(
                                pkey, WindowedSketch(
                                    alpha=self.alpha,
                                    window=self.short_window,
                                    slices=self.slices,
                                    clock=self.clock))
                    psk.observe(p_seconds)
        if data_plane(family):
            read = method in ("GET", "HEAD")
            if status == 429:
                # Shed before execution: its "latency" is queue wait,
                # not service time — keep it OUT of the aggregate
                # read/write tails (a shedding storm must not make the
                # cluster p50 look better) and out of the error rate's
                # denominator; the burn windows track it separately.
                self._burn_short.add_shed()
                self._burn_long.add_shed()
            else:
                self._agg["read" if read else "write"].observe(seconds)
                bad = status >= 500
                slow = (read and self.objectives.read_p99 is not None
                        and seconds > self.objectives.read_p99)
                self._burn_short.add(bad, slow, read)
                self._burn_long.add(bad, slow, read)
        if exemplar_slow:
            self.exemplars_recorded += 1
            doc = {
                "ts": time.time(), "family": family, "method": method,
                "status": status, "seconds": round(seconds, 6),
                "trace_id": trace_id}
            if phases is not None:
                # The slow request's time budget rides the exemplar:
                # /debug/slow answers "slow doing WHAT" inline instead
                # of sending the operator to cross-reference a trace.
                if phase_dict is None:
                    phase_dict = phases.to_dict() \
                        if hasattr(phases, "to_dict") else phases
                doc["phases"] = {k: round(v, 6)
                                 for k, v in phase_dict.items()}
            self._exemplars.append(doc)

    # -- burn-rate engine ----------------------------------------------------

    @staticmethod
    def _window_rates(breaching: int, denom: int, shed: int,
                      budget: float) -> dict:
        rate = (breaching / denom) if denom else 0.0
        return {"total": denom, "breaching": breaching,
                "rate": round(rate, 6), "shed": shed,
                "burn": round(rate / budget, 3)}

    def burn_state(self) -> dict:
        """Evaluate the declared objectives over both windows; emits
        `slo.burn` (once per episode) when fast burn flips on.  Called
        from heartbeats, healthz, /debug/slo, and the burn gauge — no
        background thread needed."""
        obj = self.objectives
        out: dict = {"declared": obj.declared, "fast_burn": False,
                     "slow_burn": False}
        if not obj.declared:
            return out
        # (total, bad, slow, reads, shed) per window.
        short = self._burn_short.totals()
        long_ = self._burn_long.totals()
        fast = slow_burn = False
        worst: tuple[str, float] | None = None
        if obj.availability is not None:
            budget = 1.0 - obj.availability
            avail = {"objective": obj.availability, "budget": budget,
                     "short": self._window_rates(short[1], short[0],
                                                 short[4], budget),
                     "long": self._window_rates(long_[1], long_[0],
                                                long_[4], budget)}
            out["availability"] = avail
            b = min(avail["short"]["burn"], avail["long"]["burn"])
            if short[0] >= self.MIN_WINDOW_REQUESTS:
                if b >= FAST_BURN:
                    fast = True
                elif b >= SLOW_BURN:
                    slow_burn = True
            if worst is None or b > worst[1]:
                worst = ("availability", b)
        if obj.read_p99 is not None:
            # A p99 objective budgets 1% of READS above the threshold:
            # the denominator is reads, not all requests — a write-
            # heavy workload must not dilute a read-latency collapse
            # below the burn thresholds.
            budget = 0.01
            lat = {"objective_p99": obj.read_p99, "budget": budget,
                   "short": self._window_rates(short[2], short[3],
                                               short[4], budget),
                   "long": self._window_rates(long_[2], long_[3],
                                              long_[4], budget)}
            out["latency"] = lat
            b = min(lat["short"]["burn"], lat["long"]["burn"])
            if short[3] >= self.MIN_WINDOW_REQUESTS:
                if b >= FAST_BURN:
                    fast = True
                elif b >= SLOW_BURN:
                    slow_burn = True
            if worst is None or b > worst[1]:
                worst = ("latency", b)
        out["fast_burn"] = fast
        out["slow_burn"] = slow_burn
        # Episode flag flips under the lock: burn_state runs from
        # scrapes, heartbeats, and healthz on different threads, and
        # `slo.burn` must fire exactly once per episode.
        emit = False
        with self._lock:
            if fast and not self._burning:
                self._burning = True
                emit = True
            elif not fast:
                self._burning = False
        if emit:
            self._emit_burn(out, worst)
        return out

    def _emit_burn(self, state: dict, worst) -> None:
        from ..events import emit as emit_event
        slo_kind, burn = worst if worst else ("availability", 0.0)
        detail = state.get(slo_kind) or {}
        with _tracer.root_span("slo.burn", self.role):
            emit_event("slo.burn", node=self.node or self.role,
                       severity="warn", role=self.role, slo=slo_kind,
                       burn=burn,
                       short_rate=detail.get("short", {}).get("rate", 0.0),
                       long_rate=detail.get("long", {}).get("rate", 0.0),
                       short_total=detail.get("short", {}).get("total", 0))

    # -- exports -------------------------------------------------------------

    def quantile_gauge_values(self) -> dict:
        """Gauge callback for SeaweedFS_request_quantile_seconds
        {role, family, status, q} — only live (windowed) series."""
        out: dict[tuple, float] = {}
        with self._lock:
            items = list(self._sketches.items())
        for (family, sc), wsk in items:
            merged = wsk.merged()
            if merged.count == 0:
                continue
            for q in QUANTILES:
                out[(self.role, family, sc, f"{q:g}")] = \
                    merged.quantile(q)
        return out

    def phase_gauge_values(self) -> dict:
        """Gauge callback for SeaweedFS_request_phase_seconds
        {role, family, phase, q} — live windowed phase-time quantiles
        (the per-role answer to "where does request time go")."""
        out: dict[tuple, float] = {}
        with self._lock:
            items = list(self._phase_sketches.items())
        for (family, phase), wsk in items:
            merged = wsk.merged()
            if merged.count == 0:
                continue
            for q in QUANTILES:
                out[(self.role, family, phase, f"{q:g}")] = \
                    merged.quantile(q)
        return out

    def phase_quantiles(self) -> dict:
        """JSON view of the live phase sketches, grouped by family —
        the /debug/slo `phases` section and the bench's p99 breakdown
        source."""
        with self._lock:
            items = list(self._phase_sketches.items())
        out: dict[str, dict] = {}
        for (family, phase), wsk in items:
            merged = wsk.merged()
            if merged.count == 0:
                continue
            out.setdefault(family, {})[phase] = {
                "count": merged.count,
                **{f"p{int(q * 100)}": merged.quantile(q)
                   for q in QUANTILES}}
        return out

    def burn_gauge_values(self) -> dict:
        """Gauge callback for SeaweedFS_slo_burn_rate{role, slo,
        window}; empty when no objective is declared."""
        state = self.burn_state()
        out: dict[tuple, float] = {}
        for slo_kind in ("availability", "latency"):
            detail = state.get(slo_kind)
            if not detail:
                continue
            for window in ("short", "long"):
                out[(self.role, slo_kind, window)] = \
                    detail[window].get("burn", 0.0)
        return out

    def exemplars(self, limit: int = 50) -> list[dict]:
        out = list(self._exemplars)
        return out[-limit:][::-1]  # newest first

    def agg_quantiles(self, op: str) -> dict:
        merged = self._agg[op].merged()
        qs = {f"p{int(q * 100)}": merged.quantile(q)
              for q in QUANTILES}
        qs["count"] = merged.count
        return qs

    def heartbeat_view(self) -> dict:
        """Compact per-beat state: burn verdict + the mergeable
        aggregate sketches, so the master can fold every node into one
        cluster-wide quantile without a per-node scrape."""
        state = self.burn_state()
        return {"declared": state["declared"],
                "fast_burn": state["fast_burn"],
                "slow_burn": state["slow_burn"],
                "read": self._agg["read"].to_dict(),
                "write": self._agg["write"].to_dict()}

    def snapshot(self) -> dict:
        """Full /debug/slo payload."""
        with self._lock:
            items = list(self._sketches.items())
        families = {}
        for (family, sc), wsk in items:
            merged = wsk.merged()
            if merged.count == 0:
                continue
            families[f"{family} {sc}"] = {
                "count": merged.count,
                **{f"p{int(q * 100)}": merged.quantile(q)
                   for q in QUANTILES}}
        return {"role": self.role, "node": self.node,
                "objectives": self.objectives.to_dict(),
                "exemplar_threshold": self.exemplar_threshold(),
                "exemplars_recorded": self.exemplars_recorded,
                "burn": self.burn_state(),
                "families": families,
                "phases": self.phase_quantiles(),
                "read": {"quantiles": self.agg_quantiles("read"),
                         "sketch": self._agg["read"].to_dict()},
                "write": {"quantiles": self.agg_quantiles("write"),
                          "sketch": self._agg["write"].to_dict()}}


def merge_sketch_dicts(dicts: list[dict]) -> QuantileSketch | None:
    """Fold wire-format sketches (heartbeat_view / /debug/slo payloads)
    into one QuantileSketch — the /cluster/healthz aggregation.  Skips
    parameter-mismatched sketches (mixed-version clusters) rather than
    corrupting the estimate; returns None when nothing merged."""
    out: QuantileSketch | None = None
    for d in dicts:
        if not isinstance(d, dict) or "buckets" not in d:
            continue
        try:
            sk = QuantileSketch.from_dict(d)
        except (ValueError, TypeError, AttributeError, KeyError):
            # Malformed wire payloads (mixed-version or buggy peers:
            # buckets as a list, non-numeric fields) must degrade to
            # "skipped", never 500 the healthz handler.
            continue
        if out is None:
            out = sk
        else:
            try:
                out.merge(sk)
            except ValueError:
                continue
    return out


# -- routes ------------------------------------------------------------------

def setup_slo_routes(server) -> None:
    """Mount /debug/slow (exemplars) + /debug/slo (full SLO state) on a
    server whose enable_metrics created a tracker.  Mounted by the
    cluster roles (master/volume/filer) next to the other /debug
    surfaces; gateways keep their user-facing namespace clean."""

    def _slow(query: dict, body: bytes):
        tr = getattr(server, "slo", None)
        if tr is None:
            return (404, {"error": "slo tracking not enabled"})
        try:
            limit = int(query.get("limit", 50) or 50)
        except ValueError:
            return (400, {"error": "limit must be a number"})
        return {"role": tr.role, "node": tr.node,
                "threshold_seconds": tr.exemplar_threshold(),
                "recorded": tr.exemplars_recorded,
                "exemplars": tr.exemplars(limit)}

    def _slo(query: dict, body: bytes):
        tr = getattr(server, "slo", None)
        if tr is None:
            return (404, {"error": "slo tracking not enabled"})
        return tr.snapshot()

    server.route("GET", "/debug/slow", _slow)
    server.route("GET", "/debug/slo", _slo)
