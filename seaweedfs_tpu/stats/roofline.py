"""Device roofline plane: per-kernel work accounting and achieved
fraction of the device's measured peaks.

ROADMAP item 4 (XOR elimination, sparser RS realizations, deeper
pipeline overlap) is gated on claims that need device-side evidence:
"encode sits at N% of the shape ceiling" must come from measurement,
not hand math.  This module is that evidence plane, the device-side
sibling of the PR 9 time-attribution plane:

- an analytic per-invocation cost model — bit-matrix geometry
  (out_rows, in_rows, n, batch) -> bytes moved, GF(2) MACs,
  arithmetic intensity — mirroring the `pl.CostEstimate` the Pallas
  kernels declare (ops/coder_pallas.py);
- a once-per-process `probe_peaks()` micro-bench (device matmul peak
  per mm dtype, on-device memory bandwidth, H2D/D2H transfer, host
  stream bandwidth), cached to disk keyed by backend + device kind so
  a process restart does not re-pay the probe;
- a bounded invocation ring + windowed achieved-fraction sketches
  keyed by (kernel, codec, dtype, geometry), fed by every
  execution-fenced kernel call (the fence is the caller's job — a
  dispatch-only wall would flatter the kernel);
- always-on pipeline occupancy: `cluster_encode`/`cluster_rebuild`
  hand their per-batch stage spans (stack | dispatch | device | drain)
  to `note_pipeline()`, which keeps recent gantts, publishes the
  device-occupancy fraction, names the stage that starved the device,
  and emits a `device.slow` event on sustained occupancy collapse.

Like the other planes the kernel catalog is closed (recording an
uncataloged kernel raises), the ledger is a process singleton with
absolute rows (heartbeat rollup is idempotent), and the kill switch
(`-roofline=false` / SEAWEEDFS_TPU_ROOFLINE=0) reduces every call
site to one module-flag check.

The conservation gate, in the spirit of the wire-flow plane: analytic
bytes per invocation must match the ledger-measured bytes within
max(1%, 4KB) — a cost model that drifts from what the kernels
actually move is worse than no model.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .metrics import Counter, Gauge
from .sketch import WindowedSketch

# -- arming ------------------------------------------------------------------
# One module-level flag; disarmed call sites pay exactly this check
# (same discipline as fault points and metered locks, asserted by
# tests/test_roofline.py).

ARMED = os.environ.get("SEAWEEDFS_TPU_ROOFLINE", "1") not in ("0", "false")


def set_armed(on: bool) -> None:
    global ARMED
    ARMED = bool(on)


# -- kernel catalog ----------------------------------------------------------
# Closed set, like events/journal.py TYPES and flows.PURPOSES:
# RooflineLedger.record() raises on anything not listed here, so a new
# device kernel cannot ship without declaring itself (and getting a
# cost model + tests).

KERNELS = {
    "encode_kernel":
        "single-volume parity encode: bit-matrix apply on the stacked "
        "data shards (ops/coder_pallas.py PallasCoder.encode)",
    "encode_crc_kernel":
        "fused encode + per-shard CRC32 fold in one device pass "
        "(ops/coder_pallas.py PallasCoder.encode_with_crc)",
    "reconstruct_kernel":
        "decode-matrix apply rebuilding missing shards from survivors "
        "(ops/coder_pallas.py PallasCoder.reconstruct)",
    "batch_encode":
        "multi-volume sharded encode on the device mesh "
        "(parallel/sharded_codec.py batched_encode[_with_crc])",
    "batch_reconstruct":
        "multi-volume sharded rebuild on the device mesh "
        "(parallel/sharded_codec.py batched_reconstruct[_with_crc])",
}

PIPELINE_STAGES = ("stack", "dispatch", "device", "drain")

kernel_seconds_total = Counter(
    "SeaweedFS_kernel_seconds_total",
    "execution-fenced device kernel wall seconds",
    ("kernel", "codec", "dtype"))

kernel_bytes_total = Counter(
    "SeaweedFS_kernel_bytes_total",
    "analytic bytes moved by device kernels (cost-model bytes; the "
    "conservation check pins these to ledger-measured bytes)",
    ("kernel", "codec", "dtype"))

kernel_work_total = Counter(
    "SeaweedFS_kernel_work_total",
    "analytic GF(2) MACs performed by device kernels",
    ("kernel", "codec", "dtype"))

device_occupancy = Gauge(
    "SeaweedFS_device_occupancy",
    "fraction of the streamed-pipeline window each stage kept the "
    "device busy (stage=device is the occupancy headline; other "
    "stages show where the wall went)",
    ("stage",))


def validate(kernel: str) -> str:
    if kernel not in KERNELS:
        raise ValueError(
            f"unknown roofline kernel {kernel!r}; cataloged: "
            f"{sorted(KERNELS)}")
    return kernel


# -- analytic cost model -----------------------------------------------------
# The bit-matrix kernels multiply an (8*out_rows x 8*in_rows) GF(2)
# matrix against 8*in_rows bit-rows of n bytes each: the same algebra
# the Pallas kernel declares in its pl.CostEstimate
# (ops/coder_pallas.py) — flops = 2 * (8*out) * (8*in) * n,
# bytes = (in + out) * n.  The fused-CRC variant folds a second
# (8*(in+out) x 32)-bit matrix over every input AND output row.


def cost_model(out_rows: int, in_rows: int, n: int, *, batch: int = 1,
               crc: bool = False) -> dict:
    """Analytic work for one kernel invocation.

    Returns bytes moved (read + written payload), GF(2) MACs (one MAC
    = one AND+XOR bit op on a byte lane), flops (2*MACs, the matmul
    convention the probe and the Pallas CostEstimate both use), and
    arithmetic intensity (flops per byte)."""
    b = int(batch)
    nbytes = (in_rows + out_rows) * n * b
    macs = 8 * out_rows * 8 * in_rows * n * b
    if crc:
        # CRC fold: 32 output bits from 8*(in+out) input bits, per
        # byte column (matches the kernel's declared estimate).
        macs += 8 * (in_rows + out_rows) * 32 * n * b
    flops = 2 * macs
    return {
        "bytes": nbytes,
        "macs": macs,
        "flops": flops,
        "intensity": flops / nbytes if nbytes else 0.0,
    }


def geometry_key(out_rows: int, in_rows: int, n: int,
                 batch: int = 1) -> str:
    if batch > 1:
        return f"{out_rows}x{in_rows}x{n}b{batch}"
    return f"{out_rows}x{in_rows}x{n}"


# -- GF(2) work: dense vs post-elimination -----------------------------------
# The bench publishes effective (post-elimination) XOR work beside the
# dense count per codec, so matrix-scheduling work (arxiv 2108.02692,
# arxiv 1312.5155) lands against an already-published baseline column.


def dense_gf2_work(bitmatrix) -> int:
    """XOR count of the naive schedule: each output bit-row of weight
    w costs w-1 XORs (w ANDs are free against constant 0/1 entries)."""
    import numpy as np
    bm = (np.asarray(bitmatrix) & 1).astype(np.uint8)
    weights = bm.sum(axis=1)
    return int(np.maximum(weights.astype(np.int64) - 1, 0).sum())


def effective_gf2_work(bitmatrix, max_rounds: int = 100000) -> int:
    """XOR count after greedy common-subexpression elimination (Paar's
    algorithm): repeatedly factor out the column pair shared by the
    most output rows.  Deterministic (ties break to the smallest
    pair), exact on the matrices we ship (tens of rows/columns)."""
    import numpy as np
    bm = (np.asarray(bitmatrix) & 1).astype(np.uint8)
    rows = [set(np.flatnonzero(r).tolist()) for r in bm]
    next_col = bm.shape[1]
    extracted = 0
    for _ in range(max_rounds):
        counts: dict[tuple[int, int], int] = {}
        for r in rows:
            rs = sorted(r)
            for i in range(len(rs)):
                for j in range(i + 1, len(rs)):
                    p = (rs[i], rs[j])
                    counts[p] = counts.get(p, 0) + 1
        if not counts:
            break
        best = max(counts.values())
        if best < 2:
            break
        pair = min(p for p, c in counts.items() if c == best)
        a, b = pair
        for r in rows:
            if a in r and b in r:
                r.discard(a)
                r.discard(b)
                r.add(next_col)
        next_col += 1
        extracted += 1
    return extracted + sum(max(len(r) - 1, 0) for r in rows)


# -- peak probing ------------------------------------------------------------

_PEAKS_VERSION = 2
_PROBE_DTYPES = ("int8", "bf16")
_peaks_lock = threading.Lock()
_peaks: dict | None = None


def _cache_dir() -> str:
    d = os.environ.get("SEAWEEDFS_TPU_ROOFLINE_CACHE", "")
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "seaweedfs_tpu")


def _cache_path(backend: str, kind: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in f"{backend}_{kind}")
    return os.path.join(_cache_dir(), f"roofline_peaks_{safe}.json")


def _best_of(fn, reps: int = 3) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _probe_matmul(jnp, jax, dtype: str, m: int = 256) -> float:
    """Measured matmul flops/s for one mm dtype (int8 accumulating to
    int32, bf16 to f32 — the two dtypes PallasCoder dispatches)."""
    if dtype == "int8":
        a = jnp.ones((m, m), jnp.int8)
        acc = jnp.int32
    else:
        a = jnp.ones((m, m), jnp.bfloat16)
        acc = jnp.float32

    @jax.jit
    def mm(x, y):
        return jax.lax.dot_general(
            x, y, (((1,), (0,)), ((), ())), preferred_element_type=acc)

    jax.block_until_ready(mm(a, a))  # compile outside the clock
    t = _best_of(lambda: jax.block_until_ready(mm(a, a)))
    return 2.0 * m ** 3 / max(t, 1e-9)


def _probe_membw(jnp, jax, nbytes: int = 1 << 23) -> float:
    """On-device streaming bandwidth: one read + one write pass."""
    x = jnp.ones((nbytes,), jnp.uint8)

    @jax.jit
    def touch(v):
        return v + 1

    jax.block_until_ready(touch(x))
    t = _best_of(lambda: jax.block_until_ready(touch(x)))
    return 2.0 * nbytes / max(t, 1e-9)


def _probe_transfers(np, jax, nbytes: int = 1 << 23) -> tuple:
    host = np.ones(nbytes, np.uint8)
    dev = jax.block_until_ready(jax.device_put(host))
    h2d = nbytes / max(
        _best_of(lambda: jax.block_until_ready(jax.device_put(host))),
        1e-9)
    d2h = nbytes / max(_best_of(lambda: np.asarray(dev)), 1e-9)
    stream = 2.0 * nbytes / max(_best_of(host.copy), 1e-9)
    return h2d, d2h, stream


def probe_peaks(force: bool = False) -> dict:
    """Once-per-process measured device peaks, disk-cached keyed by
    (backend, device kind) so restarts skip the micro-bench.  Every
    probe is best-of-3 with compile outside the clock; failures
    degrade to a zeroed doc rather than taking the caller down."""
    global _peaks
    with _peaks_lock:
        if _peaks is not None and not force:
            return _peaks
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np
            backend = jax.default_backend()
            devs = jax.local_devices()
            kind = devs[0].device_kind if devs else "unknown"
        except Exception:  # noqa: BLE001 — no usable device stack
            _peaks = {"version": _PEAKS_VERSION, "backend": "none",
                      "device_kind": "none", "matmul_flops": {},
                      "membw_bps": 0.0, "h2d_bps": 0.0, "d2h_bps": 0.0,
                      "host_stream_bps": 0.0, "error": "jax unavailable"}
            return _peaks

        path = _cache_path(backend, kind)
        if not force:
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                if doc.get("version") == _PEAKS_VERSION:
                    _peaks = doc
                    return _peaks
            except Exception:  # noqa: BLE001 — absent/stale cache
                pass

        t_start = time.perf_counter()
        doc = {"version": _PEAKS_VERSION, "backend": backend,
               "device_kind": kind, "matmul_flops": {},
               "membw_bps": 0.0, "h2d_bps": 0.0, "d2h_bps": 0.0,
               "host_stream_bps": 0.0}
        try:
            for dt in _PROBE_DTYPES:
                doc["matmul_flops"][dt] = _probe_matmul(jnp, jax, dt)
            doc["membw_bps"] = _probe_membw(jnp, jax)
            h2d, d2h, stream = _probe_transfers(np, jax)
            doc["h2d_bps"], doc["d2h_bps"] = h2d, d2h
            doc["host_stream_bps"] = stream
        except Exception as e:  # noqa: BLE001 — probes are best-effort
            doc["error"] = f"{type(e).__name__}: {e}"
        doc["probe_seconds"] = round(time.perf_counter() - t_start, 3)

        try:
            os.makedirs(_cache_dir(), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — read-only home is fine
            pass
        _peaks = doc
        return _peaks


def roofline_floor_seconds(flops: float, nbytes: float,
                           peaks: dict, dtype: str) -> float | None:
    """The roofline lower bound on wall time: compute-limited OR
    bandwidth-limited, whichever binds.  None when the probe failed
    (an achieved fraction against a made-up peak is noise)."""
    pf = (peaks.get("matmul_flops") or {}).get(dtype) or 0.0
    bw = peaks.get("membw_bps") or 0.0
    if pf <= 0.0 or bw <= 0.0:
        return None
    return max(flops / pf, nbytes / bw)


# -- occupancy collapse detection --------------------------------------------

_COLLAPSE_OCCUPANCY = 0.35  # device-busy fraction below this ...
_COLLAPSE_STREAK = 3        # ... for this many consecutive batches
_EMIT_EVERY = 5.0           # one device.slow event per this many s

# -- the ledger --------------------------------------------------------------

_RING_MAX = 256        # recent invocations kept for /debug/device
_PIPELINES_MAX = 16    # recent pipeline occupancy docs
_GANTT_LAST = 8        # batches of gantt carried per pipeline doc


class RooflineLedger:
    """Process-global per-kernel accounting: bounded invocation ring,
    absolute per-series totals, windowed achieved-fraction sketches,
    and recent pipeline-occupancy docs.

    The clock is injected (tests advance sketch windows and collapse
    streaks without sleeping); `record()` is the single kernel entry
    point and `note_pipeline()` the single occupancy entry point."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=_RING_MAX)
        # (kernel, codec, dtype, geometry) ->
        #   [count, seconds, bytes, macs, WindowedSketch]
        self._series: dict[tuple, list] = {}
        self._pipelines: deque = deque(maxlen=_PIPELINES_MAX)
        self._streak: dict[str, int] = {}
        self._collapsed: dict[str, bool] = {}
        self._last_emit = 0.0

    # -- kernel records ---------------------------------------------

    def record(self, kernel: str, codec: str, dtype: str, *,
               out_rows: int, in_rows: int, n: int, batch: int = 1,
               crc: bool = False, seconds: float,
               measured_bytes: int | None = None,
               node: str = "") -> dict:
        """One execution-fenced kernel invocation.  The caller fences
        (block_until_ready / host materialization) BEFORE stopping its
        clock; this only does the bookkeeping."""
        validate(kernel)
        cost = cost_model(out_rows, in_rows, n, batch=batch, crc=crc)
        geom = geometry_key(out_rows, in_rows, n, batch)
        secs = max(float(seconds), 1e-9)

        peaks = probe_peaks()
        floor = roofline_floor_seconds(cost["flops"], cost["bytes"],
                                       peaks, dtype)
        achieved = None if floor is None else min(floor / secs, 1.0)

        row = {"ts": round(self.clock(), 6), "kernel": kernel,
               "codec": codec, "dtype": dtype, "geometry": geom,
               "seconds": round(secs, 9), "bytes": cost["bytes"],
               "macs": cost["macs"], "intensity":
                   round(cost["intensity"], 3),
               "achieved": None if achieved is None
                   else round(achieved, 6),
               "measured_bytes": measured_bytes, "node": node}
        key = (kernel, codec, dtype, geom)
        with self._lock:
            self._ring.append(row)
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = [
                    0, 0.0, 0, 0,
                    WindowedSketch(min_value=1e-6, clock=self.clock)]
            series[0] += 1
            series[1] += secs
            series[2] += cost["bytes"]
            series[3] += cost["macs"]
            if achieved is not None:
                series[4].observe(achieved)

        kernel_seconds_total.inc(secs, kernel=kernel, codec=codec,
                                 dtype=dtype)
        kernel_bytes_total.inc(cost["bytes"], kernel=kernel,
                               codec=codec, dtype=dtype)
        kernel_work_total.inc(cost["macs"], kernel=kernel, codec=codec,
                              dtype=dtype)
        return row

    # -- pipeline occupancy -----------------------------------------

    def note_pipeline(self, kind: str, recorder, node: str = "") -> dict:
        """Fold one streamed run's recorder into the ledger: keep the
        occupancy doc + recent gantt, publish the occupancy gauge, and
        emit `device.slow` when the device-busy fraction stays
        collapsed for _COLLAPSE_STREAK consecutive runs."""
        occ = recorder.device_occupancy()
        bubbles = recorder.bubble_attribution()
        doc = {"ts": round(self.clock(), 6), "kind": kind,
               "node": node, "occupancy": occ, "bubbles": bubbles,
               "gantt": recorder.gantt(last=_GANTT_LAST)}
        frac = occ.get("fraction")
        with self._lock:
            self._pipelines.append(doc)
            collapsed = False
            if frac is not None:
                if frac < _COLLAPSE_OCCUPANCY:
                    self._streak[kind] = self._streak.get(kind, 0) + 1
                else:
                    self._streak[kind] = 0
                collapsed = self._streak[kind] >= _COLLAPSE_STREAK
                self._collapsed[kind] = collapsed
            now = self.clock()
            should_emit = (collapsed
                           and now - self._last_emit >= _EMIT_EVERY)
            if should_emit:
                self._last_emit = now

        if frac is not None:
            device_occupancy.set(frac, stage="device")
            for stage, share in (occ.get("stages") or {}).items():
                if stage != "device":
                    device_occupancy.set(share, stage=stage)
        if should_emit:
            self._emit_slow(kind, node, frac, bubbles)
        return doc

    def _emit_slow(self, kind: str, node: str, frac: float,
                   bubbles: dict) -> None:
        try:
            from ..events import emit
            from ..trace import root_span
            with root_span("device.slow", "roofline"):
                emit("device.slow", node=node, severity="warn",
                     pipeline=kind,
                     occupancy=round(float(frac), 4),
                     threshold=_COLLAPSE_OCCUPANCY,
                     streak=self._streak.get(kind, 0),
                     starving_stage=bubbles.get("starving_stage", ""),
                     bubble_seconds=round(
                         float(bubbles.get("bubble_seconds", 0.0)), 6))
        except Exception:  # noqa: BLE001 — accounting must never
            pass           # take the encode path down

    # -- conservation -----------------------------------------------

    def conservation(self) -> dict:
        """Analytic bytes vs ledger-measured bytes, per invocation in
        the ring, within max(1%, 4KB) — the cost-model correctness
        gate (PR 16 wire-flow style)."""
        checked = 0
        violations = []
        with self._lock:
            rows = list(self._ring)
        for row in rows:
            mb = row.get("measured_bytes")
            if mb is None:
                continue
            checked += 1
            tol = max(0.01 * mb, 4096.0)
            if abs(row["bytes"] - mb) > tol:
                if len(violations) < 8:
                    violations.append(
                        {"kernel": row["kernel"],
                         "geometry": row["geometry"],
                         "analytic": row["bytes"], "measured": mb})
        return {"ok": not violations, "checked": checked,
                "violations": violations}

    # -- read side ---------------------------------------------------

    def kernel_table(self) -> list[dict]:
        """Absolute per-series rollup (idempotent heartbeat rows)."""
        with self._lock:
            items = sorted(self._series.items())
            out = []
            for (kernel, codec, dtype, geom), s in items:
                sk = s[4]
                out.append({"kernel": kernel, "codec": codec,
                            "dtype": dtype, "geometry": geom,
                            "count": s[0],
                            "seconds": round(s[1], 6),
                            "bytes": s[2], "work": s[3],
                            "achieved_p50": _rq(sk, 0.5),
                            "achieved_p95": _rq(sk, 0.95)})
        return out

    def recent(self, n: int = 32) -> list[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    def pipelines(self, n: int = 4) -> list[dict]:
        with self._lock:
            return list(self._pipelines)[-n:]

    def occupancy_summary(self) -> dict:
        """Latest occupancy per pipeline kind + the collapse verdicts
        the healthz warning keys on."""
        with self._lock:
            docs = list(self._pipelines)
            collapsed = dict(self._collapsed)
            streaks = dict(self._streak)
        latest: dict[str, dict] = {}
        for doc in docs:
            occ = doc.get("occupancy") or {}
            latest[doc["kind"]] = {
                "fraction": occ.get("fraction"),
                "starving_stage":
                    (doc.get("bubbles") or {}).get("starving_stage", ""),
                "ts": doc.get("ts")}
        return {"latest": latest, "collapsed": collapsed,
                "streaks": streaks,
                "any_collapsed": any(collapsed.values())}

    def heartbeat_view(self) -> dict:
        """What a volume server ships under hb["device"]: absolute
        kernel rows (merge is idempotent) + the occupancy summary."""
        return {"kernels": self.kernel_table(),
                "occupancy": self.occupancy_summary()}

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._series.clear()
            self._pipelines.clear()
            self._streak.clear()
            self._collapsed.clear()
            self._last_emit = 0.0


def _rq(sketch, q: float):
    v = sketch.quantile(q)
    return None if v is None else round(v, 6)


LEDGER = RooflineLedger()


def _device_memory_stats() -> list[dict]:
    """jax.local_devices() memory stats, best-effort (CPU backends
    usually expose nothing)."""
    out = []
    try:
        import jax
        for d in jax.local_devices():
            row = {"id": d.id, "kind": d.device_kind,
                   "platform": d.platform}
            try:
                ms = d.memory_stats()
                if ms:
                    row["bytes_in_use"] = ms.get("bytes_in_use")
                    row["bytes_limit"] = ms.get("bytes_limit")
            except Exception:  # noqa: BLE001 — not all backends
                pass
            out.append(row)
    except Exception:  # noqa: BLE001 — no jax, no rows
        pass
    return out


def debug_doc(node: str, role: str) -> dict:
    """GET /debug/device payload: measured peaks, the per-kernel
    roofline table, recent invocations, recent pipeline gantts with
    bubble attribution, the conservation verdict, and device memory
    stats."""
    return {"node": node, "role": role, "armed": ARMED,
            "peaks": probe_peaks(),
            "kernels": LEDGER.kernel_table(),
            "recent": LEDGER.recent(16),
            "pipelines": LEDGER.pipelines(4),
            "occupancy": LEDGER.occupancy_summary(),
            "conservation": LEDGER.conservation(),
            "devices": _device_memory_stats()}
