"""Wire-flow attribution plane: per-purpose byte accounting.

Every byte that crosses a socket is attributed to a *purpose* from the
static catalog below — the question "how many bytes moved over which
link, for what" that traces, events, and latency SLOs cannot answer.
The plane has one choke point (cluster/rpc.py counts request and
response bodies on both the client and server side of every RPC,
including the zero-copy sendfile/splice legs, whose syscall-returned
totals never transit userspace) plus direct feeds for traffic that
bypasses the RPC plane entirely (tier backend uploads/downloads).

Like the event catalog (events/journal.py), the purpose catalog is
closed: noting an uncataloged purpose raises, so a new traffic class
cannot ship without declaring itself here (and the anti-rot test in
tests/test_flows.py drives every entry through its real code path).

Surfaces: `GET /debug/flows` per node, heartbeat-carried rows merged
into the master's cluster traffic matrix at `GET /cluster/flows`, the
`SeaweedFS_wire_bytes_total{purpose,direction,peer_role}` instrument on
every role, and declarative per-purpose bandwidth budgets
(`-flows.budget repair.fetch=50MB/s`) that emit a `flows.budget` event
and a healthz warning on sustained breach.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from .metrics import Counter

# -- purpose catalog ---------------------------------------------------------
# Closed set, like events/journal.py TYPES: FlowLedger.note() raises on
# anything not listed here.  Wire headers with an unknown purpose fall
# back to the path heuristic instead (a foreign client must not be able
# to 500 a server by sending garbage).

PURPOSES = {
    "user.read":
        "client-facing reads: needle GETs, filer file serves",
    "user.write":
        "client-facing writes: needle POSTs/DELETEs, filer uploads",
    "replicate.fanout":
        "synchronous write fan-out to sibling replica holders",
    "ec.gather":
        "EC reads pulled for encode/rebuild/degraded reads: source "
        "volume files, remote shards, shard intervals",
    "ec.scatter":
        "EC shard placement pushes: encode spread, rebuilt-shard push",
    "repair.fetch":
        "self-healing fetches: intact replica needle reads that heal "
        "a quarantined copy",
    "rlog.ship":
        "cross-cluster change-log shipping to the standby",
    "tier.up":
        "cold volume data uploaded to a remote tier backend",
    "tier.down":
        "remote tier downloads: promotion copy-back, read-through "
        "block fills",
    "proxy":
        "filer->volume proxy legs serving a user request",
    "control":
        "control plane: heartbeats, lookups, assigns, admin verbs, "
        "introspection",
}

# Stamped by rpc._request on every outbound hop (an explicit call-site
# header wins) so the receiving server attributes the same purpose —
# conservation (A->B sent == B<-A received, per purpose) holds by
# construction, not by parallel heuristics agreeing.
PURPOSE_HEADER = "X-Weed-Purpose"
# Self-identification: the caller's "host:port" and role ride every
# hop so the master's traffic matrix can pair A's "out" rows with B's
# "in" rows into per-link cells.
NODE_HEADER = "X-Weed-Node"
ROLE_HEADER = "X-Weed-Role"

DIRECTIONS = ("in", "out")

wire_bytes_total = Counter(
    "SeaweedFS_wire_bytes_total",
    "wire bytes by transfer purpose and direction (HTTP body bytes, "
    "framing excluded; zero-copy sendfile/splice legs count "
    "syscall-returned totals)",
    ("purpose", "direction", "peer_role"))


def validate(purpose: str) -> str:
    if purpose not in PURPOSES:
        raise ValueError(
            f"unknown flow purpose {purpose!r}; cataloged: "
            f"{sorted(PURPOSES)}")
    return purpose


def tag(purpose: str) -> dict:
    """Request-header dict a call site merges into its rpc headers to
    declare the transfer's purpose (worker-thread fan-outs can't rely
    on the thread-local purpose context)."""
    return {PURPOSE_HEADER: validate(purpose)}


# -- purpose resolution ------------------------------------------------------

_CONTROL_PREFIXES = ("/dir/", "/cluster/", "/admin/", "/debug/",
                     "/col/", "/vol/", "/stats", "/raft", "/ui")
_CONTROL_PATHS = ("/heartbeat", "/metrics", "/status", "/dir", "/vol",
                  "/cluster", "/admin", "/debug")


def resolve(method: str, path: str, header_purpose: str = "",
            query_type: str = "", low_priority: bool = False) -> str:
    """Best-effort purpose for a request that did not declare one.

    A valid explicit header always wins (an UNKNOWN header value falls
    through — heuristic, never a 500); `?type=replicate` is the legacy
    fan-out marker; control-plane paths and low-priority internal
    traffic are `control`; what remains is a user read or write."""
    if header_purpose in PURPOSES:
        return header_purpose
    if query_type == "replicate":
        return "replicate.fanout"
    p = path.split("?", 1)[0]
    if p.startswith(_CONTROL_PREFIXES) or p in _CONTROL_PATHS:
        return "control"
    if low_priority:
        return "control"
    return "user.read" if method in ("GET", "HEAD") else "user.write"


# -- local identity + per-request context ------------------------------------

_tls = threading.local()
_proc_lock = threading.Lock()
_proc_node = ""
_proc_role = "client"

_ROLE_OF_SUBSYSTEM = {"volumeServer": "volume"}


def role_of(subsystem: str) -> str:
    return _ROLE_OF_SUBSYSTEM.get(subsystem, subsystem)


def set_process_identity(node: str, role: str) -> None:
    """Default identity for threads that never bound one (daemons,
    pool workers).  First server wins: a single-role process (the
    deployed case) self-identifies correctly; multi-role in-process
    test stacks bind per-thread instead."""
    global _proc_node, _proc_role
    with _proc_lock:
        if not _proc_node:
            _proc_node, _proc_role = node, role


def bind_thread(node: str, role: str) -> None:
    """This thread's outbound RPCs originate from `node` (a server's
    handler thread, a heartbeat loop, the replication shipper)."""
    _tls.node, _tls.role = node, role


def clear_thread() -> None:
    _tls.node = _tls.role = None


def local_identity() -> tuple[str, str]:
    node = getattr(_tls, "node", None)
    if node:
        return node, getattr(_tls, "role", "") or "client"
    return _proc_node, _proc_role


@contextmanager
def purpose(p: str):
    """Thread-local purpose context: outbound RPCs under this block
    are attributed to `p` (same-thread call sites; worker-thread
    fan-outs pass tag() headers instead)."""
    validate(p)
    prev = getattr(_tls, "purpose", None)
    _tls.purpose = p
    try:
        yield
    finally:
        _tls.purpose = prev


def current_purpose() -> str | None:
    return getattr(_tls, "purpose", None)


def begin_request(peer: str, peer_role: str, req_purpose: str) -> None:
    """Server side: park the resolved (peer, peer_role, purpose) for
    the request this thread is handling, so _respond can note the
    response leg without re-threading the values through dispatch."""
    _tls.req = (peer, peer_role, req_purpose)


def current_request() -> tuple | None:
    return getattr(_tls, "req", None)


def end_request() -> None:
    _tls.req = None


# -- bandwidth budgets -------------------------------------------------------

_UNITS = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}


def parse_rate(spec: str) -> float:
    """'50MB/s' / '512KB' / '1.5GB/s' -> bytes per second."""
    s = spec.strip()
    if s.endswith("/s"):
        s = s[:-2]
    s = s.strip().upper()
    for suffix in ("GB", "MB", "KB", "B"):
        if s.endswith(suffix):
            num = s[:-len(suffix)].strip()
            return float(num) * _UNITS[suffix]
    return float(s)


def parse_budgets(spec: str) -> dict[str, float]:
    """'-flows.budget repair.fetch=50MB/s,rlog.ship=1MB/s' grammar:
    comma-separated purpose=rate pairs; unknown purposes raise at
    startup, not at breach time."""
    out: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"flows budget {part!r}: expected purpose=rate")
        p, rate = part.split("=", 1)
        out[validate(p.strip())] = parse_rate(rate)
    return out


# -- the ledger --------------------------------------------------------------

# Rate window: bytes summed over the last _RATE_WINDOW seconds of
# 1-second buckets.  Short on purpose — budgets are about sustained
# pressure NOW, not lifetime averages.
_RATE_WINDOW = 2.0
_EMIT_EVERY = 5.0  # one flows.budget event per episode per this many s


class FlowLedger:
    """Per-process byte/op accounting keyed by
    (local, peer_addr, peer_role, purpose, direction).

    `local` is the originating endpoint ("host:port" of the server the
    noting thread belongs to, "" for a bare client process) — it keeps
    attribution per-node when several roles share one process (test
    stacks), and is the key the heartbeat filters on when a volume
    server ships its rows to the master."""

    def __init__(self):
        self._lock = threading.Lock()
        # key -> [bytes, ops]
        self._rows: dict[tuple, list] = {}
        # (local, purpose, direction) -> deque of [epoch-sec, bytes]
        self._buckets: dict[tuple, deque] = {}
        self._budgets: dict[str, float] = {}
        self._sustain = 2.0
        self._breach: dict[tuple, float] = {}  # bucket key -> since ts
        self._last_emit: dict[str, float] = {}
        self._env_loaded = False

    # -- configuration ----------------------------------------------

    def set_budgets(self, budgets: dict[str, float],
                    sustain: float | None = None) -> None:
        for p in budgets:
            validate(p)
        with self._lock:
            self._budgets = dict(budgets)
            if sustain is not None:
                self._sustain = float(sustain)
            self._env_loaded = True
            self._breach.clear()
            self._last_emit.clear()

    def _ensure_env(self) -> None:
        # -flows.budget reaches servers as an env var (command/
        # __init__.py) — loaded lazily so import order never matters.
        if self._env_loaded:
            return
        self._env_loaded = True
        spec = os.environ.get("SEAWEEDFS_TPU_FLOWS_BUDGET", "")
        sus = os.environ.get("SEAWEEDFS_TPU_FLOWS_SUSTAIN", "")
        try:
            if spec:
                self._budgets = parse_budgets(spec)
            if sus:
                self._sustain = float(sus)
        except ValueError:
            # A bad spec must not take the data path down; the flag
            # parser validates loudly at startup.
            pass

    # -- the single entry point -------------------------------------

    def note(self, purpose_: str, direction: str, nbytes: int, *,
             peer: str = "", peer_role: str = "", local: str | None = None,
             ops: int = 1) -> None:
        validate(purpose_)
        if direction not in DIRECTIONS:
            raise ValueError(f"flow direction {direction!r} not in "
                             f"{DIRECTIONS}")
        if local is None:
            local = local_identity()[0]
        n = int(nbytes)
        key = (local, peer, peer_role, purpose_, direction)
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = [0, 0]
            row[0] += n
            row[1] += ops
        if n:
            wire_bytes_total.inc(n, purpose=purpose_,
                                 direction=direction,
                                 peer_role=peer_role or "client")
            self._pace(local, purpose_, direction, n)

    # -- budget pacing ----------------------------------------------

    def _pace(self, local: str, purpose_: str, direction: str,
              n: int) -> None:
        self._ensure_env()
        now = time.time()
        key = (local, purpose_, direction)
        with self._lock:
            dq = self._buckets.get(key)
            if dq is None:
                dq = self._buckets[key] = deque(maxlen=8)
            sec = int(now)
            if dq and dq[-1][0] == sec:
                dq[-1][1] += n
            else:
                dq.append([sec, n])
            limit = self._budgets.get(purpose_)
        if limit is None:
            return
        rate = self.rate(local, purpose_, direction, now=now)
        if rate <= limit:
            self._breach.pop(key, None)
            return
        since = self._breach.setdefault(key, now)
        if now - since < self._sustain:
            return
        last = self._last_emit.get(purpose_, 0.0)
        if now - last < _EMIT_EVERY:
            return
        self._last_emit[purpose_] = now
        self._emit_breach(local, purpose_, direction, rate, limit,
                          now - since)

    @staticmethod
    def _emit_breach(local: str, purpose_: str, direction: str,
                     rate: float, limit: float, sustained: float) -> None:
        try:
            from ..events import emit
            from ..trace import root_span
            with root_span("flows.budget", "flows"):
                emit("flows.budget", node=local, severity="warn",
                     purpose=purpose_, direction=direction,
                     rate_bps=int(rate), limit_bps=int(limit),
                     sustained_seconds=round(sustained, 3))
        except Exception:  # noqa: BLE001 — accounting must never
            pass           # take the data path down

    def rate(self, local: str, purpose_: str, direction: str,
             now: float | None = None) -> float:
        """Bytes/second over the trailing window for one
        (local, purpose, direction)."""
        now = time.time() if now is None else now
        lo = now - _RATE_WINDOW
        with self._lock:
            dq = self._buckets.get((local, purpose_, direction))
            if not dq:
                return 0.0
            total = sum(b for sec, b in dq if sec >= lo)
        return total / _RATE_WINDOW

    # -- read side ---------------------------------------------------

    def snapshot(self, local: str | None = None) -> list[dict]:
        """Cumulative rows (absolute values — the heartbeat rollup is
        idempotent, a dropped beat never double-counts)."""
        with self._lock:
            items = sorted(self._rows.items())
        return [{"local": k[0], "peer": k[1], "peer_role": k[2],
                 "purpose": k[3], "direction": k[4],
                 "bytes": v[0], "ops": v[1]}
                for k, v in items
                if local is None or k[0] == local]

    def totals(self, purpose_: str | None = None,
               direction: str | None = None,
               local: str | None = None,
               peer: str | None = None) -> tuple[int, int]:
        """(bytes, ops) summed over matching rows — the cross-assert
        hook tests compare legacy per-subsystem counters against."""
        b = o = 0
        with self._lock:
            for (loc, pr, _role, purp, d), (nb, no) in \
                    self._rows.items():
                if purpose_ is not None and purp != purpose_:
                    continue
                if direction is not None and d != direction:
                    continue
                if local is not None and loc != local:
                    continue
                if peer is not None and pr != peer:
                    continue
                b += nb
                o += no
        return b, o

    def budget_status(self, local: str | None = None) -> dict:
        """Per budgeted purpose: configured limit, the worst live rate
        across directions, and whether the breach has sustained past
        the threshold (the healthz-warning condition)."""
        self._ensure_env()
        now = time.time()
        with self._lock:
            budgets = dict(self._budgets)
            bucket_keys = list(self._buckets)
            sustain = self._sustain
        out: dict[str, dict] = {}
        for p, limit in sorted(budgets.items()):
            worst_rate = 0.0
            worst_dir = ""
            breached = False
            for key in bucket_keys:
                loc, purp, d = key
                if purp != p or (local is not None and loc != local):
                    continue
                r = self.rate(loc, purp, d, now=now)
                if r > worst_rate:
                    worst_rate, worst_dir = r, d
                since = self._breach.get(key)
                if since is not None and now - since >= sustain \
                        and r > limit:
                    breached = True
            out[p] = {"limit_bps": limit,
                      "rate_bps": round(worst_rate, 1),
                      "direction": worst_dir, "breached": breached}
        return out

    def reset(self) -> None:
        """Test hook: fresh ledger AND fresh budget config (env
        re-read on next note)."""
        with self._lock:
            self._rows.clear()
            self._buckets.clear()
            self._budgets = {}
            self._sustain = 2.0
            self._breach.clear()
            self._last_emit.clear()
            self._env_loaded = False


LEDGER = FlowLedger()


def debug_doc(node: str, role: str) -> dict:
    """GET /debug/flows payload: this process's full ledger (every
    local identity it has noted under), budget verdicts, and the
    catalog itself (so the shell can validate -purpose filters)."""
    return {"node": node, "role": role,
            "purposes": {p: PURPOSES[p] for p in sorted(PURPOSES)},
            "rows": LEDGER.snapshot(),
            "budgets": LEDGER.budget_status()}
