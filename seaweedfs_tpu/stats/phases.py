"""Per-request phase ledger: where did a request's wall time go?

The SLO plane (stats/slo.py) answers *which* requests are slow and
*whether* the tail is burning; this module answers *why* — how one
request's wall time splits across named phases:

  queue           admission-lane wait before dispatch (cluster/rpc.py)
  lock            time blocked on instrumented hot locks
                  (stats/contention.py feeds this automatically)
  disk            pread/pwrite/sendfile in the volume engine
  device          execution-fenced EC kernel/device legs
                  (stats.metrics.observe_ec_stage feeds this)
  rpc_downstream  outbound RPC round-trips (rpc.call and friends)
  handler         the residual: handler execution time not claimed by
                  any other phase (mostly CPU + GIL wait)

The rpc middleware opens a ledger on the serving thread before the
handler runs and closes it at response time; instrumentation points
anywhere below (storage, EC, client pool, metered locks) accumulate
into whatever ledger is active on their thread — zero coordination,
and zero cost when no ledger is active (one thread-local read).

The closed ledger rides three surfaces:

- `SeaweedFS_request_phase_seconds{role,family,phase,q}` windowed
  quantile gauges (SloTracker machinery) on /metrics;
- the `phases` field of every /debug/slow exemplar, so a slow trace
  shows its time budget inline;
- a `phases` attribute on the request's server span in /debug/traces.

Because `handler` is computed as the residual of the dispatch wall,
the non-queue phases always sum to the observed request seconds — the
"budget sums to the wall" invariant tests and BENCH_load gate on.

Cost design — this runs on EVERY request, so the plane must price in
low single-digit microseconds (the BENCH_load_r02 <3% gate):

- one Ledger per serving THREAD, reused across its keep-alive
  requests (no per-request allocation); phases accumulate into a
  fixed 6-slot float list reset with one C-speed slice assignment;
- nothing materializes a dict on the fast path — `Ledger.to_dict()`
  runs only for the consumers that actually read the budget (a slow
  exemplar, a recorded trace span, the 1-in-K phase-sketch sample).

Kill switch: SEAWEEDFS_TPU_PHASES=0 disables ledger creation entirely
(instrumentation points then see no active ledger and pay only the
thread-local read).  Toggleable at runtime via `phases.ENABLED` /
POST /debug/attribution.
"""

from __future__ import annotations

import os
import threading
import time

# Canonical phase names in slot order.  `queue` happens before the
# ledger opens (the middleware measured it at the admission gate) and
# is seeded in; `handler` is the closing residual.
PHASES = ("queue", "lock", "handler", "disk", "device",
          "rpc_downstream")
_IDX = {name: i for i, name in enumerate(PHASES)}
_QUEUE, _HANDLER = _IDX["queue"], _IDX["handler"]
# Public slot indices for inline hot-path accounting
# (`ledger.arr[IDX_DISK] += dt` skips the name lookup note() does).
IDX_LOCK = _IDX["lock"]
IDX_DISK = _IDX["disk"]
IDX_DEVICE = _IDX["device"]
IDX_RPC = _IDX["rpc_downstream"]
_ZEROS = [0.0] * len(PHASES)

ENABLED = os.environ.get("SEAWEEDFS_TPU_PHASES", "") not in ("0",
                                                             "false")

_local = threading.local()


class Ledger:
    """One request's phase accumulator.  Not thread-safe by design: a
    ledger belongs to exactly one serving thread (fan-out work on
    worker threads is accounted as `rpc_downstream` at the dispatch
    site, the same boundary the trace spans draw), and the thread
    reuses its ledger across keep-alive requests — consumers that
    outlive the request take a to_dict() copy, never the ledger."""

    __slots__ = ("t0", "arr")

    def __init__(self):
        self.t0 = 0.0
        self.arr = list(_ZEROS)

    def note(self, phase: str, seconds: float) -> None:
        self.arr[_IDX[phase]] += seconds

    def finish(self) -> None:
        """Close the ledger: the dispatch wall not claimed by a named
        phase becomes `handler`, so sum(non-queue phases) == wall."""
        arr = self.arr
        elapsed = time.perf_counter() - self.t0
        inner = sum(arr) - arr[_QUEUE]
        arr[_HANDLER] = max(0.0, elapsed - inner)

    def to_dict(self) -> dict[str, float]:
        """Materialize the nonzero phases — only consumers call this
        (exemplars, recorded spans, sampled sketches)."""
        arr = self.arr
        return {name: arr[i] for i, name in enumerate(PHASES)
                if arr[i] > 0.0}


def start(queue_seconds: float = 0.0) -> Ledger | None:
    """Open (reset) this thread's ledger (rpc middleware).  Returns
    None when the plane is disabled; callers skip finish() then."""
    if not ENABLED:
        return None
    ledger = getattr(_local, "spare", None)
    if ledger is None:
        ledger = _local.spare = Ledger()
    arr = ledger.arr
    arr[:] = _ZEROS
    if queue_seconds > 0.0:
        arr[_QUEUE] = queue_seconds
    ledger.t0 = time.perf_counter()
    _local.ledger = ledger
    return ledger


def finish(ledger: Ledger) -> Ledger:
    """Close this thread's ledger (handler residual computed) and
    detach it.  Returns the ledger for lazy to_dict() consumption —
    valid until this thread's next start()."""
    _local.ledger = None
    ledger.finish()
    return ledger


def active() -> Ledger | None:
    return getattr(_local, "ledger", None)


def note(phase: str, seconds: float) -> None:
    """Accumulate into the active ledger, if any — the hook for
    instrumentation that already measured its own elapsed time
    (metered locks, EC stage timers)."""
    ledger = getattr(_local, "ledger", None)
    if ledger is not None:
        ledger.arr[_IDX[phase]] += seconds


class phase:
    """Context manager accounting its body into the active ledger:

        with phases.phase("disk"):
            os.pread(...)

    When no ledger is active (no request on this thread, or the plane
    is disabled) the cost is one thread-local read — no perf_counter
    calls, no arithmetic.

    Lock waits noted INSIDE the window (a contended MeteredLock under
    an rpc_downstream call, e.g. the client conn pool) are subtracted
    from this phase's elapsed: each second of request wall belongs to
    exactly one phase, or the budget would sum past the wall."""

    __slots__ = ("idx", "_ledger", "_t0", "_lock0")

    def __init__(self, name: str):
        self.idx = _IDX[name]

    def __enter__(self):
        self._ledger = getattr(_local, "ledger", None)
        if self._ledger is not None:
            self._lock0 = self._ledger.arr[IDX_LOCK]
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        ledger = self._ledger
        if ledger is not None:
            elapsed = time.perf_counter() - self._t0 - \
                (ledger.arr[IDX_LOCK] - self._lock0)
            if elapsed > 0.0:
                ledger.arr[self.idx] += elapsed
            self._ledger = None
        return False
