"""Pure-python Prometheus text-exposition validator (promtool-style).

`validate_exposition(text)` returns a list of problems (empty = clean).
Checks the invariants Prometheus' own parser enforces on a scrape:

- line grammar: `name{labels} value` with a legal metric name, legal
  label names, and correctly escaped label values (`\\`, `\"`, `\n`);
- HELP/TYPE comments: at most one each per family, emitted before any
  of the family's samples, with a known TYPE;
- family grouping: a family's samples are contiguous (interleaving two
  families is a parse error for Prometheus);
- histograms: every `<base>_bucket` series group (same labels minus
  `le`) has ascending `le` values, CUMULATIVE (non-decreasing) counts,
  and a `+Inf` bucket that matches `<base>_count` when present;
- values parse as floats (NaN/+Inf/-Inf allowed).

Used by the tier-1 tests against live scrapes of master/volume/filer,
and usable standalone against any registry's `expose()` output.
"""

from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _parse_value(tok: str) -> float | None:
    t = tok.strip()
    if t in ("+Inf", "Inf"):
        return math.inf
    if t == "-Inf":
        return -math.inf
    if t == "NaN":
        return math.nan
    try:
        return float(t)
    except ValueError:
        return None


def _parse_labels(s: str, lineno: int,
                  problems: list[str]) -> dict[str, str] | None:
    """Parse `k="v",k2="v2"` honoring the escape rules; None on error."""
    labels: dict[str, str] = {}
    i = 0
    n = len(s)
    while i < n:
        eq = s.find("=", i)
        if eq < 0:
            problems.append(f"line {lineno}: label without '=': {s[i:]!r}")
            return None
        name = s[i:eq].strip()
        if not _LABEL_NAME_RE.match(name):
            problems.append(f"line {lineno}: bad label name {name!r}")
            return None
        if eq + 1 >= n or s[eq + 1] != '"':
            problems.append(f"line {lineno}: unquoted value for {name}")
            return None
        j = eq + 2
        val = []
        while True:
            if j >= n:
                problems.append(
                    f"line {lineno}: unterminated value for {name}")
                return None
            c = s[j]
            if c == "\\":
                if j + 1 >= n or s[j + 1] not in ('\\', '"', 'n'):
                    problems.append(
                        f"line {lineno}: bad escape in value of {name}")
                    return None
                val.append("\n" if s[j + 1] == "n" else s[j + 1])
                j += 2
            elif c == '"':
                j += 1
                break
            else:
                val.append(c)
                j += 1
        labels[name] = "".join(val)
        if j < n:
            if s[j] != ",":
                problems.append(
                    f"line {lineno}: junk after value of {name}: "
                    f"{s[j:]!r}")
                return None
            j += 1
        i = j
    return labels


def _family_of(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def validate_exposition(text: str) -> list[str]:
    problems: list[str] = []
    helped: set[str] = set()
    typed: dict[str, str] = {}
    sampled: set[str] = set()      # families that emitted samples
    closed: set[str] = set()       # families whose sample run ended
    current_family: str | None = None
    # (family, labels-minus-le frozen) -> [(le, count, lineno)]
    buckets: dict[tuple, list[tuple[float, float, int]]] = {}
    counts: dict[tuple, float] = {}

    for lineno, line in enumerate(text.split("\n"), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                if parts[1:2] and parts[1] in ("HELP", "TYPE"):
                    problems.append(f"line {lineno}: truncated {parts[1]}")
                continue  # plain comment
            kind, fam = parts[1], parts[2]
            if fam in sampled:
                problems.append(
                    f"line {lineno}: {kind} for {fam} after its samples")
            if kind == "HELP":
                if fam in helped:
                    problems.append(f"line {lineno}: duplicate HELP {fam}")
                helped.add(fam)
            else:
                if fam in typed:
                    problems.append(f"line {lineno}: duplicate TYPE {fam}")
                if len(parts) < 4 or parts[3] not in (
                        "counter", "gauge", "histogram", "summary",
                        "untyped"):
                    problems.append(
                        f"line {lineno}: unknown TYPE for {fam}")
                typed[fam] = parts[3] if len(parts) > 3 else ""
            continue

        # sample line: name[{labels}] value [timestamp]
        if "{" in line:
            brace = line.index("{")
            name = line[:brace]
            close = line.rfind("}")
            if close < brace:
                problems.append(f"line {lineno}: unbalanced braces")
                continue
            labels = _parse_labels(line[brace + 1:close], lineno,
                                   problems)
            if labels is None:
                continue
            rest = line[close + 1:]
        else:
            toks = line.split(None, 1)
            name = toks[0]
            labels = {}
            rest = toks[1] if len(toks) > 1 else ""
        if not _NAME_RE.match(name):
            problems.append(f"line {lineno}: bad metric name {name!r}")
            continue
        toks = rest.split()
        if not toks:
            problems.append(f"line {lineno}: missing value for {name}")
            continue
        value = _parse_value(toks[0])
        if value is None:
            problems.append(
                f"line {lineno}: bad value {toks[0]!r} for {name}")
            continue

        fam = _family_of(name)
        if fam != current_family:
            if current_family is not None:
                closed.add(current_family)
            if fam in closed:
                problems.append(
                    f"line {lineno}: samples of {fam} interleaved with "
                    "another family")
            current_family = fam
        sampled.add(fam)

        if typed.get(fam) == "histogram":
            key = (fam, frozenset((k, v) for k, v in labels.items()
                                  if k != "le"))
            if name == fam + "_bucket":
                if "le" not in labels:
                    problems.append(
                        f"line {lineno}: {name} without le label")
                    continue
                le = _parse_value(labels["le"])
                if le is None:
                    problems.append(
                        f"line {lineno}: bad le {labels['le']!r}")
                    continue
                buckets.setdefault(key, []).append((le, value, lineno))
            elif name == fam + "_count":
                counts[key] = value

    for (fam, _lk), entries in buckets.items():
        les = [e[0] for e in entries]
        if les != sorted(les):
            problems.append(f"{fam}: le buckets not ascending")
        vals = [e[1] for e in entries]
        if any(b < a for a, b in zip(vals, vals[1:])):
            problems.append(f"{fam}: bucket counts not cumulative")
        if not les or not math.isinf(les[-1]):
            problems.append(f"{fam}: missing +Inf bucket")
        elif (fam, _lk) in counts and vals[-1] != counts[(fam, _lk)]:
            problems.append(
                f"{fam}: +Inf bucket {vals[-1]} != _count "
                f"{counts[(fam, _lk)]}")
    return problems
