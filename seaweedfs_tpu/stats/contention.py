"""Lock-contention metering: named instrumented locks + /debug/locks.

The phase ledger (stats/phases.py) attributes a slow request's time to
`lock` only if something measures the waits; this module is that
something.  A `MeteredLock` wraps a threading.Lock/RLock under a
bounded, operator-meaningful name ("volume.write", "integrity.ecc",
"admission.read", "rpc.pool") and records:

- `SeaweedFS_lock_wait_seconds{lock=}`  — histogram of CONTENDED
  acquire waits (the uncontended path never touches the histogram);
- `SeaweedFS_lock_hold_seconds{lock=}`  — histogram of hold times;
- the wait is also fed to the active request's phase ledger, so lock
  time shows up in /debug/slow exemplars without extra plumbing.

`/debug/locks` (setup_contention_routes) lists every registered lock
with its current holder and waiters — thread names AND stacks, pulled
lazily from sys._current_frames() at snapshot time, so the acquire
path never formats a stack.

Cost contract (asserted by tests/test_attribution.py, same stance as
the fault registry's disarmed guarantee):

- disarmed (ENABLED=False / SEAWEEDFS_TPU_LOCK_METER=0): one module-
  global truthiness check, then the raw lock — no timing, no dicts;
- armed + uncontended: a try-acquire, two attribute stores and one
  perf_counter read on acquire; one perf_counter read and a histogram
  observe on release.  No extra locks are taken on the acquire side.

Contended acquires (the case worth measuring) pay the histogram and
the waiter-table upkeep.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import weakref

from . import phases as _phases
from .metrics import Histogram

ENABLED = os.environ.get("SEAWEEDFS_TPU_LOCK_METER", "") not in (
    "0", "false")

# Wait buckets skew low: a 100µs convoy on a per-request lock is
# already interesting; holds reuse the same shape.
_LOCK_BUCKETS = (0.00001, 0.00005, 0.0001, 0.00025, 0.0005, 0.001,
                 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 10.0)

lock_wait_seconds = Histogram(
    "SeaweedFS_lock_wait_seconds",
    "contended lock acquire wait time by lock name", ("lock",),
    buckets=_LOCK_BUCKETS)

lock_hold_seconds = Histogram(
    "SeaweedFS_lock_hold_seconds",
    "lock hold time by lock name", ("lock",),
    buckets=_LOCK_BUCKETS)

# Every live MeteredLock, for the /debug/locks snapshot.  WeakSet so
# short-lived locks (per-volume ecc locks of deleted volumes) don't
# accumulate forever.  Registration and snapshot iteration serialize
# on _REGISTRY_LOCK: a /debug/locks walk racing a fresh lock's
# construction would otherwise RuntimeError mid-iteration.
_REGISTRY: "weakref.WeakSet[MeteredLock]" = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


def _registered() -> "list[MeteredLock]":
    with _REGISTRY_LOCK:
        return list(_REGISTRY)


class MeteredLock:
    """A named lock with wait/hold metering.  Wraps threading.Lock by
    default; pass lock=threading.RLock() for reentrant use — nested
    acquires by the holder are counted by depth and the hold is
    measured outermost-acquire to outermost-release.

    hold_observe_min: holds shorter than this skip the hold histogram
    (they still update the live holder view and the acquire counter).
    Per-request locks guarding two counter increments (admission
    lanes, the client pool) set it to 1ms: their nanosecond holds are
    histogram noise that would cost more to record than they teach,
    while a pathological hold (someone sleeping under the lane lock)
    still lands."""

    __slots__ = ("name", "_lock", "_holder", "_depth", "_since",
                 "_waiters", "contended", "acquired",
                 "hold_observe_min", "_wait_series", "_hold_series",
                 "__weakref__")

    def __init__(self, name: str, lock=None,
                 hold_observe_min: float = 0.0):
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()
        self._holder = 0          # thread ident, 0 = unheld
        self._depth = 0           # reentrancy depth (RLock inner)
        self._since = 0.0         # perf_counter at outermost acquire
        # ident -> wall-clock wait start; plain dict mutated only by
        # the waiting thread itself (GIL-serialized item ops).
        self._waiters: dict[int, float] = {}
        self.contended = 0        # lifetime contended-acquire count
        self.acquired = 0         # lifetime acquire count (armed only)
        self.hold_observe_min = hold_observe_min
        # Pre-resolved series handles: label work happens once, not
        # per observe — the armed-uncontended release path must stay
        # microseconds (asserted by test).
        self._wait_series = lock_wait_seconds.series(lock=name)
        self._hold_series = lock_hold_seconds.series(lock=name)
        with _REGISTRY_LOCK:
            _REGISTRY.add(self)

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if not ENABLED:
            return self._lock.acquire(blocking, timeout)
        me = threading.get_ident()
        if self._holder == me:
            # Reentrant fast path (RLock inner): never contended.
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._depth += 1
                self.acquired += 1
            return ok
        if self._lock.acquire(False):
            self._holder = me
            self._depth = 1
            self._since = time.perf_counter()
            self.acquired += 1
            return True
        if not blocking:
            return False
        self._waiters[me] = time.time()
        t0 = time.perf_counter()
        try:
            ok = self._lock.acquire(True, timeout)
        finally:
            self._waiters.pop(me, None)
        wait = time.perf_counter() - t0
        self.contended += 1
        self._wait_series.observe(wait)
        _phases.note("lock", wait)
        if ok:
            self._holder = me
            self._depth = 1
            self._since = time.perf_counter()
            self.acquired += 1
        return ok

    def release(self) -> None:
        if not ENABLED:
            # Disarmed fast path — but if metering was disarmed
            # MID-HOLD (the runtime /debug/attribution toggle), the
            # armed acquire's bookkeeping must still settle: a stale
            # _holder would turn this thread's next acquire into a
            # phantom reentrant path and show a forever-held lock on
            # /debug/locks.  _holder is 0 in the common case, so this
            # stays one attr truthiness check.
            if self._holder and \
                    self._holder == threading.get_ident():
                self._depth -= 1
                if self._depth <= 0:
                    self._holder = 0
            self._lock.release()
            return
        if self._holder != threading.get_ident():
            # The acquire happened while disarmed: raw release.
            self._lock.release()
            return
        self._depth -= 1
        if self._depth > 0:
            self._lock.release()
            return
        hold = time.perf_counter() - self._since
        self._holder = 0
        self._lock.release()
        if hold >= self.hold_observe_min:
            self._hold_series.observe(hold)

    # `with lock:` binds __enter__ directly to acquire (the bool
    # return is fine — `with` discards it): one Python call saved on
    # the hottest path in the module.
    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._lock, "locked", None)
        if locked is not None:
            return locked()
        # RLock has no locked(); the holder field is our view.
        return self._holder != 0

    # -- introspection -------------------------------------------------------

    def snapshot(self, frames=None,
                 threads=None) -> dict | None:
        """State for /debug/locks; None when idle (unheld, no
        waiters) so the surface lists only what matters."""
        holder, since = self._holder, self._since
        waiters = dict(self._waiters)
        if not holder and not waiters:
            return None
        out: dict = {"lock": self.name, "contended": self.contended}
        if holder:
            out["holder"] = _thread_view(holder, frames, threads)
            out["held_seconds"] = round(
                time.perf_counter() - since, 6)
        now = time.time()
        out["waiters"] = [
            dict(_thread_view(ident, frames, threads),
                 waiting_seconds=round(now - t0, 6))
            for ident, t0 in waiters.items()]
        return out


def _thread_view(ident: int, frames, threads) -> dict:
    out: dict = {"thread_id": ident}
    if threads is not None:
        th = threads.get(ident)
        if th is not None:
            out["thread"] = th.name
    if frames is not None:
        frame = frames.get(ident)
        if frame is not None:
            out["stack"] = [
                line.rstrip("\n")
                for line in traceback.format_stack(frame)[-12:]]
    return out


def wrap_rwlock_write(rwlock, name: str) -> None:
    """Arm an utils.rwlock.RWLock's write side with wait/hold metering
    under `name` (the volume engine's dataFileAccessLock analog).  The
    read side stays unmetered — readers are the uncontended common
    case and must never pay a histogram."""
    rwlock._meter_name = name


def snapshot_all() -> list[dict]:
    """Current holders/waiters across every registered lock — the
    /debug/locks payload.  Stacks are resolved here, once per
    snapshot, never on the acquire path."""
    frames = sys._current_frames()
    threads = {th.ident: th for th in threading.enumerate()}
    out = []
    for lk in _registered():
        try:
            snap = lk.snapshot(frames, threads)
        except Exception:  # noqa: BLE001 — a racing release mid-walk
            continue
        if snap is not None:
            out.append(snap)
    out.sort(key=lambda d: d["lock"])
    return out


def totals() -> list[dict]:
    """Lifetime acquire/contended counters per lock name (merged
    across instances sharing a name, e.g. per-volume ecc locks)."""
    agg: dict[str, list[int]] = {}
    for lk in _registered():
        row = agg.setdefault(lk.name, [0, 0])
        row[0] += lk.acquired
        row[1] += lk.contended
    return [{"lock": name, "acquired": a, "contended": c}
            for name, (a, c) in sorted(agg.items())]


# -- routes ------------------------------------------------------------------

def set_plane_enabled(on: bool, feature: str = "") -> None:
    """Arm/disarm the time-attribution plane at runtime — all of it,
    or one feature ("locks" | "phases" | "profiler") for overhead
    bisection.  The profiler is paused, not destroyed — its ring
    survives a disarm.  The per-request instrumentation points read
    these flags dynamically, so the flip is immediate and
    restart-free."""
    global ENABLED
    from ..utils import pprof
    from . import phases as _ph
    if feature in ("", "locks"):
        ENABLED = on
    if feature in ("", "phases"):
        _ph.ENABLED = on
    if feature in ("", "profiler"):
        prof = pprof.PROFILER
        if prof is not None:
            prof.start() if on else prof.stop()


def setup_contention_routes(server) -> None:
    """Mount GET /debug/locks: live holders/waiters with stacks plus
    lifetime per-lock counters.  Mounted unconditionally on the
    cluster roles beside /debug/slow — the surface is read-only and
    cheap (stacks resolve per request, not per acquire).

    Also mounts POST /debug/attribution?enabled=0|1 — the restart-free
    kill switch for the whole plane (lock metering + phase ledger +
    continuous profiler).  Operationally: disarm to rule the plane out
    while chasing a regression; it is also how BENCH_load_r02 prices
    the plane A/B on ONE cluster instance, immune to instance-level
    variance (allocator layout, ASLR) that dwarfs a 2% effect."""

    def _locks(query: dict, body: bytes):
        return {"metering": ENABLED,
                "active": snapshot_all(),
                "locks": totals()}

    def _toggle(query: dict, body: bytes):
        on = query.get("enabled", "1") not in ("0", "false")
        feature = query.get("feature", "")
        if feature not in ("", "locks", "phases", "profiler"):
            return (400, {"error": f"unknown feature {feature!r}"})
        set_plane_enabled(on, feature)
        from . import phases as _ph
        from ..utils import pprof
        return {"enabled": on,
                "lock_meter": ENABLED,
                "phases": _ph.ENABLED,
                "profiler_running": bool(pprof.PROFILER is not None
                                         and pprof.PROFILER.running)}

    server.route("GET", "/debug/locks", _locks)
    server.route("POST", "/debug/attribution", _toggle)
