"""Metrics & system stats (reference: weed/stats/).

metrics.py is a from-scratch Prometheus client (counters, gauges,
histograms, text exposition, push-gateway loop — stats/metrics.go);
sysstats.py reads disk/memory figures (stats/disk.go, memory.go).
"""

from .hotkeys import HotKeyTracker, SpaceSaving  # noqa: F401
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsPusher, Registry, ec_stage_bytes,
                      ec_stage_seconds, global_registry,
                      observe_ec_stage)
from .promcheck import validate_exposition  # noqa: F401
from .sketch import QuantileSketch, WindowedSketch  # noqa: F401
from .sysstats import disk_status, memory_status  # noqa: F401

# stats.slo is NOT imported here: it imports the event journal, which
# imports stats.metrics — importing it at package-init time would
# cycle.  Import it as seaweedfs_tpu.stats.slo directly.
