"""Metrics & system stats (reference: weed/stats/).

metrics.py is a from-scratch Prometheus client (counters, gauges,
histograms, text exposition, push-gateway loop — stats/metrics.go);
sysstats.py reads disk/memory figures (stats/disk.go, memory.go).
"""

from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsPusher, Registry, ec_stage_bytes,
                      ec_stage_seconds, global_registry,
                      observe_ec_stage)
from .promcheck import validate_exposition  # noqa: F401
from .sysstats import disk_status, memory_status  # noqa: F401
