"""Quantile sketches: log-bucketed (DDSketch-style) estimators with a
sliding window, mergeable across processes.

The cumulative request histograms (stats/metrics.py) answer "how many
requests were ever slower than X" — useless for live tail latency: a
p99 over a process's whole lifetime is dominated by history, and fixed
bucket edges quantize the answer.  This module is the measured-tails
half of the SLO plane (stats/slo.py):

- `QuantileSketch`: log-spaced buckets with ratio gamma = (1+a)/(1-a).
  A value x lands in bucket i = ceil(log_gamma(x/min_value)); the
  bucket's representative value 2*gamma^i/(gamma+1)*min_value is
  within RELATIVE ERROR `alpha` of every value in the bucket.  So the
  DOCUMENTED ACCURACY BOUND is: for any rank r, the reported
  r-quantile q' and the true r-quantile q satisfy |q' - q| <= alpha*q
  (values below `min_value` collapse to one zero-bucket reported as
  `min_value`; sub-microsecond request latencies do not exist on this
  stack).  Tests (tests/test_slo.py) assert this bound against
  numpy.percentile on adversarial (bimodal, heavy-tailed)
  distributions.
- Buckets are a sparse dict, so memory is O(distinct buckets) — about
  ~700 possible buckets across 1us..1000s at the default alpha=0.01,
  a few dozen occupied in practice.
- Merging two sketches with the same (alpha, min_value) is exact bucket
  addition: the merged sketch is IDENTICAL to the sketch of the
  concatenated streams, which is what lets per-process sketches ride a
  heartbeat and aggregate into one cluster-wide quantile on
  /cluster/healthz.
- `WindowedSketch` slices time into `slices` ring segments of
  window/slices seconds each and drops whole segments as they expire:
  quantiles cover at least `window - window/slices` and at most
  `window` seconds of history.  The clock is injected so tests advance
  windows deterministically — no sleeps in the tier-1 suite.
"""

from __future__ import annotations

import math
import threading
import time

DEFAULT_ALPHA = 0.01
DEFAULT_MIN_VALUE = 1e-6


class QuantileSketch:
    """Mergeable log-bucketed quantile estimator (relative error
    `alpha` on the value at any rank — see module docstring)."""

    __slots__ = ("alpha", "min_value", "_gamma", "_log_gamma",
                 "count", "sum", "_buckets", "_zero")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 min_value: float = DEFAULT_MIN_VALUE):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha {alpha} not in (0, 1)")
        self.alpha = alpha
        self.min_value = min_value
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self._gamma)
        self.count = 0
        self.sum = 0.0
        self._buckets: dict[int, int] = {}
        self._zero = 0  # observations <= min_value

    def observe(self, value: float, n: int = 1) -> None:
        self.count += n
        self.sum += value * n
        if value <= self.min_value:
            self._zero += n
            return
        i = math.ceil(math.log(value / self.min_value) / self._log_gamma)
        self._buckets[i] = self._buckets.get(i, 0) + n

    def _value_of(self, index: int) -> float:
        # Midpoint estimate: within alpha of every value in
        # (gamma^(i-1), gamma^i] * min_value.
        return (2.0 * self._gamma ** index / (self._gamma + 1.0)
                * self.min_value)

    def quantile(self, q: float) -> float | None:
        """The q-quantile (0 <= q <= 1) of the observed stream, or None
        when empty.  Nearest-rank: rank = ceil(q * count)."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(q * self.count))
        seen = self._zero
        if rank <= seen:
            return self.min_value
        for i in sorted(self._buckets):
            seen += self._buckets[i]
            if rank <= seen:
                return self._value_of(i)
        return self._value_of(max(self._buckets))  # float-rounding tail

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Add `other`'s buckets into this sketch (exact: equals the
        sketch of the concatenated streams).  Parameter mismatch raises
        — merging across different gammas would silently mis-bucket."""
        if (other.alpha, other.min_value) != (self.alpha, self.min_value):
            raise ValueError(
                f"cannot merge sketches with different parameters: "
                f"({self.alpha}, {self.min_value}) vs "
                f"({other.alpha}, {other.min_value})")
        self.count += other.count
        self.sum += other.sum
        self._zero += other._zero
        for i, n in other._buckets.items():
            self._buckets[i] = self._buckets.get(i, 0) + n
        return self

    # -- wire format (heartbeats, /debug/slo) --------------------------------

    def to_dict(self) -> dict:
        return {"alpha": self.alpha, "min_value": self.min_value,
                "count": self.count, "sum": round(self.sum, 9),
                "zero": self._zero,
                "buckets": {str(i): n
                            for i, n in sorted(self._buckets.items())}}

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        sk = cls(alpha=float(d.get("alpha", DEFAULT_ALPHA)),
                 min_value=float(d.get("min_value", DEFAULT_MIN_VALUE)))
        sk.count = int(d.get("count", 0))
        sk.sum = float(d.get("sum", 0.0))
        sk._zero = int(d.get("zero", 0))
        sk._buckets = {int(i): int(n)
                       for i, n in d.get("buckets", {}).items()}
        return sk


class WindowedSketch:
    """Sliding-window QuantileSketch: a ring of `slices` sub-sketches,
    each covering window/slices seconds; expired slices are dropped
    whole.  Bounded memory, thread-safe, injected clock (tests advance
    time without sleeping)."""

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 min_value: float = DEFAULT_MIN_VALUE,
                 window: float = 300.0, slices: int = 6,
                 clock=time.monotonic):
        if slices < 1:
            raise ValueError("slices must be >= 1")
        self.alpha = alpha
        self.min_value = min_value
        self.window = window
        self.slices = slices
        self.slice_seconds = window / slices
        self.clock = clock
        self._lock = threading.Lock()
        # ring[i] = (slice_epoch, sketch); slice_epoch identifies which
        # wall slice the entry belongs to, so expiry is a comparison,
        # not a scan of timestamps.
        self._ring: list[tuple[int, QuantileSketch] | None] = \
            [None] * slices

    def _epoch(self, now: float) -> int:
        return int(now // self.slice_seconds)

    def _current_locked(self, now: float) -> QuantileSketch:
        epoch = self._epoch(now)
        idx = epoch % self.slices
        slot = self._ring[idx]
        if slot is None or slot[0] != epoch:
            sk = QuantileSketch(self.alpha, self.min_value)
            self._ring[idx] = (epoch, sk)
            return sk
        return slot[1]

    def observe(self, value: float) -> None:
        now = self.clock()
        with self._lock:
            self._current_locked(now).observe(value)

    def merged(self) -> QuantileSketch:
        """One sketch over every live (non-expired) slice."""
        now = self.clock()
        newest = self._epoch(now)
        out = QuantileSketch(self.alpha, self.min_value)
        with self._lock:
            for slot in self._ring:
                if slot is not None and newest - slot[0] < self.slices:
                    out.merge(slot[1])
        return out

    def quantile(self, q: float) -> float | None:
        return self.merged().quantile(q)

    def count(self) -> int:
        return self.merged().count

    def to_dict(self) -> dict:
        return self.merged().to_dict()
