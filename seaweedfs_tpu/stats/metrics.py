"""Prometheus-style metrics: counters, gauges, histograms + exposition.

Reference: weed/stats/metrics.go:21-118 — request counters and latency
histograms for filer/volume/S3, volume-count and disk-size gauges, and
LoopPushingMetric (:140) which POSTs to a push gateway whose address is
distributed from master configuration.  No prometheus_client package in
the image, so the exposition format is emitted directly.
"""

from __future__ import annotations

import bisect
import threading
import time
import urllib.request
from typing import Callable

DEFAULT_BUCKETS = (0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                   0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt_num(v: float) -> str:
    """Full-precision exposition: %g would truncate counters past 1e6
    (a stuck-looking counter) and byte gauges past ~6 digits."""
    f = float(v)
    if f.is_integer() and abs(f) < 2**63:
        return str(int(f))
    return repr(f)


def _esc_label_value(v) -> str:
    # Prometheus exposition requires all three escapes: backslash first
    # (or the others' escapes would be double-escaped), then quote, then
    # newline — an unescaped \n in a label value splits the sample line
    # and corrupts the whole line-oriented scrape.
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    kind = ""

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple:
        # Hot path (every request observes): build the key directly and
        # let a KeyError/length mismatch fall into the slow error path
        # instead of constructing two sets per call.
        try:
            if len(labels) == len(self.label_names):
                return tuple(str(labels[k]) for k in self.label_names)
        except KeyError:
            pass
        raise ValueError(
            f"{self.name}: labels {sorted(labels)} != declared "
            f"{sorted(self.label_names)}")

    def _labels_of(self, key: tuple) -> dict[str, str]:
        return dict(zip(self.label_names, key))


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(f"{self.name}"
                       f"{_fmt_labels(self._labels_of(key))} "
                       f"{_fmt_num(v)}")
        return out


class Gauge(_Metric):
    """A settable value, or a callback sampled at scrape time (the
    reference computes volume counts/disk sizes on collect)."""

    kind = "gauge"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = (),
                 callback: Callable[[], float | dict] | None = None):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}
        self.callback = callback

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        if self.callback is not None:
            sampled = self.callback()
            if isinstance(sampled, dict):
                # {labels-tuple-or-dict: value}
                for labels, v in sorted(
                        sampled.items(), key=lambda kv: str(kv[0])):
                    if isinstance(labels, tuple):
                        labels = dict(zip(self.label_names, labels))
                    out.append(f"{self.name}{_fmt_labels(labels)} "
                               f"{_fmt_num(v)}")
            else:
                out.append(f"{self.name} {_fmt_num(sampled)}")
            return out
        with self._lock:
            items = sorted(self._values.items())
        if not items and not self.label_names:
            items = [((), 0.0)]
        for key, v in items:
            out.append(f"{self.name}"
                       f"{_fmt_labels(self._labels_of(key))} "
                       f"{_fmt_num(v)}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        # Per-bucket (non-cumulative) counts + bisect: one increment
        # per observation instead of a 15-bucket scan — this runs on
        # every request.  expose() converts to Prometheus cumulative.
        k = self._key(labels)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.buckets) + 1))
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def count(self, **labels) -> int:
        """Observation count of one series (0 when never observed)."""
        with self._lock:
            return self._totals.get(self._key(labels), 0)

    def series(self, **labels):
        """Pre-resolved single-series observe handle for hot paths
        (per-request lock metering): label validation, key building,
        and slot allocation happen ONCE here; each observe() is then
        a bisect + one locked list/float update — ~3x cheaper than
        the labeled observe().  Exposition reads the same storage."""
        k = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.buckets) + 1))
            self._sums.setdefault(k, 0.0)
            self._totals.setdefault(k, 0)
        hist = self

        class _Series:
            __slots__ = ()

            @staticmethod
            def observe(value: float) -> None:
                i = bisect.bisect_left(hist.buckets, value)
                with hist._lock:
                    counts[i] += 1
                    hist._sums[k] += value
                    hist._totals[k] += 1
        return _Series()

    def time(self, **labels):
        """Context manager: observe elapsed seconds."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self  # nestable with other context managers

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, **labels)
        return _Timer()

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = sorted(self._counts)
            counts = {k: list(v) for k, v in self._counts.items()}
            sums = dict(self._sums)
            totals = dict(self._totals)
        for key in keys:
            labels = self._labels_of(key)
            running = 0
            for i, b in enumerate(self.buckets):
                running += counts[key][i]
                lb = dict(labels)
                lb["le"] = f"{b:g}"
                out.append(f"{self.name}_bucket{_fmt_labels(lb)} "
                           f"{running}")
            lb = dict(labels)
            lb["le"] = "+Inf"
            out.append(f"{self.name}_bucket{_fmt_labels(lb)} "
                       f"{totals[key]}")
            out.append(f"{self.name}_sum{_fmt_labels(labels)} "
                       f"{_fmt_num(sums[key])}")
            out.append(f"{self.name}_count{_fmt_labels(labels)} "
                       f"{totals[key]}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric):
        with self._lock:
            self._metrics.append(metric)
        return metric

    def register_once(self, metric: _Metric):
        """register() for shared singletons: a second registration into
        the same registry is a no-op instead of a duplicate exposition
        block (which would fail promcheck)."""
        with self._lock:
            if metric not in self._metrics:
                self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_: str,
                label_names: tuple[str, ...] = ()) -> Counter:
        return self.register(Counter(name, help_, label_names))

    def gauge(self, name: str, help_: str,
              label_names: tuple[str, ...] = (),
              callback=None) -> Gauge:
        return self.register(Gauge(name, help_, label_names, callback))

    def histogram(self, name: str, help_: str,
                  label_names: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self.register(Histogram(name, help_, label_names,
                                       buckets))

    def expose(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            try:
                lines.extend(m.expose())
            except Exception:  # noqa: BLE001 — one broken callback
                continue       # must not kill the whole scrape
        return "\n".join(lines) + "\n"


global_registry = Registry()


# -- EC pipeline stage instruments ------------------------------------------
# Process-global singletons every EC code path observes into — the
# Pallas coder's execution-fenced kernel timings (ops/coder_pallas.py),
# the batched mesh encode's fetch/device/scatter stages
# (parallel/cluster_encode.py), and the volume server's distributed
# reconstruction ladder (shard gather, device solve, host staging).
# Servers register the SAME objects into their scrape registry
# (Registry.register accepts an existing metric), so kernel time, host
# staging, and network fan-out are separately visible on /metrics
# wherever EC work runs.  Buckets extend past the request-latency
# defaults: a batched multi-volume encode legitimately takes minutes.

EC_STAGE_BUCKETS = DEFAULT_BUCKETS + (30.0, 60.0, 120.0)

ec_stage_seconds = Histogram(
    "SeaweedFS_ec_stage_seconds",
    "EC pipeline stage wall time (device stages are execution-fenced)",
    ("stage",), buckets=EC_STAGE_BUCKETS)

ec_stage_bytes = Counter(
    "SeaweedFS_ec_stage_bytes_total",
    "bytes processed per EC pipeline stage", ("stage",))


def observe_ec_stage(stage: str, seconds: float, nbytes: int = 0) -> None:
    ec_stage_seconds.observe(seconds, stage=stage)
    # Unconditional: a zero-byte observation must still materialize the
    # stage's series (rate() over a family that only appears under load
    # reads as a counter reset, and per-stage byte totals silently
    # under-count stages whose first calls carry nbytes=0).
    ec_stage_bytes.inc(nbytes, stage=stage)
    # Time-attribution: execution-fenced device legs observed while a
    # request ledger is active (a degraded read's EC reconstruction,
    # an inline repair's decode) land in that request's `device`
    # phase; host staging / fan-out stages stay in `handler`/`rpc`.
    if "kernel" in stage or "device" in stage:
        from . import phases as _phases
        _phases.note("device", seconds)


# -- data-integrity instruments ---------------------------------------------
# Process-global singletons the scrub/self-healing pipeline observes
# into (storage/scrub.py, cluster/volume_server.py); the volume server
# registers the same objects on its /metrics scrape.

scrub_checked_total = Counter(
    "SeaweedFS_scrub_checked_total",
    "items CRC-verified by the scrubber", ("kind",))  # needle|shard_block

scrub_bytes_total = Counter(
    "SeaweedFS_scrub_bytes_total",
    "bytes read and CRC-verified by the scrubber")

scrub_corrupt_total = Counter(
    "SeaweedFS_scrub_corrupt_total",
    "corruptions detected by the scrubber", ("kind",))

scrub_sweeps_total = Counter(
    "SeaweedFS_scrub_sweeps_total", "completed scrub sweeps")

needle_repairs_total = Counter(
    "SeaweedFS_needle_repairs_total",
    "self-healing repairs by source", ("source",))  # replica|ec

# Repair bandwidth, the dominant EC operating cost at scale (arxiv
# 1309.0186): every shard byte read to rebuild/reconstruct EC data,
# labeled by codec so the LRC-vs-RS saving is a PromQL ratio.  Fed by
# the local rebuild (ec/encoder.py), the volume server's degraded-read
# / repair ladder, and the cluster batch-rebuild planner.
ec_repair_read_bytes_total = Counter(
    "SeaweedFS_ec_repair_read_bytes_total",
    "shard bytes read to repair or reconstruct EC data", ("codec",))


# -- cross-cluster replication instruments -----------------------------------
# Process-global singletons the rlog shipper observes into
# (replication/shipper.py); the volume server registers the same
# objects on its /metrics scrape (promcheck-gated in tests).

replication_shipped_bytes_total = Counter(
    "SeaweedFS_replication_shipped_bytes_total",
    "change-log payload bytes acked by the standby cluster")

replication_resends_total = Counter(
    "SeaweedFS_replication_resends_total",
    "replication batches re-sent (WAN retries + injected duplicate "
    "delivery) — every resend is a no-op at the receiver's watermark",
    ("reason",))  # retry|duplicate

replication_lag_seconds_total = Counter(
    "SeaweedFS_replication_lag_seconds_total",
    "observed replication lag integrated over shipper ticks (a "
    "burn-style counter: its rate IS the average lag in seconds)")

replication_lag_seconds = Gauge(
    "SeaweedFS_replication_lag_seconds",
    "age of the oldest unacked change-log record, per volume",
    ("volume",))


# -- tiering / lifecycle instruments -----------------------------------------
# Process-global singletons the tier plane observes into: the shared
# remote block cache (storage/remote_cache.py, served-byte accounting
# at pread granularity), the tier movers (storage/tier.py), vacuum's
# TTL reclaim, and the master's lifecycle daemon.  The volume server
# and master register the same objects on their /metrics scrape.

tier_cache_hit_bytes_total = Counter(
    "SeaweedFS_tier_cache_hit_bytes_total",
    "tiered-read bytes served from the remote block cache")

tier_cache_miss_bytes_total = Counter(
    "SeaweedFS_tier_cache_miss_bytes_total",
    "tiered-read bytes that had to be fetched from the remote backend")

tier_moved_bytes_total = Counter(
    "SeaweedFS_tier_moved_bytes_total",
    "volume .dat bytes moved across the tier boundary",
    ("direction",))  # upload|download

ttl_expired_bytes_total = Counter(
    "SeaweedFS_ttl_expired_bytes_total",
    "bytes reclaimed from TTL-expired needles",
    ("via",))  # vacuum|volume_retire

lifecycle_actions_total = Counter(
    "SeaweedFS_lifecycle_actions_total",
    "lifecycle daemon actions by kind and outcome",
    ("action", "outcome"))  # tier|expire|promote x ok|error

# -- front door (netcore / filer hot-path) instruments ----------------------
# The filer chunk cache (storage/chunk_cache.py) and small-file packer
# (filer/packing.py); connection-plane counters live in
# netcore/registry.py beside the registry that feeds them.

filer_chunk_cache_hit_bytes_total = Counter(
    "SeaweedFS_filer_chunk_cache_hit_bytes_total",
    "filer chunk-read bytes served from the process chunk cache")

filer_chunk_cache_miss_bytes_total = Counter(
    "SeaweedFS_filer_chunk_cache_miss_bytes_total",
    "filer chunk-read bytes fetched from volume servers")

filer_packed_files_total = Counter(
    "SeaweedFS_filer_packed_files_total",
    "small files packed into shared needles on filer upload")

filer_packed_needles_total = Counter(
    "SeaweedFS_filer_packed_needles_total",
    "shared pack needles written (files-per-needle = files/needles)")

filer_packed_bytes_total = Counter(
    "SeaweedFS_filer_packed_bytes_total",
    "payload bytes stored via the small-file packer")

# Metadata-HA shard plane (filer/metaha.py): journal appends on the
# shard primary, replicated applies on followers, and epoch-fence
# refusals (the metadata split-brain guard).

filer_shard_journal_records_total = Counter(
    "SeaweedFS_filer_shard_journal_records_total",
    "metadata mutations framed into a shard .mlog on the primary",
    ("shard",))

filer_shard_apply_total = Counter(
    "SeaweedFS_filer_shard_apply_total",
    "replicated shard records applied on followers, by result "
    "(applied / duplicate)",
    ("shard", "result"))

filer_shard_fences_total = Counter(
    "SeaweedFS_filer_shard_fences_total",
    "stale-epoch shard operations refused (the metadata split-brain "
    "fence)",
    ("shard",))


def observe_batch_stage(stages: dict, stage: str, seconds: float,
                        nbytes: int) -> None:
    """observe_ec_stage plus a per-batch accumulator: the batched EC
    encode/rebuild report per-stage totals on their finish events
    (events/journal.py), not just in the process histograms.  `stages`
    maps stage -> [seconds, bytes]."""
    observe_ec_stage(stage, seconds, nbytes)
    acc = stages.setdefault(stage, [0.0, 0])
    acc[0] += seconds
    acc[1] += nbytes


def stage_attrs(stages: dict) -> dict:
    """Flatten an observe_batch_stage accumulator into event attrs:
    {<stage>_seconds, <stage>_bytes}."""
    out = {}
    for stage, (seconds, nbytes) in stages.items():
        out[f"{stage}_seconds"] = round(seconds, 6)
        out[f"{stage}_bytes"] = int(nbytes)
    return out


class MetricsPusher:
    """LoopPushingMetric (stats/metrics.go:140): periodically POST the
    exposition text to a push gateway."""

    def __init__(self, registry: Registry, gateway_url: str, job: str,
                 instance: str, interval_seconds: float = 15.0):
        self.registry = registry
        self.url = (f"{gateway_url.rstrip('/')}/metrics/job/{job}"
                    f"/instance/{instance}")
        self.interval = interval_seconds
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metrics-push")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        """Stop the loop, wait for any in-flight push (bounded — the
        push itself has a 10s timeout), then flush one final exposition
        so a short-lived process doesn't lose its last interval."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=15.0)
        try:
            self.push_once()
        except Exception:  # noqa: BLE001 — gateway down; best effort
            pass

    def push_once(self) -> None:
        body = self.registry.expose().encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "text/plain"})
        urllib.request.urlopen(req, timeout=10).read()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.push_once()
            except Exception:  # noqa: BLE001 — gateway down; retry
                pass
