"""Heavy-hitter tracking: space-saving top-k sketches for hot volumes,
hot needles, and hot client IPs on the volume-server data path.

At scale the operationally decisive read-path signal is SKEW — which
volumes and needles are taking disproportionate traffic (the hot-volume
/ degraded-read-storm findings of arXiv:1309.0186) — because that is
what decides where a cache or a small-file pack pays off (ROADMAP 3).
Counting every key exactly is unbounded; the space-saving algorithm
(Metwally et al.) keeps a fixed table of `capacity` counters:

- a tracked key increments its counter;
- an untracked key evicts the MINIMUM counter m and enters with
  count = m + 1, error = m.

Guarantees (asserted in tests/test_slo.py):

- EXACT when distinct keys <= capacity (error = 0 for every entry);
- otherwise every reported count overestimates its key's true count by
  at most its `error` field, and error <= min-counter <= N/capacity
  for N total offers — so under a skewed (Zipf) workload the true
  heavy hitters are always present and their counts are tight.

`HotKeyTracker` bundles the six sketches the volume server feeds
(volume/needle/client x read/write) behind one lock-free-read snapshot
for `/debug/hot` and the shell's `cluster.hot`.
"""

from __future__ import annotations

import threading
import time


class SpaceSaving:
    """Fixed-size heavy-hitter counter table (space-saving)."""

    __slots__ = ("capacity", "total", "_counts", "_lock")

    def __init__(self, capacity: int = 128):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.total = 0
        # key -> [count, error]
        self._counts: dict[object, list[int]] = {}
        self._lock = threading.Lock()

    def offer(self, key, inc: int = 1) -> None:
        with self._lock:
            self.total += inc
            ent = self._counts.get(key)
            if ent is not None:
                ent[0] += inc
                return
            if len(self._counts) < self.capacity:
                self._counts[key] = [inc, 0]
                return
            # Evict the minimum counter; the newcomer inherits its
            # count as upper-bound error.  O(capacity) scan — at the
            # default 128 entries this is microseconds against a
            # ~100us request, and only paid once the table is full.
            victim = min(self._counts, key=lambda k: self._counts[k][0])
            m = self._counts.pop(victim)[0]
            self._counts[key] = [m + inc, m]

    def top(self, k: int = 16) -> list[dict]:
        """Top-k entries, largest first: {key, count, error}.  `count`
        overestimates the true count by at most `error`."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: kv[1][0], reverse=True)[:k]
            return [{"key": key, "count": c, "error": e}
                    for key, (c, e) in items]

    def count(self, key) -> tuple[int, int]:
        """(count, error) for one key; (0, 0) when untracked."""
        with self._lock:
            ent = self._counts.get(key)
            return (ent[0], ent[1]) if ent is not None else (0, 0)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self.total = 0


# The dimensions the volume server tracks, and the two op classes.
# `client` is the ORIGINATING client (the filer forwards it on the
# proxy leg via X-Weed-Client, so /debug/hot names the real caller,
# not the filer's own IP); `tenant` is the resolved principal.
DIMENSIONS = ("volume", "needle", "client", "tenant")
OPS = ("read", "write")


class HotKeyTracker:
    """volume/needle/client/tenant x read/write space-saving sketches
    for one volume server; `snapshot()` is the /debug/hot payload."""

    def __init__(self, capacity: int = 128):
        self.capacity = capacity
        self.started = time.time()
        self._sketches = {(dim, op): SpaceSaving(capacity)
                          for dim in DIMENSIONS for op in OPS}

    def _offer(self, op: str, vid: int, key: int, client: str,
               tenant: str = "") -> None:
        self._sketches[("volume", op)].offer(vid)
        self._sketches[("needle", op)].offer(f"{vid},{key:x}")
        if client:
            self._sketches[("client", op)].offer(client)
        if tenant:
            self._sketches[("tenant", op)].offer(tenant)

    def read(self, vid: int, key: int, client: str = "",
             tenant: str = "") -> None:
        self._offer("read", vid, key, client, tenant)

    def write(self, vid: int, key: int, client: str = "",
              tenant: str = "") -> None:
        self._offer("write", vid, key, client, tenant)

    def snapshot(self, k: int = 16) -> dict:
        out: dict = {"capacity": self.capacity, "started": self.started,
                     "dimensions": {}}
        for dim in DIMENSIONS:
            out["dimensions"][dim] = {
                op: {"total": self._sketches[(dim, op)].total,
                     "top": self._sketches[(dim, op)].top(k)}
                for op in OPS}
        return out

    def clear(self) -> None:
        for sk in self._sketches.values():
            sk.clear()
        self.started = time.time()
