"""WebDAV gateway over the filer (reference: weed/server/webdav_server.go,
which adapts golang.org/x/net/webdav onto a filer-client FileSystem).

Implements the WebDAV class-2 verb set most clients (davfs2, macOS
Finder, Windows explorer, cadaver) exercise:

  OPTIONS            capability advertisement (DAV: 1,2)
  PROPFIND           207 multistatus listings, Depth 0/1
  GET/HEAD/PUT       file IO (streamed through the filer)
  DELETE             file or recursive collection delete
  MKCOL              mkdir
  MOVE/COPY          rename via the filer's atomic rename / byte copy
  LOCK/UNLOCK        in-memory advisory locks (x/net/webdav's memLS)
  PROPPATCH          accepted and echoed (properties are not persisted;
                     the reference's webdav FS ignores them too)
"""

from __future__ import annotations

import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from ..cluster import rpc
from ..filer.client import FilerProxy

DAV_NS = "DAV:"


def _dav(tag: str) -> str:
    return f"{{{DAV_NS}}}{tag}"


def _http_date(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


class WebDavServer:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 0, root: str = "/",
                 metrics_port: int | None = None,
                 ssl_context=None):
        self.filer = FilerProxy(filer_url)
        self.root = "/" + root.strip("/") if root.strip("/") else ""
        self.server = rpc.JsonHttpServer(host, port, pass_headers=True,
                                         ssl_context=ssl_context)
        for method in ("OPTIONS", "PROPFIND", "PROPPATCH", "GET", "HEAD",
                       "PUT", "POST", "DELETE", "MKCOL", "MOVE", "COPY",
                       "LOCK", "UNLOCK"):
            self.server.prefix_route(method, "/", self._route)
        # token -> path of advisory locks (memLS equivalent)
        self._locks: dict[str, tuple[str, float]] = {}  # token -> (path, expiry)
        self._locks_mu = threading.Lock()
        # WebDAV paths own the URL namespace; /metrics rides its own
        # port like the other gateways.
        self.metrics_registry = self.server.enable_metrics(
            "webdav", serve_route=False)
        self.metrics_server = None
        if metrics_port is not None:
            self.metrics_server = rpc.JsonHttpServer(host, metrics_port)
            self.metrics_server.serve_metrics_route(
                self.metrics_registry)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        if self.metrics_server is not None:
            self.metrics_server.start()

    def stop(self) -> None:
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.server.stop()

    def url(self) -> str:
        return self.server.url()

    # -- routing -------------------------------------------------------------

    def _fpath(self, path: str) -> str:
        p = urllib.parse.unquote(path).rstrip("/")
        return (self.root + p) or "/"

    def _route(self, path: str, query: dict, body: bytes):
        method = query.get("_method", "GET")
        headers = query.get("_headers", {})
        fpath = self._fpath(path)
        try:
            if method == "OPTIONS":
                return (200, b"", {"DAV": "1,2", "MS-Author-Via": "DAV",
                                   "Allow": "OPTIONS, PROPFIND, PROPPATCH,"
                                   " GET, HEAD, PUT, DELETE, MKCOL, MOVE,"
                                   " COPY, LOCK, UNLOCK"})
            if method == "PROPFIND":
                return self._propfind(fpath, path, headers)
            if method == "PROPPATCH":
                return self._proppatch(fpath, path)
            if method in ("GET", "HEAD"):
                return self._get(fpath, headers, head=(method == "HEAD"))
            if method == "PUT":
                return self._put(fpath, headers, body)
            if method == "DELETE":
                return self._delete(fpath)
            if method == "MKCOL":
                return self._mkcol(fpath, body)
            if method in ("MOVE", "COPY"):
                return self._move_copy(fpath, headers,
                                       copy=(method == "COPY"))
            if method == "LOCK":
                return self._lock(fpath)
            if method == "UNLOCK":
                return self._unlock(fpath, headers)
            return (405, b"method not allowed")
        except rpc.RpcError as e:
            return (e.status if e.status >= 400 else 502,
                    e.message.encode())

    # -- PROPFIND ------------------------------------------------------------

    def _prop_response(self, multistatus, href: str, meta: dict) -> None:
        resp = ET.SubElement(multistatus, _dav("response"))
        ET.SubElement(resp, _dav("href")).text = urllib.parse.quote(href)
        propstat = ET.SubElement(resp, _dav("propstat"))
        prop = ET.SubElement(propstat, _dav("prop"))
        is_dir = bool(meta.get("is_directory"))
        name = href.rstrip("/").rsplit("/", 1)[-1] or "/"
        ET.SubElement(prop, _dav("displayname")).text = name
        rt = ET.SubElement(prop, _dav("resourcetype"))
        attrs = meta.get("attributes", {})
        mtime = attrs.get("mtime", meta.get("mtime", 0))
        if is_dir:
            ET.SubElement(rt, _dav("collection"))
        else:
            size = meta.get("size",
                            sum(c.get("size", 0)
                                for c in meta.get("chunks", [])))
            ET.SubElement(prop, _dav("getcontentlength")).text = str(size)
            ET.SubElement(prop, _dav("getcontenttype")).text = \
                attrs.get("mime", "application/octet-stream")
        ET.SubElement(prop, _dav("getlastmodified")).text = \
            _http_date(mtime)
        ET.SubElement(prop, _dav("supportedlock"))
        ET.SubElement(propstat, _dav("status")).text = "HTTP/1.1 200 OK"

    def _propfind(self, fpath: str, href: str, headers: dict):
        depth = headers.get("depth", "1")
        meta = self.filer.meta(fpath) if fpath != "/" else \
            {"is_directory": True}
        if meta is None:
            return (404, b"not found")
        ET.register_namespace("D", DAV_NS)
        ms = ET.Element(_dav("multistatus"))
        href_base = href if href.endswith("/") or \
            not meta.get("is_directory") else href + "/"
        self._prop_response(ms, href_base, meta)
        if depth != "0" and meta.get("is_directory"):
            for e in self.filer.list_all(fpath):
                child_href = href_base.rstrip("/") + "/" + e["name"]
                if e.get("is_directory"):
                    child_href += "/"
                self._prop_response(ms, child_href, e)
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(ms)
        return (207, body, {"Content-Type": 'application/xml; '
                                            'charset="utf-8"'})

    def _proppatch(self, fpath: str, href: str):
        if self.filer.meta(fpath) is None:
            return (404, b"not found")
        ET.register_namespace("D", DAV_NS)
        ms = ET.Element(_dav("multistatus"))
        resp = ET.SubElement(ms, _dav("response"))
        ET.SubElement(resp, _dav("href")).text = urllib.parse.quote(href)
        ps = ET.SubElement(resp, _dav("propstat"))
        ET.SubElement(ps, _dav("prop"))
        ET.SubElement(ps, _dav("status")).text = "HTTP/1.1 200 OK"
        return (207, b'<?xml version="1.0" encoding="utf-8"?>' +
                ET.tostring(ms),
                {"Content-Type": 'application/xml; charset="utf-8"'})

    # -- file IO -------------------------------------------------------------

    def _get(self, fpath: str, headers: dict, head: bool):
        meta = self.filer.meta(fpath)
        if meta is None:
            return (404, b"not found")
        if meta.get("is_directory"):
            return (405, b"is a collection")
        attrs = meta.get("attributes", {})
        base = {"Content-Type": attrs.get("mime",
                                          "application/octet-stream"),
                "Last-Modified": _http_date(attrs.get("mtime", 0)),
                "Accept-Ranges": "bytes"}
        if head:
            size = sum(c.get("size", 0) for c in meta.get("chunks", []))
            base["Content-Length"] = str(size)
            return (200, b"", base)
        rng = headers.get("range", "")
        # Stream the open filer response through (no buffering).
        resp = self.filer.get(fpath, rng)
        base["Content-Length"] = resp.headers.get("Content-Length", "0")
        if resp.status == 206:
            base["Content-Range"] = resp.headers.get("Content-Range", "")
            return (206, resp, base)
        return (200, resp, base)

    def _put(self, fpath: str, headers: dict, body: bytes):
        existed = self.filer.meta(fpath) is not None
        self.filer.put(fpath, body,
                       headers.get("content-type",
                                   "application/octet-stream"))
        return (204 if existed else 201, b"")

    def _delete(self, fpath: str):
        if not self.filer.delete(fpath, recursive=True):
            return (404, b"not found")
        return (204, b"")

    def _mkcol(self, fpath: str, body: bytes):
        if body:
            return (415, b"MKCOL with body is unsupported")
        if self.filer.meta(fpath) is not None:
            return (405, b"already exists")
        parent = fpath.rsplit("/", 1)[0] or "/"
        if parent != "/" and self.filer.meta(parent) is None:
            return (409, b"parent collection missing")
        self.filer.mkdir(fpath)
        return (201, b"")

    def _move_copy(self, fpath: str, headers: dict, copy: bool):
        dest_url = headers.get("destination", "")
        if not dest_url:
            return (400, b"Destination header required")
        dest_path = urllib.parse.unquote(
            urllib.parse.urlparse(dest_url).path).rstrip("/")
        dest = (self.root + dest_path) or "/"
        overwrite = headers.get("overwrite", "T").upper() != "F"
        meta = self.filer.meta(fpath)
        if meta is None:
            return (404, b"source not found")
        existed = self.filer.meta(dest) is not None
        if existed and not overwrite:
            return (412, b"destination exists")
        if copy:
            if meta.get("is_directory"):
                return (501, b"COPY of collections is unsupported")
            with self.filer.get(fpath) as resp:
                data = resp.read()
            ctype = meta.get("attributes", {}).get(
                "mime", "application/octet-stream")
            self.filer.put(dest, data, ctype)
        else:
            if existed:
                self.filer.delete(dest, recursive=True)
            self.filer.rename(fpath, dest)
        return (204 if existed else 201, b"")

    # -- locks (advisory, in-memory like x/net/webdav memLS) -----------------

    LOCK_TIMEOUT = 3600.0

    def _purge_expired_locks(self) -> None:
        # Callers hold self._locks_mu.  Enforces the Second-3600 timeout
        # we advertise — abandoned tokens (crashed clients) must not
        # accumulate forever.
        now = time.time()
        for tok in [t for t, (_p, exp) in self._locks.items()
                    if exp < now]:
            del self._locks[tok]

    def _lock(self, fpath: str):
        token = f"opaquelocktoken:{uuid.uuid4()}"
        with self._locks_mu:
            self._purge_expired_locks()
            self._locks[token] = (fpath, time.time() + self.LOCK_TIMEOUT)
        ET.register_namespace("D", DAV_NS)
        root = ET.Element(_dav("prop"))
        ld = ET.SubElement(root, _dav("lockdiscovery"))
        al = ET.SubElement(ld, _dav("activelock"))
        lt = ET.SubElement(al, _dav("locktoken"))
        ET.SubElement(lt, _dav("href")).text = token
        ET.SubElement(al, _dav("timeout")).text = "Second-3600"
        body = b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(root)
        return (200, body, {"Content-Type": 'application/xml; '
                                            'charset="utf-8"',
                            "Lock-Token": f"<{token}>"})

    def _unlock(self, fpath: str, headers: dict):
        token = headers.get("lock-token", "").strip("<>")
        with self._locks_mu:
            self._locks.pop(token, None)
        return (204, b"")
