"""Bit-sliced GF(2) lowering of the LRC(10,2,2) matrices.

Sibling of `rs_bitmatrix.py` for the `lrc` codec: the same LSB-first
8x-expansion (`expand_bitmatrix`) applied to the LRC generator, so the
local-parity XOR rows, the Cauchy global rows, and every decode matrix
all flow through the identical `apply_bitmatrix_pallas` MXU kernel —
only the matrix argument changes.  The generic construction lives on
`codecs.Codec` (these matrices are codec *data*); this module keeps
the historical per-scheme entry points for benches and tests.
"""

from __future__ import annotations

import numpy as np


def _codec():
    from ..codecs import get_codec
    return get_codec("lrc")


def parity_bitmatrix() -> np.ndarray:
    """(8*4, 8*10) GF(2) parity matrix of LRC(10,2,2): two XOR
    local-parity row blocks (identity 8x8 blocks) + two Cauchy global
    row blocks."""
    return _codec().parity_bitmatrix()


def decode_bitmatrix(present: tuple[int, ...], wanted: tuple[int, ...],
                     prefer: tuple[int, ...] = ()
                     ) -> tuple[np.ndarray, tuple[int, ...]]:
    """(8*len(wanted), 8*len(used)) reconstruction matrix + the minimal
    `used` read set (5 survivors for an in-group loss, not 10)."""
    return _codec().decode_bitmatrix(tuple(present), tuple(wanted),
                                     tuple(prefer))
