"""Bit-sliced GF(2^8) -> GF(2) lowering of Reed-Solomon matrices.

The MXU cannot XOR-accumulate, but GF(2^8) multiplication by a *constant* is
linear over GF(2): for a fixed coefficient c there is an 8x8 bit matrix M_c
with  bits(c*x) = M_c @ bits(x) (mod 2).  A whole RS code matrix C (r x k
over GF(2^8)) therefore lowers to a single (8r x 8k) 0/1 matrix B, and shard
encoding becomes

    parity_bits = (B @ data_bits) mod 2

which is an ordinary small-by-huge integer matmul — exactly what the TPU MXU
is built for (bf16 inputs, f32 accumulate: sums <= 8k < 2^24 are exact).
This replaces the reference's AVX2 PSHUFB galois kernels
(klauspost/reedsolomon, used at `weed/storage/erasure_coding/ec_encoder.go`)
with a formulation that runs at matmul speed on the MXU.

Bit conventions: bit j of a byte is (byte >> j) & 1 (LSB-first).  Row/col
index 8*s + j refers to bit j of shard s.
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256


def mul_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of 'multiply by constant c' acting on LSB-first bits.

    Column j is bits(c * 2^j):  out_bit[i] = XOR_j in_bit[j] * M[i, j].
    """
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gf256.gf_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (prod >> i) & 1
    return m


def expand_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Lower an (r x k) GF(2^8) matrix to the (8r x 8k) GF(2) block matrix."""
    r, k = mat.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            c = int(mat[i, j])
            if c:
                out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = mul_bitmatrix(c)
    return out


@functools.lru_cache(maxsize=None)
def parity_bitmatrix(data_shards: int, total_shards: int,
                     kind: str = "vandermonde") -> np.ndarray:
    """Bit-lowered parity matrix: (8*parity, 8*data) uint8 0/1."""
    pm = gf256.parity_matrix(data_shards, total_shards, kind)
    b = expand_bitmatrix(pm)
    b.setflags(write=False)
    return b


@functools.lru_cache(maxsize=256)
def decode_bitmatrix(data_shards: int, total_shards: int,
                     present: tuple[int, ...], wanted: tuple[int, ...] | None = None,
                     kind: str = "vandermonde") -> tuple[np.ndarray, tuple[int, ...]]:
    """Bit-lowered reconstruction matrix for a given survivor set.

    Returns (B, used): B is (8*len(wanted), 8*data_shards) and maps the bits
    of the `used` survivor shards (first data_shards of `present`, stacked in
    order) to the bits of the `wanted` shards.
    """
    mat, used = gf256.decode_matrix(
        data_shards, total_shards, list(present),
        wanted=list(wanted) if wanted is not None else None, kind=kind)
    b = expand_bitmatrix(mat)
    b.setflags(write=False)  # cached: must not be mutated by callers
    return b, tuple(used)


# ---------------------------------------------------------------------------
# Host-side bit (un)packing helpers — numpy oracle for the JAX/Pallas paths
# ---------------------------------------------------------------------------


def unpack_bits(shards: np.ndarray) -> np.ndarray:
    """(k, n) uint8 bytes -> (8k, n) uint8 bits, LSB-first per shard row."""
    k, n = shards.shape
    shifts = np.arange(8, dtype=np.uint8)
    bits = (shards[:, None, :] >> shifts[None, :, None]) & 1  # (k, 8, n)
    return bits.reshape(8 * k, n)


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """(8r, n) uint8 bits -> (r, n) uint8 bytes, LSB-first."""
    r8, n = bits.shape
    r = r8 // 8
    weights = (1 << np.arange(8, dtype=np.uint16))
    grouped = bits.reshape(r, 8, n).astype(np.uint16)
    return (grouped * weights[None, :, None]).sum(axis=1).astype(np.uint8)


def encode_bits_numpy(data: np.ndarray, data_shards: int, total_shards: int,
                      kind: str = "vandermonde") -> np.ndarray:
    """Bit-sliced encode in numpy (oracle for the matmul formulation)."""
    b = parity_bitmatrix(data_shards, total_shards, kind)
    bits = unpack_bits(np.asarray(data, np.uint8))
    parity_bits = (b.astype(np.int32) @ bits.astype(np.int32)) & 1
    return pack_bits(parity_bits.astype(np.uint8))
