"""ErasureCoder backend selection.

The reference hides klauspost/reedsolomon behind direct calls in
`ec_encoder.go`; BASELINE.json's design point is an `ErasureCoder`
interface seam that picks a backend at startup.  Backends:

- "numpy":  table-lookup oracle (always available, slow)
- "native": C++ AVX2 PSHUFB kernels (klauspost-class CPU path; needs
            `make -C native`)
- "jax":    XLA bit-sliced matmul (any jax backend)
- "pallas": fused MXU kernel (TPU; interpreter mode elsewhere)

Selection: SEAWEEDFS_TPU_CODER env var, else pallas on TPU, else native
if built, else jax.
All backends share the same API: encode / encode_all / reconstruct / verify,
operating on (shards, n) uint8 arrays; results are byte-identical.
"""

from __future__ import annotations

import os
from typing import Protocol

import numpy as np


class ErasureCoder(Protocol):
    data_shards: int
    parity_shards: int
    total_shards: int

    def encode(self, data) -> np.ndarray: ...
    def encode_all(self, data) -> np.ndarray: ...
    def reconstruct(self, shards: dict[int, np.ndarray],
                    wanted: list[int] | None = None) -> dict[int, np.ndarray]: ...
    def verify(self, shards) -> bool: ...


_BACKENDS = ("numpy", "native", "jax", "pallas")


def _native_available() -> bool:
    from ..utils import native as native_mod
    return native_mod.load() is not None


def default_backend() -> str:
    env = os.environ.get("SEAWEEDFS_TPU_CODER")
    if env:
        if env not in _BACKENDS:
            raise ValueError(
                f"SEAWEEDFS_TPU_CODER={env!r}; expected one of {_BACKENDS}")
        return env
    try:
        import jax
        if jax.devices()[0].platform == "tpu":
            return "pallas"
        if _native_available():
            return "native"
        return "jax"
    except Exception:
        return "native" if _native_available() else "numpy"


def new_coder(data_shards: int = 10, parity_shards: int = 4,
              matrix_kind: str = "vandermonde",
              backend: str | None = None, codec=None) -> ErasureCoder:
    """Build a coder.  `codec` (a registered codec name or Codec
    object, e.g. "lrc") overrides the RS shard-count arguments — the
    codec IS the scheme; the backend is just where the matmul runs."""
    backend = backend or default_backend()
    if backend == "numpy":
        from .coder_numpy import NumpyCoder
        return NumpyCoder(data_shards, parity_shards, matrix_kind, codec)
    if backend == "native":
        from .coder_native import NativeCoder
        return NativeCoder(data_shards, parity_shards, matrix_kind, codec)
    if backend == "jax":
        from .coder_jax import JaxCoder
        return JaxCoder(data_shards, parity_shards, matrix_kind, codec)
    if backend == "pallas":
        from .coder_pallas import PallasCoder
        return PallasCoder(data_shards, parity_shards, matrix_kind,
                           codec=codec)
    raise ValueError(f"unknown erasure backend {backend!r}")
