"""GF(2^8) arithmetic and matrix algebra for Reed-Solomon erasure coding.

This is the mathematical core of the TPU-native erasure-coding pipeline.  The
reference (SeaweedFS) delegates this to github.com/klauspost/reedsolomon,
whose field is GF(2^8) with the reducing polynomial x^8+x^4+x^3+x^2+1
(0x11D) and whose systematic code matrix is built from an extended
Vandermonde matrix made systematic by right-multiplying with the inverse of
its top square (the Backblaze JavaReedSolomon construction).  We reproduce
that construction exactly so that shard bytes are bit-identical with the
reference's `.ec00`-`.ec13` outputs (reference call sites:
`weed/storage/erasure_coding/ec_encoder.go:198` `reedsolomon.New(10,4)`).

Everything here is tiny, setup-time work done in numpy on the host; the hot
path (the actual byte crunching) lives in `rs_bitmatrix.py` / `coder_jax.py`
/ `coder_pallas.py`, which consume the matrices produced here.
"""

from __future__ import annotations

import functools

import numpy as np

# The reducing polynomial used by klauspost/reedsolomon (and Backblaze's
# JavaReedSolomon, and Intel ISA-L's default): x^8 + x^4 + x^3 + x^2 + 1.
GENERATING_POLYNOMIAL = 0x11D

FIELD_SIZE = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build exp/log tables for GF(2^8) with generator 2.

    exp table is doubled (510 entries) so mul can skip the mod-255.
    """
    exp = np.zeros(510, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GENERATING_POLYNOMIAL
    exp[255:510] = exp[0:255]
    log[0] = -1  # log(0) undefined; sentinel
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Multiply two field elements."""
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[GF_LOG[a] + GF_LOG[b]])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] - GF_LOG[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_EXP[(255 - GF_LOG[a]) % 255])


def gf_exp(a: int, n: int) -> int:
    """a raised to the n'th power (klauspost `galExp` semantics: 0^0 == 1)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(GF_LOG[a] * n) % 255])


def _build_mul_table() -> np.ndarray:
    """Full 256x256 multiplication table (used by the numpy reference coder)."""
    t = np.zeros((256, 256), dtype=np.uint8)
    la = GF_LOG[1:256]
    idx = la[:, None] + la[None, :]
    t[1:, 1:] = GF_EXP[idx]
    t.setflags(write=False)
    return t


MUL_TABLE = _build_mul_table()


def mul_table() -> np.ndarray:
    return MUL_TABLE


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8)
# ---------------------------------------------------------------------------


def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8). a: (m,k) uint8, b: (k,n) uint8."""
    t = mul_table()
    # products[i,j,l] = a[i,l] * b[l,j] in GF; XOR-reduce over l.
    prods = t[a[:, None, :], b.T[None, :, :]]  # (m, n, k)
    return np.bitwise_xor.reduce(prods, axis=2).astype(np.uint8)


def mat_identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8). Raises ValueError if singular."""
    n = m.shape[0]
    if m.shape[0] != m.shape[1]:
        raise ValueError("matrix must be square")
    t = mul_table()
    work = np.concatenate([m.astype(np.uint8), mat_identity(n)], axis=1)
    for col in range(n):
        # Find pivot.
        pivot = -1
        for r in range(col, n):
            if work[r, col] != 0:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(2^8)")
        if pivot != col:
            work[[col, pivot]] = work[[pivot, col]]
        # Scale pivot row to 1.
        inv = gf_inv(int(work[col, col]))
        work[col] = t[inv, work[col]]
        # Eliminate all other rows.
        for r in range(n):
            if r != col and work[r, col] != 0:
                factor = int(work[r, col])
                work[r] ^= t[factor, work[col]]
    return work[:, n:].copy()


# ---------------------------------------------------------------------------
# Code-matrix constructions
# ---------------------------------------------------------------------------


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """Extended Vandermonde matrix: vm[r, c] = r ** c in GF(2^8).

    This is the exact construction used by klauspost/reedsolomon
    (`vandermonde(totalShards, dataShards)`), which seaweedfs uses through
    `reedsolomon.New(10, 4)` (reference: ec_encoder.go:198).
    """
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


@functools.lru_cache(maxsize=None)
def build_systematic_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """klauspost `buildMatrix`: systematic (total x data) encode matrix.

    Top `data_shards` rows are the identity; the remaining rows generate the
    parity shards.  Byte-compatible with the reference's shard files.
    """
    if not (0 < data_shards < total_shards <= FIELD_SIZE):
        raise ValueError("invalid shard counts")
    vm = vandermonde(total_shards, data_shards)
    top_inv = mat_inv(vm[:data_shards])
    m = mat_mul(vm, top_inv)
    assert np.array_equal(m[:data_shards], mat_identity(data_shards))
    m.setflags(write=False)
    return m


@functools.lru_cache(maxsize=None)
def build_cauchy_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """klauspost `buildMatrixCauchy` (WithCauchyMatrix option).

    Identity on top; parity row r, col c = 1 / (r ^ c) where r ranges over
    [data_shards, total_shards).  Already systematic by construction.
    Provided for the parameterized RS(16,4)/RS(8,3) alt schemes in
    BASELINE.json; the default stays Vandermonde for reference parity.
    """
    if not (0 < data_shards < total_shards <= FIELD_SIZE):
        raise ValueError("invalid shard counts")
    m = np.zeros((total_shards, data_shards), dtype=np.uint8)
    m[:data_shards] = mat_identity(data_shards)
    for r in range(data_shards, total_shards):
        for c in range(data_shards):
            m[r, c] = gf_inv(r ^ c)
    m.setflags(write=False)
    return m


def parity_matrix(data_shards: int, total_shards: int,
                  kind: str = "vandermonde") -> np.ndarray:
    """The (parity x data) sub-matrix that maps data shards to parity shards."""
    if kind == "vandermonde":
        return build_systematic_matrix(data_shards, total_shards)[data_shards:]
    if kind == "cauchy":
        return build_cauchy_matrix(data_shards, total_shards)[data_shards:]
    raise ValueError(f"unknown matrix kind {kind!r}")


def decode_matrix(data_shards: int, total_shards: int,
                  present: list[int], wanted: list[int] | None = None,
                  kind: str = "vandermonde") -> tuple[np.ndarray, list[int]]:
    """Build the matrix that reconstructs shards from surviving shards.

    `present` is the sorted list of available shard ids (>= data_shards of
    them).  Returns (matrix, used) where `used` is the subset of `present`
    (exactly `data_shards` ids — the first data_shards available, matching
    klauspost's subshard selection in `Reconstruct`) and `matrix` maps the
    stacked `used` shards to the `wanted` shard contents (default: all
    missing shards).
    """
    if kind == "vandermonde":
        full = build_systematic_matrix(data_shards, total_shards)
    elif kind == "cauchy":
        full = build_cauchy_matrix(data_shards, total_shards)
    else:
        raise ValueError(f"unknown matrix kind {kind!r}")

    present = sorted(present)
    bad = [s for s in present if not 0 <= s < total_shards]
    if bad:
        raise ValueError(
            f"survivor shard ids {bad} out of range [0, {total_shards})")
    if len(set(present)) != len(present):
        raise ValueError(f"duplicate survivor shard ids in {present}")
    if len(present) < data_shards:
        raise ValueError(
            f"too few shards: have {len(present)}, need {data_shards}")
    used = present[:data_shards]
    sub = full[used]  # (data, data)
    sub_inv = mat_inv(sub)  # maps used-shard bytes -> original data bytes

    if wanted is None:
        wanted = [s for s in range(total_shards) if s not in set(present)]
    # shard w = full[w] @ data = full[w] @ sub_inv @ used_shards
    mat = mat_mul(full[list(wanted)], sub_inv)
    return mat, used
