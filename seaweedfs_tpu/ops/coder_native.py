"""CPU-native Reed-Solomon coder backed by the C++ AVX2 PSHUFB kernels.

This is the host-side analog of klauspost/reedsolomon (the reference's CPU
path) — it exists (a) as the honest CPU baseline for the TPU benchmark and
(b) as the fast fallback when no accelerator is attached.  Requires
`make -C native`; raises at construction if the library is missing.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..utils import native as native_mod


class NativeCoder:
    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 matrix_kind: str = "vandermonde", codec=None):
        from ..codecs import get_codec, rs_codec
        lib = native_mod.load()
        if lib is None:
            raise RuntimeError(
                "native library not built — run `make -C native`")
        self._mix = native_mod.gf_encode_fn(lib)
        self.codec = rs_codec(data_shards, parity_shards, matrix_kind) \
            if codec is None else get_codec(codec)
        self.data_shards = self.codec.data_shards
        self.parity_shards = self.codec.parity_shards
        self.total_shards = self.codec.total_shards
        self.matrix_kind = self.codec.matrix_kind
        self.parity_mat = self.codec.parity_matrix()

    def _apply(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        rows, cols = mat.shape
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        n = shards.shape[1]
        out = np.empty((rows, n), dtype=np.uint8)
        mat_flat = np.ascontiguousarray(mat, dtype=np.uint8)
        ins = (ctypes.c_void_p * cols)(*[
            shards[c].ctypes.data for c in range(cols)])
        outs = (ctypes.c_void_p * rows)(*[
            out[r].ctypes.data for r in range(rows)])
        self._mix(mat_flat.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)), rows, cols, ins, outs, n)
        return out

    def encode(self, data) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.shape[0] != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} data shards, got {data.shape[0]}")
        return self._apply(self.parity_mat, data)

    def encode_all(self, data) -> np.ndarray:
        data = np.asarray(data, np.uint8)
        return np.concatenate([data, self.encode(data)], axis=0)

    def reconstruct(self, shards: dict[int, np.ndarray],
                    wanted: list[int] | None = None) -> dict[int, np.ndarray]:
        present = sorted(shards)
        if wanted is None:
            wanted = [s for s in range(self.total_shards) if s not in shards]
        bad = [w for w in wanted if not 0 <= w < self.total_shards]
        if bad:
            raise ValueError(
                f"shard ids {bad} out of range [0, {self.total_shards})")
        if not wanted:
            return {}
        mat, used = self.codec.decode_matrix(tuple(present), tuple(wanted))
        stacked = np.stack([np.asarray(shards[s], np.uint8) for s in used])
        rec = self._apply(mat, stacked)
        return {w: rec[i] for i, w in enumerate(wanted)}

    def verify(self, shards) -> bool:
        shards = np.asarray(shards, np.uint8)
        parity = self.encode(shards[: self.data_shards])
        return bool(np.array_equal(parity, shards[self.data_shards:]))
