"""Erasure-coding compute kernels: GF(2^8) math lowered to TPU matmuls."""
