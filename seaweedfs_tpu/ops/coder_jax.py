"""XLA Reed-Solomon coder: bit-sliced GF(2) matmul, jittable on TPU/CPU.

The RS byte-mix (klauspost/reedsolomon's galois kernels in the reference,
used from `weed/storage/erasure_coding/ec_encoder.go`) becomes, per
`rs_bitmatrix.py`,

    out_bits = (B @ in_bits) mod 2

This module keeps the whole computation in traced JAX so it runs under jit
on any backend; the Pallas variant (`coder_pallas.py`) fuses unpack/matmul/
pack into VMEM for peak MXU throughput.

Bit layout is *plane-major* to stay 2D on TPU: row `s*k + j` holds bit `s`
of shard `j`.  Sums over the contracting dim are <= 8k <= 2048 so bf16
inputs with f32 accumulation are exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def plane_major(bmat: np.ndarray, rows: int, cols: int) -> np.ndarray:
    """Permute an interleaved (8r x 8k) bit matrix into plane-major order.

    Interleaved index 8*s + b (bit b of shard s)  ->  plane-major b*n + s.
    """
    r8, k8 = bmat.shape
    assert r8 == 8 * rows and k8 == 8 * cols
    row_perm = [8 * (q % rows) + (q // rows) for q in range(8 * rows)]
    col_perm = [8 * (q % cols) + (q // cols) for q in range(8 * cols)]
    return bmat[np.ix_(row_perm, col_perm)]


@functools.partial(jax.jit, static_argnames=("out_rows",))
def apply_bitmatrix(bmat_pm: jax.Array, shards: jax.Array,
                    out_rows: int) -> jax.Array:
    """out = GF-matrix-mix of byte shards, via one GF(2) matmul.

    bmat_pm: (8*out_rows, 8*k) plane-major 0/1, any int/float dtype.
    shards:  (k, n) uint8.
    Returns (out_rows, n) uint8.
    """
    x = shards.astype(jnp.int32)
    # Unpack: plane-major bit rows, still 2D. (8k, n)
    bits = jnp.concatenate([(x >> s) & 1 for s in range(8)], axis=0)
    # GF(2) matmul on the MXU: bf16 x bf16 -> f32 is exact for sums <= 8k.
    acc = jnp.dot(bmat_pm.astype(jnp.bfloat16), bits.astype(jnp.bfloat16),
                  preferred_element_type=jnp.float32)
    parity_bits = acc.astype(jnp.int32) & 1  # (8*out_rows, n)
    # Pack plane-major rows back into bytes.
    out = parity_bits[0:out_rows]
    for s in range(1, 8):
        out = out | (parity_bits[s * out_rows:(s + 1) * out_rows] << s)
    return out.astype(jnp.uint8)


class JaxCoder:
    """Drop-in analog of NumpyCoder running under jit (XLA path)."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 matrix_kind: str = "vandermonde", codec=None):
        from ..codecs import get_codec, rs_codec
        self.codec = rs_codec(data_shards, parity_shards, matrix_kind) \
            if codec is None else get_codec(codec)
        self.data_shards = self.codec.data_shards
        self.parity_shards = self.codec.parity_shards
        self.total_shards = self.codec.total_shards
        self.matrix_kind = self.codec.matrix_kind
        pb = self.codec.parity_bitmatrix()
        self._parity_pm = jnp.asarray(
            plane_major(pb, self.parity_shards, self.data_shards),
            jnp.bfloat16)

    # -- primitives --------------------------------------------------------

    def encode(self, data) -> jax.Array:
        """(data_shards, n) uint8 -> (parity_shards, n) uint8."""
        data = jnp.asarray(data, jnp.uint8)
        if data.shape[0] != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} data shards, got {data.shape[0]}")
        return apply_bitmatrix(self._parity_pm, data, self.parity_shards)

    def encode_all(self, data) -> jax.Array:
        data = jnp.asarray(data, jnp.uint8)
        return jnp.concatenate([data, self.encode(data)], axis=0)

    @functools.lru_cache(maxsize=256)
    def _decode_mat_pm(self, present: tuple[int, ...],
                       wanted: tuple[int, ...]) -> tuple[jax.Array, tuple[int, ...]]:
        bmat, used = self.codec.decode_bitmatrix(present, wanted)
        pm = plane_major(np.asarray(bmat), len(wanted), len(used))
        return jnp.asarray(pm, jnp.bfloat16), used

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        """Recover missing shards from >= data_shards survivors (one matmul).

        Unlike the reference's two-step Reconstruct (solve data, then
        re-encode parity — `klauspost.Reconstruct`), the decode matrix here
        composes both steps, so any mix of lost data/parity shards is one
        fused GF(2) matmul.
        """
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [s for s in range(self.total_shards) if s not in shards]
        bad = [w for w in wanted if not 0 <= w < self.total_shards]
        if bad:
            raise ValueError(
                f"shard ids {bad} out of range [0, {self.total_shards})")
        if not wanted:
            return {}
        mat_pm, used = self._decode_mat_pm(present, tuple(wanted))
        stacked = jnp.stack([jnp.asarray(shards[s], jnp.uint8) for s in used])
        rec = apply_bitmatrix(mat_pm, stacked, len(wanted))
        return {w: rec[i] for i, w in enumerate(wanted)}

    def verify(self, shards) -> bool:
        shards = jnp.asarray(shards, jnp.uint8)
        parity = self.encode(shards[: self.data_shards])
        return bool(jnp.array_equal(parity, shards[self.data_shards:]))
