"""Pure-numpy Reed-Solomon coder — the semantic reference implementation.

Mirrors the behavior of klauspost/reedsolomon's `Encode`, `Reconstruct` and
`ReconstructData` as used by seaweedfs (`ec_encoder.go:198,235`,
`store_ec.go:325,367`), but via table-lookup numpy ops.  This is the slow,
obviously-correct oracle that the JAX/Pallas coders are tested against; it
is also the fallback when no accelerator is present.
"""

from __future__ import annotations

import numpy as np

from . import gf256


class NumpyCoder:
    """Systematic erasure coder over GF(2^8) for any registered codec
    (default: RS(data_shards, parity_shards))."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 matrix_kind: str = "vandermonde", codec=None):
        from ..codecs import get_codec, rs_codec
        self.codec = rs_codec(data_shards, parity_shards, matrix_kind) \
            if codec is None else get_codec(codec)
        self.data_shards = self.codec.data_shards
        self.parity_shards = self.codec.parity_shards
        self.total_shards = self.codec.total_shards
        self.matrix_kind = self.codec.matrix_kind
        self.parity_mat = self.codec.parity_matrix()

    # -- core GF matmul on byte planes ------------------------------------

    @staticmethod
    def _apply(mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """out[r] = XOR_c mat[r,c] * shards[c]  (GF(2^8) row mix).

        shards: (k, n) uint8.  Returns (rows, n) uint8.
        """
        t = gf256.mul_table()
        rows = mat.shape[0]
        n = shards.shape[1]
        out = np.zeros((rows, n), dtype=np.uint8)
        for r in range(rows):
            acc = out[r]
            for c in range(mat.shape[1]):
                coef = mat[r, c]
                if coef == 0:
                    continue
                np.bitwise_xor(acc, t[coef][shards[c]], out=acc)
        return out

    # -- public API --------------------------------------------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data: (data_shards, n) uint8 -> parity (parity_shards, n) uint8."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.shape[0] != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} data shards, got {data.shape[0]}")
        return self._apply(self.parity_mat, data)

    def encode_all(self, data: np.ndarray) -> np.ndarray:
        """Returns all (total_shards, n) shards (data rows passed through)."""
        return np.concatenate([np.asarray(data, np.uint8),
                               self.encode(data)], axis=0)

    def reconstruct(self, shards: dict[int, np.ndarray],
                    wanted: list[int] | None = None) -> dict[int, np.ndarray]:
        """Recover missing shards from any >= data_shards survivors.

        `shards` maps shard id -> (n,) or (n,) uint8 rows.  Returns a dict of
        the reconstructed shards (id -> bytes).  Matches klauspost
        `Reconstruct` (all shards) / `ReconstructData` (wanted=[0..k)).
        """
        present = sorted(shards)
        bad = [s for s in present if not 0 <= s < self.total_shards]
        if bad:
            raise ValueError(
                f"survivor shard ids {bad} out of range [0, {self.total_shards})")
        if wanted is None:
            wanted = [s for s in range(self.total_shards) if s not in shards]
        bad = [w for w in wanted if not 0 <= w < self.total_shards]
        if bad:
            raise ValueError(
                f"shard ids {bad} out of range [0, {self.total_shards})")
        if not wanted:
            return {}
        if not self.codec.is_rs:
            # Generic codecs (LRC): one minimal-read GF solve covers
            # any mix of data/local-parity/global-parity shards.
            mat, used = self.codec.decode_matrix(
                tuple(present), tuple(wanted))
            stacked = np.stack([np.asarray(shards[s], np.uint8)
                                for s in used])
            rec = self._apply(mat, stacked)
            return {w: rec[i] for i, w in enumerate(wanted)}
        missing_parity = [w for w in wanted if w >= self.data_shards]
        # One decode solve covers wanted data shards plus any data shards
        # needed to re-encode wanted parity.
        solve_data = sorted({w for w in wanted if w < self.data_shards} |
                            ({d for d in range(self.data_shards)
                              if d not in shards} if missing_parity else set()))

        out: dict[int, np.ndarray] = {}
        solved: dict[int, np.ndarray] = {}
        if solve_data:
            mat, used = gf256.decode_matrix(
                self.data_shards, self.total_shards, present,
                wanted=solve_data, kind=self.matrix_kind)
            stacked = np.stack([np.asarray(shards[s], np.uint8) for s in used])
            rec = self._apply(mat, stacked)
            solved = {d: rec[i] for i, d in enumerate(solve_data)}
            out.update({d: solved[d] for d in solve_data if d in wanted})

        if missing_parity:
            data = np.stack([
                np.asarray(shards[d], np.uint8) if d in shards else solved[d]
                for d in range(self.data_shards)])
            parity = self.encode(data)
            for w in missing_parity:
                out[w] = parity[w - self.data_shards]
        return out

    def verify(self, shards: np.ndarray) -> bool:
        """shards: (total, n). True iff parity rows match the data rows."""
        parity = self.encode(shards[: self.data_shards])
        return bool(np.array_equal(parity, shards[self.data_shards:]))
