"""Fused Pallas TPU kernel for Reed-Solomon GF(2^8) coding on the MXU.

The XLA path (`coder_jax.py`) materializes the unpacked bit planes (an 8x
expansion of the data) in HBM between the unpack and the matmul.  This
kernel fuses the whole pipeline per tile in VMEM:

    HBM --(k,BN) bytes--> VMEM
        unpack to (8k,BN) bit planes            (VPU shifts)
        (8r,8k) @ (8k,BN) bf16 matmul, f32 acc  (MXU)
        mod-2 + pack to (r,BN) bytes            (VPU)
    VMEM --(r,BN) bytes--> HBM

so HBM traffic stays at bytes-in + bytes-out while the GF math runs at MXU
rate.  This is the TPU replacement for klauspost/reedsolomon's AVX2 galois
kernels (reference hot loop: weed/storage/erasure_coding/ec_encoder.go:162,
store_ec.go:322).

The same kernel serves encode (B = parity bit-matrix) and reconstruction
(B = decode bit-matrix for the survivor set) — only the matrix changes.
"""

from __future__ import annotations

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..stats import roofline as _roofline
from ..stats.metrics import observe_ec_stage
from . import crc_fold


def _record_roofline(kernel: str, coder, *, out_rows: int,
                     in_rows: int, n: int, crc: bool,
                     seconds: float, measured_bytes: int) -> None:
    """Feed one execution-fenced kernel wall into the roofline ledger.
    Accounting must never take the encode path down; the ARMED check
    stays at the call site so the disarmed cost is one flag read."""
    try:
        _roofline.LEDGER.record(
            kernel, coder.codec.name, coder.mm, out_rows=out_rows,
            in_rows=in_rows, n=n, crc=crc, seconds=seconds,
            measured_bytes=measured_bytes)
    except Exception:  # noqa: BLE001
        pass


def _prof_on() -> bool:
    """Per-stage device-time histograms (SEAWEEDFS_TPU_EC_PROF=0 to
    disable).  Profiling fences each call with block_until_ready — in
    the serving paths results are staged to host right away so the
    fence costs nothing; raw-throughput benchmarks that pipeline
    dispatches (bench.py drives apply_bitmatrix_pallas directly and is
    unaffected) can turn it off."""
    return os.environ.get("SEAWEEDFS_TPU_EC_PROF", "1") \
        not in ("0", "false")

# Lane-dimension tile: one grid step processes k x BLOCK_N bytes.
# 8k x BLOCK_N bf16 bit planes = 80*4096*2B = 640KB VMEM for RS(10,4) —
# comfortably inside VMEM while long enough to amortize the small matmul M.
BLOCK_N = 4096


def _rs_kernel(b_ref, d_ref, o_ref, *, out_rows: int, in_rows: int,
               mm_dtype):
    """One tile: bytes (in_rows, BN) -> bytes (out_rows, BN)."""
    x = d_ref[:].astype(jnp.int32)
    # Plane-major unpack: row s*k + j is bit s of shard j. Stays 2D.
    bits = jnp.concatenate(
        [(x >> s) & 1 for s in range(8)], axis=0).astype(mm_dtype)
    acc_t = jnp.float32 if mm_dtype == jnp.bfloat16 else jnp.int32
    acc = jnp.dot(b_ref[:], bits, preferred_element_type=acc_t)
    pbits = acc.astype(jnp.int32) & 1  # sums <= 8k < 2^24: exact either way
    out = pbits[0:out_rows]
    for s in range(1, 8):
        out = out | (pbits[s * out_rows:(s + 1) * out_rows] << s)
    o_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("out_rows", "in_rows", "interpret",
                                    "block_n", "mm"))
def apply_bitmatrix_pallas(bmat_pm: jax.Array, shards: jax.Array,
                           out_rows: int, in_rows: int,
                           interpret: bool = False,
                           block_n: int = BLOCK_N,
                           mm: str = "bf16") -> jax.Array:
    """(8*out_rows, 8*in_rows) plane-major bit matrix x (in_rows, n) bytes.

    n must be a multiple of block_n (the file pipeline's buffers are);
    `pad_to_block` below handles ragged tails.
    """
    n = shards.shape[1]
    grid = (n // block_n,)
    mm_dtype = jnp.bfloat16 if mm == "bf16" else jnp.int8
    kernel = functools.partial(_rs_kernel, out_rows=out_rows,
                               in_rows=in_rows, mm_dtype=mm_dtype)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * out_rows, 8 * in_rows), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((in_rows, block_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((out_rows, block_n), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((out_rows, n), jnp.uint8),
        cost_estimate=pl.CostEstimate(
            flops=2 * 8 * out_rows * 8 * in_rows * n,
            bytes_accessed=(in_rows + out_rows) * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(bmat_pm.astype(mm_dtype), shards)


def _rs_crc_kernel(b_ref, d_ref, w0_ref, pl_ref, pm_ref, o_ref, c_ref, *,
                   out_rows: int, in_rows: int, mm_dtype):
    """One tile of the CRC-fused pipeline: bytes (in_rows, BN) ->
    parity bytes (out_rows, BN) PLUS a position-shifted CRC32-C tile
    partial per row (in_rows data rows first, then out_rows parity
    rows) — the `.ecc` sidecar computed from the bits already unpacked
    in VMEM (ops/crc_fold.py has the algebra).  pm_ref is the
    tile-position-in-block shift matrix, selected by the grid index
    mod tiles-per-block, so host-side folding is a plain XOR."""
    x = d_ref[:].astype(jnp.int32)
    bits_i = jnp.concatenate(
        [(x >> s) & 1 for s in range(8)], axis=0)
    bits = bits_i.astype(mm_dtype)
    acc_t = jnp.float32 if mm_dtype == jnp.bfloat16 else jnp.int32
    acc = jnp.dot(b_ref[:], bits, preferred_element_type=acc_t)
    pbits = acc.astype(jnp.int32) & 1
    out = pbits[0:out_rows]
    for s in range(1, 8):
        out = out | (pbits[s * out_rows:(s + 1) * out_rows] << s)
    o_ref[:] = out.astype(jnp.uint8)

    w0 = w0_ref[:]          # (BN, 32)
    pm = pm_ref[:]          # (32, 32) — position shift, transposed

    def row_crcs(plane_bits, rows):
        # (8*rows, BN) plane-major 0/1 -> (rows, 1) uint32 partial
        u = jnp.dot(plane_bits, w0, preferred_element_type=acc_t)
        ub = (u.astype(jnp.int32) & 1).astype(mm_dtype)
        fold = jnp.zeros((rows, 32), acc_t)
        for s in range(8):
            fold = fold + jnp.dot(
                ub[s * rows:(s + 1) * rows],
                pl_ref[s * 32:(s + 1) * 32],
                preferred_element_type=acc_t)
        vb = (fold.astype(jnp.int32) & 1).astype(mm_dtype)
        sh = jnp.dot(vb, pm, preferred_element_type=acc_t) \
            .astype(jnp.int32) & 1
        w = jnp.left_shift(
            jnp.uint32(1),
            jax.lax.broadcasted_iota(jnp.uint32, (1, 32), 1))
        return jnp.sum(sh.astype(jnp.uint32) * w, axis=1,
                       keepdims=True, dtype=jnp.uint32)

    c_ref[:] = jnp.concatenate(
        [row_crcs(bits, in_rows),
         row_crcs(pbits.astype(mm_dtype), out_rows)], axis=0)


@functools.partial(jax.jit,
                   static_argnames=("out_rows", "in_rows", "interpret",
                                    "block_n", "mm", "crc_block"))
def apply_bitmatrix_crc_pallas(bmat_pm: jax.Array, shards: jax.Array,
                               w0: jax.Array, planes_t: jax.Array,
                               posmats_t: jax.Array,
                               out_rows: int, in_rows: int,
                               interpret: bool = False,
                               block_n: int = BLOCK_N,
                               mm: str = "bf16",
                               crc_block: int = crc_fold.BLOCK):
    """apply_bitmatrix_pallas plus fused `.ecc` CRC32-C: returns
    (parity (out_rows, n) uint8, crc tile partials
    (in_rows + out_rows, n // block_n) uint32).

    The partials are position-shifted: XOR-ing the `crc_block //
    block_n` partials of one `.ecc` block and XOR-ing the zero-block
    constant yields the actual crc32c of that block
    (crc_fold.block_crcs_from_partials / FusedCrcAccumulator).
    The input must start on a `.ecc` block boundary.
    """
    n = shards.shape[1]
    grid = (n // block_n,)
    tpb = crc_block // block_n
    mm_dtype = jnp.bfloat16 if mm == "bf16" else jnp.int8
    kernel = functools.partial(_rs_crc_kernel, out_rows=out_rows,
                               in_rows=in_rows, mm_dtype=mm_dtype)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * out_rows, 8 * in_rows), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((in_rows, block_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, 32), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8 * 32, 32), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((32, 32), lambda i: (i % tpb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((out_rows, block_n), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((in_rows + out_rows, 1), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((out_rows, n), jnp.uint8),
            jax.ShapeDtypeStruct((in_rows + out_rows, n // block_n),
                                 jnp.uint32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * 8 * out_rows * 8 * in_rows * n
            + 2 * 8 * (in_rows + out_rows) * 32 * n,
            bytes_accessed=(in_rows + out_rows) * n,
            transcendentals=0,
        ),
        interpret=interpret,
    )(bmat_pm.astype(mm_dtype), shards, w0.astype(mm_dtype),
      planes_t.astype(mm_dtype), posmats_t.astype(mm_dtype))


def pad_to_block(n: int, block_n: int = BLOCK_N) -> int:
    return -(-n // block_n) * block_n


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:
        return False


class PallasCoder:
    """RS coder whose byte mixing runs in the fused Pallas kernel.

    Off-TPU (tests on the virtual CPU mesh) the kernel runs in interpreter
    mode unless `interpret=False` is forced.
    """

    def __init__(self, data_shards: int = 10, parity_shards: int = 4,
                 matrix_kind: str = "vandermonde",
                 interpret: bool | None = None,
                 block_n: int | None = None, mm: str | None = None,
                 codec=None):
        import os

        from ..codecs import get_codec, rs_codec
        from .coder_jax import plane_major

        self.block_n = block_n or int(
            os.environ.get("SEAWEEDFS_TPU_BLOCK_N", BLOCK_N))
        # int8 is the measured on-TPU winner (BENCH_r05: 22.5 GB/s
        # round-trip vs 21.0 for bf16) and exact for 0/1 bit planes
        # (int32 accumulation; correctness-gated vs NumpyCoder in
        # tests/test_ecpipe.py).  bf16 stays the off-TPU default.
        self.mm = mm or os.environ.get("SEAWEEDFS_TPU_MM") \
            or ("int8" if _on_tpu() else "bf16")
        self.codec = rs_codec(data_shards, parity_shards, matrix_kind) \
            if codec is None else get_codec(codec)
        self.data_shards = self.codec.data_shards
        self.parity_shards = self.codec.parity_shards
        self.total_shards = self.codec.total_shards
        self.matrix_kind = self.codec.matrix_kind
        self.interpret = (not _on_tpu()) if interpret is None else interpret
        self._plane_major = plane_major
        pb = self.codec.parity_bitmatrix()
        self._parity_pm = jnp.asarray(
            plane_major(pb, self.parity_shards, self.data_shards),
            jnp.bfloat16)

    def _apply(self, mat_pm: jax.Array, shards: jax.Array,
               out_rows: int) -> jax.Array:
        n = shards.shape[1]
        padded = pad_to_block(n, self.block_n)
        if padded != n:
            shards = jnp.pad(shards, ((0, 0), (0, padded - n)))
        # in_rows follows the stacked survivors, not the scheme: a
        # minimal-read LRC decode feeds 5 rows, not data_shards.
        out = apply_bitmatrix_pallas(mat_pm, shards, out_rows,
                                     int(shards.shape[0]),
                                     interpret=self.interpret,
                                     block_n=self.block_n, mm=self.mm)
        return out[:, :n]

    @property
    def fused_crc_ok(self) -> bool:
        """True when this coder can emit `.ecc` CRC32-C tile partials
        fused into the encode kernel (ops/crc_fold.py): the kernel tile
        must evenly divide the sidecar block."""
        return crc_fold.BLOCK % self.block_n == 0

    def encode_with_crc(self, data) -> tuple[jax.Array, jax.Array]:
        """Encode AND emit `.ecc` CRC tile partials in one fused kernel.

        Returns (parity (p, n) uint8, partials (k + p, padded_n //
        block_n) uint32) — rows ordered data shards then parity shards,
        exactly the shard-file order.  Feed the partials to
        crc_fold.FusedCrcAccumulator; `data` must start block-aligned
        in its shard files (the encoder's chunks do).
        """
        if not self.fused_crc_ok:
            raise ValueError(
                f"block_n {self.block_n} does not divide the .ecc "
                f"block {crc_fold.BLOCK}")
        data = jnp.asarray(data, jnp.uint8)
        if data.shape[0] != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} data shards, "
                f"got {data.shape[0]}")
        t = crc_fold.tables(self.block_n)
        consts = getattr(self, "_crc_consts", None)
        if consts is None:
            consts = self._crc_consts = (
                jnp.asarray(t.w0), jnp.asarray(t.planes_t),
                jnp.asarray(t.posmats_t))
        n = data.shape[1]
        padded = pad_to_block(n, self.block_n)
        if padded != n:
            data = jnp.pad(data, ((0, 0), (0, padded - n)))
        if not _prof_on():
            parity, partials = apply_bitmatrix_crc_pallas(
                self._parity_pm, data, *consts, self.parity_shards,
                self.data_shards, interpret=self.interpret,
                block_n=self.block_n, mm=self.mm)
            return parity[:, :n], partials
        # Execution-fenced wall (fencing audit: this leg used to
        # return unfenced async handles with no timing at all — a
        # dispatch-only wall would flatter the fused kernel).
        t0 = time.perf_counter()
        parity, partials = apply_bitmatrix_crc_pallas(
            self._parity_pm, data, *consts, self.parity_shards,
            self.data_shards, interpret=self.interpret,
            block_n=self.block_n, mm=self.mm)
        parity = jax.block_until_ready(parity)
        partials = jax.block_until_ready(partials)
        dt = time.perf_counter() - t0
        observe_ec_stage("encode_crc_kernel", dt, self.data_shards * n)
        if _roofline.ARMED:
            _record_roofline(
                "encode_crc_kernel", self,
                out_rows=self.parity_shards, in_rows=self.data_shards,
                n=int(n), crc=True, seconds=dt,
                measured_bytes=(self.data_shards
                                + self.parity_shards) * int(n))
        return parity[:, :n], partials

    def encode(self, data) -> jax.Array:
        data = jnp.asarray(data, jnp.uint8)
        if data.shape[0] != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} data shards, got {data.shape[0]}")
        if not _prof_on():
            return self._apply(self._parity_pm, data, self.parity_shards)
        t0 = time.perf_counter()
        out = jax.block_until_ready(
            self._apply(self._parity_pm, data, self.parity_shards))
        dt = time.perf_counter() - t0
        observe_ec_stage("encode_kernel", dt,
                         data.shape[0] * data.shape[1])
        if _roofline.ARMED:
            n = int(data.shape[1])
            _record_roofline(
                "encode_kernel", self, out_rows=self.parity_shards,
                in_rows=int(data.shape[0]), n=n, crc=False, seconds=dt,
                measured_bytes=(int(data.shape[0])
                                + self.parity_shards) * n)
        return out

    def encode_all(self, data) -> jax.Array:
        data = jnp.asarray(data, jnp.uint8)
        return jnp.concatenate([data, self.encode(data)], axis=0)

    @functools.lru_cache(maxsize=256)
    def _decode_mat_pm(self, present: tuple[int, ...], wanted: tuple[int, ...]):
        bmat, used = self.codec.decode_bitmatrix(present, wanted)
        pm = self._plane_major(np.asarray(bmat), len(wanted), len(used))
        return jnp.asarray(pm, jnp.bfloat16), used

    def reconstruct(self, shards: dict[int, jax.Array],
                    wanted: list[int] | None = None) -> dict[int, jax.Array]:
        present = tuple(sorted(shards))
        if wanted is None:
            wanted = [s for s in range(self.total_shards) if s not in shards]
        bad = [w for w in wanted if not 0 <= w < self.total_shards]
        if bad:
            raise ValueError(
                f"shard ids {bad} out of range [0, {self.total_shards})")
        if not wanted:
            return {}
        mat_pm, used = self._decode_mat_pm(present, tuple(wanted))
        stacked = jnp.stack([jnp.asarray(shards[s], jnp.uint8) for s in used])
        if not _prof_on():
            rec = self._apply(mat_pm, stacked, len(wanted))
            return {w: rec[i] for i, w in enumerate(wanted)}
        t0 = time.perf_counter()
        rec = jax.block_until_ready(
            self._apply(mat_pm, stacked, len(wanted)))
        dt = time.perf_counter() - t0
        observe_ec_stage("reconstruct_kernel", dt,
                         stacked.shape[0] * stacked.shape[1])
        if _roofline.ARMED:
            n = int(stacked.shape[1])
            _record_roofline(
                "reconstruct_kernel", self, out_rows=len(wanted),
                in_rows=int(stacked.shape[0]), n=n, crc=False,
                seconds=dt,
                measured_bytes=(int(stacked.shape[0])
                                + len(wanted)) * n)
        return {w: rec[i] for i, w in enumerate(wanted)}

    def verify(self, shards) -> bool:
        shards = jnp.asarray(shards, jnp.uint8)
        parity = self.encode(shards[: self.data_shards])
        return bool(jnp.array_equal(parity, shards[self.data_shards:]))
