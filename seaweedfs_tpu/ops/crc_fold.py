"""CRC32-C as GF(2) linear algebra — the math behind the fused-CRC kernel.

The `.ecc` sidecar (ec/integrity.py) wants one CRC32-C per 1MB block of
every shard file.  Computed on the CPU that is a full second pass over
bytes the encode kernel already had in VMEM.  This module turns the CRC
into the SAME kind of GF(2) matmul the RS parity already is, so the
Pallas kernel (ops/coder_pallas.py) and the mesh-batched jnp path
(parallel/sharded_codec.py) emit block checksums as a tiny second
output per tile — HBM traffic stays bytes-in + bytes-out and the
sidecar becomes free.

The algebra.  Write the table-driven register evolution of crc32c as
``step(x, m)`` (register x advanced over message m, WITHOUT the pre/post
inversions: ``step(x, m) = ~crc32c(m, ~x)``).  ``step`` is GF(2)-linear
in (x, m) jointly — CRC is polynomial remainder — so for a tile of T
bytes:

    step(0, tile) = sum_{c,s} bit_{s}(tile[c]) * S^(T-1-c)(E(2^s))

where S = advance-one-zero-byte (a 32x32 bit matrix) and E(v) =
step(0, [v]).  Three structural facts make this one matmul plus O(32^2)
fixups instead of a 32 x 8T monster:

1. E(2^(s+1)) = Sh(E(2^s)) for the fixed invertible map Sh =
   multiply-by-x^-1 mod P (verified at table-build time), so ONE weight
   table W0 (contribution of bit 0 per column) serves all 8 bit planes:
   the plane-s partial is folded through Sh^s afterwards.
2. Sh commutes with S (both are multiplications in GF(2)[x]/P), so the
   plane fold can run AFTER the column contraction.
3. Tiles chain linearly: the register after a full `.ecc` block of
   `tpb` tiles is sum_j P^(tpb-1-j)(q_j) with P = S^T, so a per-tile
   position matrix (selected by tile index mod tpb) turns per-tile
   partials into XOR-able per-block contributions.

The actual crc32c of a block is then CONST(block) ^ packed_bits, where
CONST(block) = crc32c of `block` zero bytes (the affine part the
inversions introduce).

Everything here is probed numerically from ``core.crc.crc32c`` — the
tables are correct by construction against the reference
implementation, whatever its bit conventions.
"""

from __future__ import annotations

import functools
import os
import threading

import numpy as np

from ..core.crc import CASTAGNOLI_POLY, crc32c
from ..ec import SMALL_BLOCK_SIZE

_MASK = 0xFFFFFFFF


def fused_crc_enabled() -> bool:
    """Whether the fused-CRC paths (local `write_ec_files`, batch
    encode, batch rebuild) are active.  `SEAWEEDFS_TPU_EC_FUSED_CRC`
    overrides in either direction (`0`/`false` reverts to the CPU byte
    accumulators end to end, `1` forces fused).  Unset, the default is
    platform-gated like the int8 mm choice (coder_pallas._on_tpu): ON
    where the matmul is free MXU work, OFF on the CPU backend where the
    bench measured the same einsum as costing more than the native
    crc32c pass it replaces (bench_e2e.py)."""
    env = os.environ.get("SEAWEEDFS_TPU_EC_FUSED_CRC")
    if env is not None:
        return env not in ("0", "false")
    from .coder_pallas import _on_tpu
    return _on_tpu()

# `.ecc` checksum granularity (ec/integrity.BLOCK re-derived here to
# avoid an import cycle; asserted equal in tests).
BLOCK = SMALL_BLOCK_SIZE

_ZERO1 = b"\x00"


def _step(x: int, m: bytes) -> int:
    """Raw register evolution: linear in (x, m), no pre/post inversion."""
    return _MASK ^ crc32c(m, _MASK ^ x)


def _bits32(v: int) -> np.ndarray:
    return np.array([(v >> o) & 1 for o in range(32)], dtype=np.uint8)


def _pack32(bits: np.ndarray) -> int:
    return int(sum(int(b) << o for o, b in enumerate(bits)))


def _mat_from_value_map(fn) -> np.ndarray:
    """32x32 bit matrix of a GF(2)-linear value map: column i = fn(2^i)."""
    m = np.zeros((32, 32), dtype=np.uint8)
    for i in range(32):
        m[:, i] = _bits32(fn(1 << i))
    return m


def _f_inv(y: int) -> int:
    """Inverse of the table recurrence f(r) = (r>>1) ^ (P if r&1) —
    multiply-by-x^-1 in the reflected register domain."""
    if (y >> 31) & 1:
        return (((y ^ CASTAGNOLI_POLY) << 1) | 1) & _MASK
    return (y << 1) & _MASK


def _matmul2(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


def _mat_pow(m: np.ndarray, e: int) -> np.ndarray:
    out = np.eye(32, dtype=np.uint8)
    base = m
    while e:
        if e & 1:
            out = _matmul2(out, base)
        base = _matmul2(base, base)
        e >>= 1
    return out


class CrcFoldTables:
    """All constants for one (tile_n, block) geometry.

    Attributes (numpy, ready to be cast to the kernel's matmul dtype):
      w0      (tile_n, 32) uint8 — bit-0 column contribution weights
      planes  (8, 32, 32)  uint8 — A_s = Sh^s, plane-fold matrices
      planes_t (256, 32)   uint8 — A_s transposed, stacked 2D for Pallas
      posmats (tpb, 32, 32) uint8 — P^(tpb-1-j), position-in-block shift
      posmats_t (tpb*32, 32) uint8 — transposed, stacked 2D for Pallas
      block_const  uint32  — crc32c of `block` zero bytes
      tpb     int          — tiles per `.ecc` block
    """

    def __init__(self, tile_n: int, block: int = BLOCK):
        if block % tile_n != 0:
            raise ValueError(
                f"crc tile {tile_n} must divide the .ecc block {block}")
        self.tile_n = tile_n
        self.block = block
        self.tpb = block // tile_n

        e1 = _step(0, b"\x01")
        smat = _mat_from_value_map(lambda v: _step(v, _ZERO1))
        shmat = _mat_from_value_map(_f_inv)
        # Structural checks (cheap, and they pin the two identities the
        # whole construction rests on to the reference implementation).
        v = e1
        for s in range(7):
            nxt = _step(0, bytes([1 << (s + 1)]))
            got = _pack32(_matmul2(shmat, _bits32(v).reshape(32, 1))[:, 0])
            if got != nxt:
                raise AssertionError("crc_fold: Sh(E(2^s)) != E(2^(s+1))")
            v = nxt
        if not np.array_equal(_matmul2(smat, shmat), _matmul2(shmat, smat)):
            raise AssertionError("crc_fold: S and Sh do not commute")

        # W0: contribution of bit 0 of the byte at tile offset c, i.e.
        # S^(T-1-c)(E(1)).  Built by walking the value backwards from
        # the last column — tile_n cheap 1-byte crc updates.
        w0 = np.zeros((tile_n, 32), dtype=np.uint8)
        val = e1
        for c in range(tile_n - 1, -1, -1):
            w0[c] = _bits32(val)
            val = _step(val, _ZERO1)
        self.w0 = w0

        planes = np.zeros((8, 32, 32), dtype=np.uint8)
        planes[0] = np.eye(32, dtype=np.uint8)
        for s in range(1, 8):
            planes[s] = _matmul2(shmat, planes[s - 1])
        self.planes = planes
        self.planes_t = np.concatenate(
            [planes[s].T for s in range(8)], axis=0)

        p_tile = _mat_pow(smat, tile_n)  # advance one whole tile
        posmats = np.zeros((self.tpb, 32, 32), dtype=np.uint8)
        posmats[self.tpb - 1] = np.eye(32, dtype=np.uint8)
        for j in range(self.tpb - 2, -1, -1):
            posmats[j] = _matmul2(p_tile, posmats[j + 1])
        self.posmats = posmats
        self.posmats_t = np.concatenate(
            [posmats[j].T for j in range(self.tpb)], axis=0)

        self.block_const = crc32c(b"\x00" * block) & _MASK


_TABLE_CACHE: dict = {}
_TABLE_LOCK = threading.Lock()


def tables(tile_n: int, block: int = BLOCK) -> CrcFoldTables:
    key = (tile_n, block)
    with _TABLE_LOCK:
        t = _TABLE_CACHE.get(key)
        if t is None:
            t = _TABLE_CACHE[key] = CrcFoldTables(tile_n, block)
        return t


# ---------------------------------------------------------------------------
# Reference (numpy) tile partials — the oracle the kernel is tested
# against, and the host-side fallback combiner's building block.
# ---------------------------------------------------------------------------

def tile_partials_np(rows: np.ndarray, tile_n: int,
                     block: int = BLOCK) -> np.ndarray:
    """(R, n) uint8 rows -> (R, n//tile_n) uint32 position-shifted tile
    partials (pure numpy; mirrors the kernel computation exactly).
    n must be a multiple of tile_n and the rows must start block-aligned.
    """
    t = tables(tile_n, block)
    r, n = rows.shape
    if n % tile_n:
        raise ValueError(f"width {n} not a multiple of tile {tile_n}")
    nt = n // tile_n
    x = rows.astype(np.int64)
    # plane-major bits, tiled: (8, R, nt, T)
    bits = np.stack([(x >> s) & 1 for s in range(8)]) \
        .reshape(8, r, nt, tile_n)
    # column contraction with the shared bit-0 weights
    u = np.einsum("srtc,co->srto", bits, t.w0.astype(np.int64))
    # plane fold: sum_s A_s @ u_s   (mod 2 once at the end — exact ints)
    v = np.einsum("srto,sio->rti", u, t.planes.astype(np.int64)) & 1
    # position shift within the .ecc block
    pos = t.posmats.astype(np.int64)
    nt_idx = np.arange(nt) % t.tpb
    shifted = np.einsum("rti,tio->rto", v, pos[nt_idx].transpose(0, 2, 1)
                        .astype(np.int64)) & 1
    weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))
    return (shifted.astype(np.uint64) * weights).sum(axis=2) \
        .astype(np.uint32)


def block_crcs_from_partials(partials: np.ndarray, width: int,
                             tile_n: int, block: int = BLOCK) -> list[int]:
    """Fold position-shifted tile partials of ONE row into actual
    crc32c values, one per full `.ecc` block.  `width` is the true byte
    width (must be a multiple of `block`); partials beyond it (zero
    padding) are ignored."""
    t = tables(tile_n, block)
    if width % block:
        raise ValueError(f"width {width} not a multiple of block {block}")
    nb = width // block
    use = np.asarray(partials[: nb * t.tpb], dtype=np.uint32) \
        .reshape(nb, t.tpb)
    lin = np.bitwise_xor.reduce(use, axis=1)
    return [int(v) ^ t.block_const for v in lin]


# ---------------------------------------------------------------------------
# jnp tile partials / per-block CRCs — fused into the mesh-batched
# encode/rebuild steps (parallel/sharded_codec.py).  Written with plain
# jnp so it traces inside jit / vmap / shard_map on any backend.
# ---------------------------------------------------------------------------

# Tile used by the jnp path (the Pallas kernel uses its own block_n as
# the tile).  8192 keeps the W0 constant small (8192x32) while leaving
# only 128 position fixups per 1MB block.
JNP_TILE = 8192


@functools.lru_cache(maxsize=8)
def _jnp_consts(tile_n: int, block: int):
    # Numpy constants, NOT jnp: block_crcs_jnp traces inside jit /
    # shard_map, and a device array materialized during one trace would
    # leak that trace's tracer through this cache.
    t = tables(tile_n, block)
    return (t.w0.astype(np.float32),
            t.planes.astype(np.float32),
            t.posmats.transpose(0, 2, 1).astype(np.float32),
            t.tpb, t.block_const)


def block_crcs_jnp(rows, tile_n: int = JNP_TILE, block: int = BLOCK):
    """(R, n) uint8 -> (R, n//block) uint32 of ACTUAL crc32c values per
    `.ecc` block, fully on device.  n must be a multiple of `block`
    and the rows must start block-aligned (zero-padded tail blocks
    simply yield the crc of a zero block — callers slice by true
    width)."""
    import jax.numpy as jnp
    w0, planes, posmats_t, tpb, const = _jnp_consts(tile_n, block)
    r = rows.shape[0]
    n = rows.shape[1]
    if n % block:
        raise ValueError(f"width {n} not a multiple of block {block}")
    nb = n // block
    x = rows.astype(jnp.int32)
    # Plane-at-a-time: materializing all 8 bit planes at once as f32
    # costs 32x the input bytes in one intermediate; looping bounds the
    # live intermediate at 4x (one plane) while staying mod-2-exact —
    # u_s counts <= tile_n and the per-plane fold is reduced &1 before
    # summing, exactly as the Pallas kernel does (mod-2 linearity makes
    # the reassociation free).
    fold = jnp.zeros((r, nb * tpb, 32), jnp.float32)
    for s in range(8):
        bits_s = ((x >> s) & 1).reshape(r, nb * tpb, tile_n) \
            .astype(jnp.float32)
        # column contraction (exact: counts <= tile_n < 2^24 in f32)
        u_s = jnp.einsum("rtc,co->rto", bits_s, w0)
        ub = (u_s.astype(jnp.int32) & 1).astype(jnp.float32)
        # plane fold contribution (counts <= 32 per term)
        fold = fold + jnp.einsum("rto,io->rti", ub, planes[s])
    v = (fold.astype(jnp.int32) & 1).astype(jnp.float32)
    # position shift + in-block XOR in one contraction
    v4 = v.reshape(r, nb, tpb, 32)
    blockbits = (jnp.einsum("rbji,jio->rbo", v4, posmats_t)
                 .astype(jnp.int32) & 1)
    weights = jnp.left_shift(
        jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
    packed = jnp.sum(blockbits.astype(jnp.uint32) * weights, axis=2,
                     dtype=jnp.uint32)
    return packed ^ jnp.uint32(const)


# ---------------------------------------------------------------------------
# Host-side streaming combiner — consumes kernel tile partials chunk by
# chunk (plus optional ragged byte tails) and emits the same list of
# per-block CRCs BlockCrcAccumulator would have produced.
# ---------------------------------------------------------------------------

class FusedCrcAccumulator:
    """Per-shard-row `.ecc` accumulator fed from kernel outputs.

    ``feed_tiles(partials, width)`` consumes position-shifted tile
    partials covering `width` bytes (width % block == 0, and the stream
    must be block-aligned — i.e. no byte tail pending).
    ``feed_bytes(buf)`` is the CPU fallback for ragged chunks/tails;
    both may be mixed as long as tile feeds land on block boundaries.
    ``finalize()`` matches BlockCrcAccumulator.finalize() bit for bit.
    """

    def __init__(self, tile_n: int, block: int = BLOCK):
        self.tile_n = tile_n
        self.block = block
        self._crcs: list[int] = []
        self._cur = 0
        self._fill = 0

    def feed_tiles(self, partials, width: int) -> None:
        if self._fill:
            raise ValueError(
                "feed_tiles on a non-block-aligned stream "
                f"(pending tail of {self._fill} bytes)")
        self._crcs.extend(block_crcs_from_partials(
            partials, width, self.tile_n, self.block))

    def feed_bytes(self, buf) -> None:
        mv = memoryview(buf)
        while len(mv):
            take = min(self.block - self._fill, len(mv))
            self._cur = crc32c(bytes(mv[:take]), self._cur)
            self._fill += take
            mv = mv[take:]
            if self._fill == self.block:
                self._crcs.append(self._cur)
                self._cur = 0
                self._fill = 0

    def finalize(self) -> list[int]:
        if self._fill:
            self._crcs.append(self._cur)
            self._cur = 0
            self._fill = 0
        return list(self._crcs)
