"""gRPC facade for the master: the reference's `Seaweed` service.

Reference: weed/server/master_grpc_server*.go + pb/master.proto.  Every
RPC bridges to the SAME handler/topology code the JSON/HTTP plane uses,
so the two planes can't drift; the gRPC port rides the reference's
convention of HTTP port + 10000 (pb/grpc_client_server.go
ParseServerToGrpcAddress).

Stubs are not generated (no grpcio-tools in the image): the service is
registered through grpc's generic-handler API with the protoc-generated
message classes, which is wire-identical.
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

from ..cluster import rpc as jrpc
from ..trace import tracer as _tracer
from . import master_pb2 as pb

GRPC_PORT_DELTA = 10_000  # grpc port = http port + 10000


def _begin_grpc_span(ctx, name: str):
    """Server span for one facade RPC: the gRPC plane bypasses the
    JsonHttpServer middleware, so the traceparent riding the invocation
    metadata (cluster/client._grpc_trace_metadata) is extracted here —
    the same contract as the HTTP header."""
    if not _tracer.recording_on():
        return None  # stock deployment: zero tracing cost (the HTTP
        #              middleware is gated the same way at setup)
    tp = ""
    try:
        for k, v in ctx.invocation_metadata() or ():
            if k == "traceparent":
                tp = v
                break
    except Exception:  # noqa: BLE001 — a trace must never fail an RPC
        pass
    return _tracer.begin_server_span("master", "GRPC", name, tp)


def _vinfo_dict(v: "pb.VolumeInformationMessage") -> dict:
    return {"id": v.id, "size": v.size, "collection": v.collection,
            "file_count": v.file_count, "delete_count": v.delete_count,
            "deleted_byte_count": v.deleted_byte_count,
            "read_only": v.read_only,
            "replica_placement": v.replica_placement,
            "version": v.version, "ttl": v.ttl,
            "compact_revision": v.compact_revision,
            "max_file_key": 0}


def _short_vinfo_dict(v) -> dict:
    return {"id": v.id, "collection": v.collection,
            "replica_placement": v.replica_placement,
            "version": v.version, "ttl": v.ttl}


class MasterGrpcServer:
    """Serves master_pb.Seaweed over a grpc.Server bridged to a
    MasterServer instance."""

    SERVICE = "master_pb.Seaweed"

    def __init__(self, master, host: str = "127.0.0.1",
                 port: int | None = None, max_workers: int = 16,
                 credentials=None):
        self.master = master
        self.port = port if port is not None \
            else master.server.port + GRPC_PORT_DELTA
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        unary = grpc.unary_unary_rpc_method_handler
        handlers = {
            "Assign": unary(
                self._assign,
                request_deserializer=pb.AssignRequest.FromString,
                response_serializer=pb.AssignResponse.SerializeToString),
            "LookupVolume": unary(
                self._lookup_volume,
                request_deserializer=pb.LookupVolumeRequest.FromString,
                response_serializer=(
                    pb.LookupVolumeResponse.SerializeToString)),
            "Statistics": unary(
                self._statistics,
                request_deserializer=pb.StatisticsRequest.FromString,
                response_serializer=(
                    pb.StatisticsResponse.SerializeToString)),
            "CollectionList": unary(
                self._collection_list,
                request_deserializer=pb.CollectionListRequest.FromString,
                response_serializer=(
                    pb.CollectionListResponse.SerializeToString)),
            "CollectionDelete": unary(
                self._collection_delete,
                request_deserializer=(
                    pb.CollectionDeleteRequest.FromString),
                response_serializer=(
                    pb.CollectionDeleteResponse.SerializeToString)),
            "VolumeList": unary(
                self._volume_list,
                request_deserializer=pb.VolumeListRequest.FromString,
                response_serializer=(
                    pb.VolumeListResponse.SerializeToString)),
            "LookupEcVolume": unary(
                self._lookup_ec_volume,
                request_deserializer=pb.LookupEcVolumeRequest.FromString,
                response_serializer=(
                    pb.LookupEcVolumeResponse.SerializeToString)),
            "GetMasterConfiguration": unary(
                self._get_configuration,
                request_deserializer=(
                    pb.GetMasterConfigurationRequest.FromString),
                response_serializer=(
                    pb.GetMasterConfigurationResponse.SerializeToString)),
            "ListMasterClients": unary(
                self._list_clients,
                request_deserializer=(
                    pb.ListMasterClientsRequest.FromString),
                response_serializer=(
                    pb.ListMasterClientsResponse.SerializeToString)),
            "LeaseAdminToken": unary(
                self._lease_admin_token,
                request_deserializer=pb.LeaseAdminTokenRequest.FromString,
                response_serializer=(
                    pb.LeaseAdminTokenResponse.SerializeToString)),
            "ReleaseAdminToken": unary(
                self._release_admin_token,
                request_deserializer=(
                    pb.ReleaseAdminTokenRequest.FromString),
                response_serializer=(
                    pb.ReleaseAdminTokenResponse.SerializeToString)),
            "SendHeartbeat": grpc.stream_stream_rpc_method_handler(
                self._send_heartbeat,
                request_deserializer=pb.Heartbeat.FromString,
                response_serializer=(
                    pb.HeartbeatResponse.SerializeToString)),
            "KeepConnected": grpc.stream_stream_rpc_method_handler(
                self._keep_connected,
                request_deserializer=pb.KeepConnectedRequest.FromString,
                response_serializer=pb.VolumeLocation.SerializeToString),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(self.SERVICE,
                                                  handlers),))
        if credentials is not None:
            bound = self._server.add_secure_port(
                f"{host}:{self.port}", credentials)
        else:
            bound = self._server.add_insecure_port(
                f"{host}:{self.port}")
        if bound == 0:
            raise OSError(
                f"gRPC bind failed on {host}:{self.port} (in use?)")
        self.port = bound
        self.host = host

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)

    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- unary bridges -------------------------------------------------------

    def _assign(self, req: "pb.AssignRequest", ctx):
        query = {"count": str(req.count or 1)}
        if req.collection:
            query["collection"] = req.collection
        if req.replication:
            query["replication"] = req.replication
        if req.ttl:
            query["ttl"] = req.ttl
        if req.data_center:
            query["dataCenter"] = req.data_center
        if req.rack:
            query["rack"] = req.rack
        if req.data_node:
            query["dataNode"] = req.data_node
        span = _begin_grpc_span(ctx, "/master_pb.Seaweed/Assign")
        status = 200  # in-message errors must not trace as "ok"
        try:
            out = self.master._assign(query, b"")
        except jrpc.RpcError as e:
            status = e.status
            return pb.AssignResponse(error=e.message)
        except BaseException:
            status = 500  # span MUST end: grpc worker threads are
            raise         # pooled, a leaked span mis-parents later RPCs
        finally:
            _tracer.end_server_span(span, status)
        return pb.AssignResponse(
            fid=out.get("fid", ""), url=out.get("url", ""),
            public_url=out.get("publicUrl", ""),
            count=out.get("count", 1), auth=out.get("auth", ""))

    def _lookup_volume(self, req: "pb.LookupVolumeRequest", ctx):
        span = _begin_grpc_span(ctx, "/master_pb.Seaweed/LookupVolume")
        status = 200
        try:
            return self._lookup_volume_inner(req)
        except BaseException:
            status = 500
            raise
        finally:
            _tracer.end_server_span(span, status)

    def _lookup_volume_inner(self, req: "pb.LookupVolumeRequest"):
        resp = pb.LookupVolumeResponse()
        for vid_str in req.volume_ids:
            entry = resp.volume_id_locations.add(volume_id=vid_str)
            try:
                out = self.master._lookup(
                    {"volumeId": vid_str,
                     "collection": req.collection}, b"")
            except jrpc.RpcError as e:
                entry.error = e.message
                continue
            except ValueError as e:  # malformed id: per-entry error,
                entry.error = str(e)  # never a transport failure
                continue
            for loc in out.get("locations", []):
                entry.locations.add(url=loc["url"],
                                    public_url=loc.get("publicUrl", ""))
            if not out.get("locations") and out.get("ecShards"):
                # EC-only volumes answer through LookupEcVolume; the
                # plain lookup mirrors the reference's error here.
                entry.error = "volume is erasure coded"
        return resp

    def _statistics(self, req: "pb.StatisticsRequest", ctx):
        topo = self.master.topo
        used = files = count = 0
        with topo._lock:
            for dn in topo.leaves():
                for v in dn.volumes.values():
                    if req.collection and \
                            v.collection != req.collection:
                        continue
                    used += v.size
                    files += v.file_count
                    count += 1
        return pb.StatisticsResponse(
            replication=req.replication, collection=req.collection,
            ttl=req.ttl,
            total_size=count * topo.volume_size_limit,
            used_size=used, file_count=files)

    def _collection_list(self, req, ctx):
        out = self.master._col_list({}, b"")
        resp = pb.CollectionListResponse()
        for name in out.get("collections", []):
            resp.collections.add(name=name)
        return resp

    def _collection_delete(self, req, ctx):
        try:
            self.master._col_delete({"collection": req.name}, b"")
        except jrpc.RpcError as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, e.message)
        return pb.CollectionDeleteResponse()

    def _volume_list(self, req, ctx):
        out = self.master._vol_list({}, b"")
        topo_pb = pb.TopologyInfo(id="topo")
        for dc in out["topology"]["data_centers"]:
            dc_pb = topo_pb.data_center_infos.add(id=dc["id"])
            for rack in dc["racks"]:
                rack_pb = dc_pb.rack_infos.add(id=rack["id"])
                for n in rack["nodes"]:
                    dn_pb = rack_pb.data_node_infos.add(
                        id=n["url"],
                        volume_count=len(n["volumes"]),
                        max_volume_count=n["max_volume_count"])
                    for v in n["volumes"]:
                        dn_pb.volume_infos.add(
                            id=v["id"], size=v["size"],
                            collection=v.get("collection", ""),
                            file_count=v["file_count"],
                            delete_count=v.get("delete_count", 0),
                            deleted_byte_count=v.get(
                                "deleted_byte_count", 0),
                            read_only=v.get("read_only", False),
                            replica_placement=v.get(
                                "replica_placement", 0),
                            version=v.get("version", 3),
                            ttl=v.get("ttl", 0),
                            compact_revision=v.get(
                                "compact_revision", 0))
                    for e in n["ec_shards"]:
                        dn_pb.ec_shard_infos.add(
                            id=e["id"], ec_index_bits=e["shard_bits"])
        return pb.VolumeListResponse(
            topology_info=topo_pb,
            volume_size_limit_mb=out["volume_size_limit"] >> 20)

    def _lookup_ec_volume(self, req, ctx):
        try:
            out = self.master._lookup(
                {"volumeId": str(req.volume_id)}, b"")
        except jrpc.RpcError as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, e.message)
        if not out.get("ecShards"):
            # A plain replicated volume answers through LookupVolume;
            # OK-but-empty here would read as "all shards lost".
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"ec volume {req.volume_id} not found")
        resp = pb.LookupEcVolumeResponse(volume_id=req.volume_id)
        for sid, locs in sorted(out.get("ecShards", {}).items(),
                                key=lambda kv: int(kv[0])):
            entry = resp.shard_id_locations.add(shard_id=int(sid))
            for loc in locs:
                entry.locations.add(url=loc["url"],
                                    public_url=loc.get("publicUrl", ""))
        return resp

    def _get_configuration(self, req, ctx):
        return pb.GetMasterConfigurationResponse(
            default_replication=self.master.default_replication,
            leader=self.master.leader_url())

    def _list_clients(self, req, ctx):
        # Watcher streams are anonymous on the JSON plane: an honest
        # empty list beats fabricated "addresses" a ported filer would
        # try (and fail) to dial.
        return pb.ListMasterClientsResponse(grpc_addresses=[])

    def _lease_admin_token(self, req, ctx):
        body = json.dumps({"name": req.lock_name or "shell",
                           "token": req.previous_token or None}).encode()
        try:
            out = self.master._admin_lease({}, body)
        except jrpc.RpcError as e:
            ctx.abort(grpc.StatusCode.ABORTED, e.message)
        return pb.LeaseAdminTokenResponse(token=out["token"],
                                          lock_ts_ns=0)

    def _release_admin_token(self, req, ctx):
        self.master._admin_release(
            {}, json.dumps({"token": req.previous_token}).encode())
        return pb.ReleaseAdminTokenResponse()

    # -- streaming bridges ---------------------------------------------------

    def _send_heartbeat(self, request_iterator, ctx):
        """Bidi heartbeat: each pb.Heartbeat maps onto the exact dict
        the HTTP /heartbeat route ingests, so a gRPC volume server and
        a JSON one register identically."""
        last_max = 0  # per-stream capacity memory
        for hb in request_iterator:
            doc = {"ip": hb.ip, "port": hb.port,
                   "public_url": hb.public_url,
                   "data_center": hb.data_center or "DefaultDataCenter",
                   "rack": hb.rack or "DefaultRack"}
            # proto3's absent-field 0 must neither register a node that
            # can never host volumes nor RESET a capacity an earlier
            # message on this stream established (an omitted key makes
            # the JSON handler apply its default of 7).
            if hb.max_volume_count > 0:
                last_max = hb.max_volume_count
            if last_max > 0:
                doc["max_volume_count"] = last_max
            if hb.volumes or hb.has_no_volumes:
                doc["volumes"] = [_vinfo_dict(v) for v in hb.volumes]
            if hb.new_volumes or hb.deleted_volumes:
                doc["new_volumes"] = [_short_vinfo_dict(v)
                                      for v in hb.new_volumes]
                doc["deleted_volumes"] = [_short_vinfo_dict(v)
                                          for v in hb.deleted_volumes]
            if hb.ec_shards or hb.has_no_ec_shards:
                doc["ec_shards"] = [
                    {"id": e.id, "collection": e.collection,
                     "shard_bits": e.ec_index_bits}
                    for e in hb.ec_shards]
            for field, key in ((hb.new_ec_shards, "new_ec_shards"),
                               (hb.deleted_ec_shards,
                                "deleted_ec_shards")):
                if field:
                    doc[key] = [
                        {"id": e.id, "collection": e.collection,
                         "shard_bits": e.ec_index_bits}
                        for e in field]
            out = self.master._heartbeat({}, json.dumps(doc).encode())
            yield pb.HeartbeatResponse(
                volume_size_limit=out.get(
                    "volume_size_limit",
                    self.master.topo.volume_size_limit),
                leader=out.get("leader") or "")

    def _keep_connected(self, request_iterator, ctx):
        """Location push: bridges the JSON plane's /cluster/watch
        EventStream into VolumeLocation messages."""
        try:
            _status, stream, _hdrs = self.master._cluster_watch({}, b"")
        except jrpc.RpcError as e:
            ctx.abort(grpc.StatusCode.UNAVAILABLE, e.message)
            return
        # Tight keepalive tick: after server.stop() cancels the RPC the
        # handler is parked in stream.read() until the next tick, and
        # grpc's non-daemon workers hold process exit for that long.
        stream.heartbeat = 1.0
        with stream:
            while ctx.is_active():
                line = stream.read()
                if line == b"":
                    return  # stream ended (deposed leader / overflow)
                if line.strip() == b"":
                    continue  # keepalive
                doc = json.loads(line)
                yield pb.VolumeLocation(
                    url=doc.get("url", ""),
                    public_url=doc.get("public_url", ""),
                    new_vids=doc.get("new_vids", []),
                    deleted_vids=doc.get("deleted_vids", []),
                    leader=doc.get("leader", ""))
