"""gRPC facade for the volume server: the reference's `VolumeServer`
maintenance service.

Reference: weed/server/volume_grpc_*.go + pb/volume_server.proto.
Bridges to the same Store/handler code the JSON admin plane uses; gRPC
port = HTTP port + 10000 like the other planes.
"""

from __future__ import annotations

import json
import os
import time
from concurrent import futures

import grpc

from ..cluster import rpc as jrpc
from ..core import types as t
from . import volume_server_pb2 as pb

GRPC_PORT_DELTA = 10_000
_CHUNK = 1 << 20


def _get_json_path(doc, path: str):
    """Dotted-path lookup into a parsed JSON doc (the gjson subset the
    Query RPC's selections use)."""
    cur = doc
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return None
    return cur


def _filter_match(doc, field: str, op: str, value: str) -> bool:
    """query/json/query_json.go filterJson semantics: missing field
    fails, empty operand means existence, strings compare lexically
    ('%'/'!%' are wildcard matches), numbers compare as float64."""
    if not field:
        return True  # no filter at all
    got = _get_json_path(doc, field)
    if got is None:
        return False
    if not op:
        return True
    if isinstance(got, bool):
        want = value.lower() == "true"
        if op == "=":
            return got is want
        if op == "!=":
            return got is not want
        return False
    if isinstance(got, (int, float)):
        try:
            num = float(value)
        except ValueError:
            return False
        return {"=": got == num, "!=": got != num, "<": got < num,
                "<=": got <= num, ">": got > num,
                ">=": got >= num}.get(op, False)
    if isinstance(got, str):
        if op in ("%", "!%"):
            import fnmatch
            hit = fnmatch.fnmatchcase(got, value)
            return hit if op == "%" else not hit
        return {"=": got == value, "!=": got != value,
                "<": got < value, "<=": got <= value,
                ">": got > value, ">=": got >= value}.get(op, False)
    return False


class VolumeGrpcServer:
    """Serves volume_server_pb.VolumeServer bridged to a VolumeServer
    instance (the JSON-plane object)."""

    SERVICE = "volume_server_pb.VolumeServer"

    def __init__(self, volume_server, host: str = "127.0.0.1",
                 port: int | None = None, max_workers: int = 16,
                 credentials=None):
        self.vs = volume_server
        self.port = port if port is not None \
            else volume_server.server.port + GRPC_PORT_DELTA
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        unary = grpc.unary_unary_rpc_method_handler
        stream_out = grpc.unary_stream_rpc_method_handler
        spec = {
            "BatchDelete": (self._batch_delete,
                            pb.BatchDeleteRequest,
                            pb.BatchDeleteResponse),
            "VacuumVolumeCheck": (self._vacuum_check,
                                  pb.VacuumVolumeCheckRequest,
                                  pb.VacuumVolumeCheckResponse),
            "VacuumVolumeCompact": (self._vacuum_compact,
                                    pb.VacuumVolumeCompactRequest,
                                    pb.VacuumVolumeCompactResponse),
            "VacuumVolumeCommit": (self._vacuum_commit,
                                   pb.VacuumVolumeCommitRequest,
                                   pb.VacuumVolumeCommitResponse),
            "VacuumVolumeCleanup": (self._vacuum_cleanup,
                                    pb.VacuumVolumeCleanupRequest,
                                    pb.VacuumVolumeCleanupResponse),
            "DeleteCollection": (self._delete_collection,
                                 pb.DeleteCollectionRequest,
                                 pb.DeleteCollectionResponse),
            "AllocateVolume": (self._allocate_volume,
                               pb.AllocateVolumeRequest,
                               pb.AllocateVolumeResponse),
            "VolumeSyncStatus": (self._sync_status,
                                 pb.VolumeSyncStatusRequest,
                                 pb.VolumeSyncStatusResponse),
            "VolumeMount": (self._mount, pb.VolumeMountRequest,
                            pb.VolumeMountResponse),
            "VolumeUnmount": (self._unmount, pb.VolumeUnmountRequest,
                              pb.VolumeUnmountResponse),
            "VolumeDelete": (self._delete, pb.VolumeDeleteRequest,
                             pb.VolumeDeleteResponse),
            "VolumeMarkReadonly": (self._mark_readonly,
                                   pb.VolumeMarkReadonlyRequest,
                                   pb.VolumeMarkReadonlyResponse),
            "VolumeMarkWritable": (self._mark_writable,
                                   pb.VolumeMarkWritableRequest,
                                   pb.VolumeMarkWritableResponse),
            "VolumeConfigure": (self._configure,
                                pb.VolumeConfigureRequest,
                                pb.VolumeConfigureResponse),
            "VolumeStatus": (self._status, pb.VolumeStatusRequest,
                             pb.VolumeStatusResponse),
            "VolumeCopy": (self._volume_copy, pb.VolumeCopyRequest,
                           pb.VolumeCopyResponse),
            "ReadVolumeFileStatus": (self._file_status,
                                     pb.ReadVolumeFileStatusRequest,
                                     pb.ReadVolumeFileStatusResponse),
            "VolumeEcShardsGenerate": (
                self._ec_generate, pb.VolumeEcShardsGenerateRequest,
                pb.VolumeEcShardsGenerateResponse),
            "VolumeEcShardsRebuild": (
                self._ec_rebuild, pb.VolumeEcShardsRebuildRequest,
                pb.VolumeEcShardsRebuildResponse),
            "VolumeEcShardsCopy": (
                self._ec_copy, pb.VolumeEcShardsCopyRequest,
                pb.VolumeEcShardsCopyResponse),
            "VolumeEcShardsDelete": (
                self._ec_delete, pb.VolumeEcShardsDeleteRequest,
                pb.VolumeEcShardsDeleteResponse),
            "VolumeEcShardsMount": (
                self._ec_mount, pb.VolumeEcShardsMountRequest,
                pb.VolumeEcShardsMountResponse),
            "VolumeEcShardsUnmount": (
                self._ec_unmount, pb.VolumeEcShardsUnmountRequest,
                pb.VolumeEcShardsUnmountResponse),
            "VolumeEcBlobDelete": (
                self._ec_blob_delete, pb.VolumeEcBlobDeleteRequest,
                pb.VolumeEcBlobDeleteResponse),
            "VolumeEcShardsToVolume": (
                self._ec_to_volume, pb.VolumeEcShardsToVolumeRequest,
                pb.VolumeEcShardsToVolumeResponse),
            "VolumeServerStatus": (self._server_status,
                                   pb.VolumeServerStatusRequest,
                                   pb.VolumeServerStatusResponse),
            "VolumeServerLeave": (self._leave,
                                  pb.VolumeServerLeaveRequest,
                                  pb.VolumeServerLeaveResponse),
            "VolumeNeedleStatus": (self._needle_status,
                                   pb.VolumeNeedleStatusRequest,
                                   pb.VolumeNeedleStatusResponse),
        }
        handlers = {
            name: unary(impl, request_deserializer=req.FromString,
                        response_serializer=resp.SerializeToString)
            for name, (impl, req, resp) in spec.items()
        }
        streams = {
            "Query": (self._query, pb.QueryRequest, pb.QueriedStripe),
            "CopyFile": (self._copy_file, pb.CopyFileRequest,
                         pb.CopyFileResponse),
            "VolumeIncrementalCopy": (
                self._incremental_copy, pb.VolumeIncrementalCopyRequest,
                pb.VolumeIncrementalCopyResponse),
            "VolumeTailSender": (self._tail_sender,
                                 pb.VolumeTailSenderRequest,
                                 pb.VolumeTailSenderResponse),
            "VolumeEcShardRead": (self._ec_shard_read,
                                  pb.VolumeEcShardReadRequest,
                                  pb.VolumeEcShardReadResponse),
            "VolumeTierMoveDatToRemote": (
                self._tier_to_remote,
                pb.VolumeTierMoveDatToRemoteRequest,
                pb.VolumeTierMoveDatToRemoteResponse),
            "VolumeTierMoveDatFromRemote": (
                self._tier_from_remote,
                pb.VolumeTierMoveDatFromRemoteRequest,
                pb.VolumeTierMoveDatFromRemoteResponse),
        }
        for name, (impl, req, resp) in streams.items():
            handlers[name] = stream_out(
                impl, request_deserializer=req.FromString,
                response_serializer=resp.SerializeToString)
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(self.SERVICE,
                                                  handlers),))
        if credentials is not None:
            bound = self._server.add_secure_port(
                f"{host}:{self.port}", credentials)
        else:
            bound = self._server.add_insecure_port(
                f"{host}:{self.port}")
        if bound == 0:
            raise OSError(
                f"gRPC bind failed on {host}:{self.port} (in use?)")
        self.port = bound
        self.host = host

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)

    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- helpers -------------------------------------------------------------

    def _volume_or_abort(self, vid: int, ctx):
        v = self.vs.store.find_volume(vid)
        if v is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"volume {vid} not on this server")
        return v

    def _call(self, handler, ctx, body: dict, query: dict | None = None):
        """Run a JSON-plane handler, mapping RpcError -> grpc status."""
        try:
            return handler(query or {},
                           json.dumps(body).encode())
        except jrpc.RpcError as e:
            code = {404: grpc.StatusCode.NOT_FOUND,
                    409: grpc.StatusCode.ALREADY_EXISTS,
                    400: grpc.StatusCode.INVALID_ARGUMENT,
                    403: grpc.StatusCode.PERMISSION_DENIED}.get(
                e.status, grpc.StatusCode.INTERNAL)
            ctx.abort(code, e.message)

    # -- needle / batch ops --------------------------------------------------

    def _batch_delete(self, req, ctx):
        resp = pb.BatchDeleteResponse()
        for fid in req.file_ids:
            r = resp.results.add(file_id=fid)
            try:
                vid, key, cookie = t.parse_file_id(fid)
                v = self.vs.store.find_volume(vid)
                if v is None:
                    r.status, r.error = 404, f"volume {vid} not here"
                    continue
                if not req.skip_cookie_check:
                    n = self.vs.store.read_needle(vid, key, cookie)
                    r.size = len(n.data)
                freed = self.vs.store.delete_needle(vid, key)
                r.status = 202
                r.size = r.size or freed
            except Exception as e:  # noqa: BLE001 — per-fid result
                r.status, r.error = 500, str(e)
        return resp

    def _needle_status(self, req, ctx):
        v = self._volume_or_abort(req.volume_id, ctx)
        hit = v.nm.get(req.needle_id)
        if hit is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"needle {req.needle_id:x} not found")
        _offset, size = hit
        return pb.VolumeNeedleStatusResponse(
            needle_id=req.needle_id, size=size,
            last_modified=int(v.last_modified),
            ttl=str(v.super_block.ttl))

    # -- vacuum 4-step -------------------------------------------------------

    def _vacuum_check(self, req, ctx):
        v = self._volume_or_abort(req.volume_id, ctx)
        return pb.VacuumVolumeCheckResponse(
            garbage_ratio=v.garbage_ratio())

    def _vacuum_compact(self, req, ctx):
        # Staging state + guard live on the Volume (storage/vacuum.py),
        # so compacts from the JSON admin plane or CLI serialize with
        # this one instead of interleaving .cpd/.cpx writes; re-running
        # compact replaces a stale staged snapshot like the reference.
        from ..storage.vacuum import compact
        v = self._volume_or_abort(req.volume_id, ctx)
        compact(v)
        return pb.VacuumVolumeCompactResponse()

    def _vacuum_commit(self, req, ctx):
        from ..storage.vacuum import VacuumError, commit_compact
        v = self._volume_or_abort(req.volume_id, ctx)
        try:
            commit_compact(v)
        except VacuumError as e:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return pb.VacuumVolumeCommitResponse(is_read_only=v.readonly)

    def _vacuum_cleanup(self, req, ctx):
        from ..storage.vacuum import cleanup_compact
        v = self._volume_or_abort(req.volume_id, ctx)
        cleanup_compact(v)
        return pb.VacuumVolumeCleanupResponse()

    # -- volume lifecycle ----------------------------------------------------

    def _delete_collection(self, req, ctx):
        for loc in self.vs.store.locations:
            for vid, v in list(loc.volumes.items()):
                if v.collection == req.collection:
                    self.vs.store.delete_volume(vid)
        self.vs._send_heartbeat(full=True)
        return pb.DeleteCollectionResponse()

    def _allocate_volume(self, req, ctx):
        self._call(self.vs._admin_assign_volume, ctx,
                   {"volume": req.volume_id,
                    "collection": req.collection,
                    "replication": req.replication or "000",
                    "ttl": req.ttl})
        return pb.AllocateVolumeResponse()

    def _sync_status(self, req, ctx):
        v = self._volume_or_abort(req.volume_id, ctx)
        base = v.file_name()
        idx_size = os.path.getsize(base + ".idx") \
            if os.path.exists(base + ".idx") else 0
        return pb.VolumeSyncStatusResponse(
            volume_id=req.volume_id, collection=v.collection,
            replication=str(v.super_block.replica_placement),
            ttl=str(v.super_block.ttl), tail_offset=v.dat_size(),
            compact_revision=v.super_block.compaction_revision,
            idx_file_size=idx_size)

    def _mount(self, req, ctx):
        self._call(self.vs._admin_mount, ctx,
                   {"volume": req.volume_id})
        return pb.VolumeMountResponse()

    def _unmount(self, req, ctx):
        self._call(self.vs._admin_unmount, ctx,
                   {"volume": req.volume_id})
        return pb.VolumeUnmountResponse()

    def _delete(self, req, ctx):
        self._call(self.vs._admin_delete_volume, ctx,
                   {"volume": req.volume_id})
        return pb.VolumeDeleteResponse()

    def _mark_readonly(self, req, ctx):
        self._call(self.vs._admin_readonly, ctx,
                   {"volume": req.volume_id, "readonly": True})
        return pb.VolumeMarkReadonlyResponse()

    def _mark_writable(self, req, ctx):
        self._call(self.vs._admin_readonly, ctx,
                   {"volume": req.volume_id, "readonly": False})
        return pb.VolumeMarkWritableResponse()

    def _configure(self, req, ctx):
        try:
            self.vs.store.configure_volume(req.volume_id,
                                           req.replication)
            self.vs._send_heartbeat(full=True)
        except Exception as e:  # noqa: BLE001 — error-in-message shape
            return pb.VolumeConfigureResponse(error=str(e))
        return pb.VolumeConfigureResponse()

    def _status(self, req, ctx):
        v = self._volume_or_abort(req.volume_id, ctx)
        return pb.VolumeStatusResponse(is_read_only=v.readonly)

    def _volume_copy(self, req, ctx):
        self._call(self.vs._copy_volume, ctx,
                   {"volume": req.volume_id,
                    "source": req.source_data_node,
                    "collection": req.collection})
        v = self.vs.store.find_volume(req.volume_id)
        return pb.VolumeCopyResponse(
            last_append_at_ns=int(v.last_modified * 1e9) if v else 0)

    def _file_status(self, req, ctx):
        v = self._volume_or_abort(req.volume_id, ctx)
        base = v.file_name()

        def _stat(ext):
            try:
                st = os.stat(base + ext)
                return int(st.st_mtime), st.st_size
            except OSError:
                return 0, 0
        idx_ts, idx_size = _stat(".idx")
        dat_ts, dat_size = _stat(".dat")
        return pb.ReadVolumeFileStatusResponse(
            volume_id=req.volume_id,
            idx_file_timestamp_seconds=idx_ts, idx_file_size=idx_size,
            dat_file_timestamp_seconds=dat_ts, dat_file_size=dat_size,
            file_count=v.file_count(),
            compaction_revision=v.super_block.compaction_revision,
            collection=v.collection)

    # -- bulk streams --------------------------------------------------------

    def _query(self, req, ctx):
        """The Query RPC (pb/volume_server.proto:92,
        server/volume_grpc_query.go): for each file id, read the
        needle, filter its JSON lines by (field operand value), project
        the selections, and stream one QueriedStripe per file whose
        records are concatenated `{sel:raw,...}` objects — the
        reference's json.ToJson shape, selection names unquoted and
        values raw, kept byte-identical for wire parity.  (The
        reference leaves CSVInput unimplemented in this RPC; CSV rides
        the HTTP /query plane here too.)"""
        import json as _json

        from ..core import types as t
        selections = list(req.selections)
        flt = (req.filter.field, req.filter.operand, req.filter.value)
        for fid in req.from_file_ids:
            try:
                vid, key, cookie = t.parse_file_id(fid)
            except ValueError as e:
                ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            v = self.vs.store.find_volume(vid)
            if v is None:
                ctx.abort(grpc.StatusCode.NOT_FOUND,
                          f"volume {vid} not on this server")
            try:
                n = self.vs.store.read_needle(vid, key, cookie)
            except Exception as e:  # noqa: BLE001 — not found / cookie
                ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))
            records = bytearray()
            for line in n.data.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = _json.loads(line)
                except ValueError:
                    continue
                if not _filter_match(doc, *flt):
                    continue
                records += b"{"
                for i, sel in enumerate(selections):
                    if i:
                        records += b","
                    records += sel.encode() + b":"
                    val = _get_json_path(doc, sel)
                    records += _json.dumps(
                        val, separators=(",", ":")).encode()
                records += b"}"
            yield pb.QueriedStripe(records=bytes(records))

    def _copy_file(self, req, ctx):
        if req.is_ec_volume:
            base = self.vs._volume_base(req.volume_id)
        else:
            v = self.vs.store.find_volume(req.volume_id)
            base = v.file_name() if v is not None \
                else self.vs._volume_base(req.volume_id)
        path = base + req.ext
        if not os.path.exists(path):
            if req.ignore_source_file_not_found:
                return
            ctx.abort(grpc.StatusCode.NOT_FOUND, f"{path} not found")
        stop = req.stop_offset or (1 << 62)
        sent = 0
        with open(path, "rb") as f:
            while sent < stop and ctx.is_active():
                piece = f.read(min(_CHUNK, stop - sent))
                if not piece:
                    return
                yield pb.CopyFileResponse(file_content=piece)
                sent += len(piece)

    def _incremental_copy(self, req, ctx):
        from ..storage.volume_backup import read_incremental
        v = self._volume_or_abort(req.volume_id, ctx)
        blob = read_incremental(v, req.since_ns)
        for i in range(0, len(blob), _CHUNK):
            if not ctx.is_active():
                return
            yield pb.VolumeIncrementalCopyResponse(
                file_content=blob[i:i + _CHUNK])

    def _tail_sender(self, req, ctx):
        from ..storage.volume_backup import read_incremental
        v = self._volume_or_abort(req.volume_id, ctx)
        blob = read_incremental(v, req.since_ns)
        # Raw appended records ride needle_body; a consumer appends
        # them verbatim (the JSON plane's /admin/volume_tail serves the
        # same byte stream).
        for i in range(0, len(blob), _CHUNK):
            if not ctx.is_active():
                return
            last = i + _CHUNK >= len(blob)
            yield pb.VolumeTailSenderResponse(
                needle_body=blob[i:i + _CHUNK], is_last_chunk=last)
        if not blob:
            yield pb.VolumeTailSenderResponse(is_last_chunk=True)

    # -- erasure coding ------------------------------------------------------

    def _ec_generate(self, req, ctx):
        self._call(self.vs._ec_generate, ctx,
                   {"volume": req.volume_id,
                    "collection": req.collection})
        return pb.VolumeEcShardsGenerateResponse()

    def _ec_rebuild(self, req, ctx):
        out = self._call(self.vs._ec_rebuild, ctx,
                         {"volume": req.volume_id})
        return pb.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=out.get("rebuilt_shards", []))

    def _ec_copy(self, req, ctx):
        self._call(self.vs._ec_copy_shard, ctx,
                   {"volume": req.volume_id,
                    "source": req.source_data_node,
                    "shards": list(req.shard_ids),
                    "copy_ecx": req.copy_ecx_file,
                    "copy_ecj": req.copy_ecj_file,
                    "copy_vif": req.copy_vif_file})
        return pb.VolumeEcShardsCopyResponse()

    def _ec_delete(self, req, ctx):
        self._call(self.vs._ec_delete_shards, ctx,
                   {"volume": req.volume_id,
                    "shards": list(req.shard_ids)})
        return pb.VolumeEcShardsDeleteResponse()

    def _ec_mount(self, req, ctx):
        self._call(self.vs._ec_mount, ctx, {"volume": req.volume_id})
        return pb.VolumeEcShardsMountResponse()

    def _ec_unmount(self, req, ctx):
        self._call(self.vs._ec_unmount, ctx, {"volume": req.volume_id})
        return pb.VolumeEcShardsUnmountResponse()

    def _ec_shard_read(self, req, ctx):
        ev = self.vs.ec_volumes.get(req.volume_id)
        if ev is None or req.shard_id not in ev.shards:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"shard {req.volume_id}.{req.shard_id} not here")
        shard = ev.shards[req.shard_id]
        remaining = req.size
        offset = req.offset
        while remaining > 0 and ctx.is_active():
            piece = shard.read_at(offset, min(_CHUNK, remaining))
            if not piece:
                return
            yield pb.VolumeEcShardReadResponse(data=piece)
            offset += len(piece)
            remaining -= len(piece)

    def _ec_blob_delete(self, req, ctx):
        ev = self.vs.ec_volumes.get(req.volume_id)
        if ev is None:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"ec volume {req.volume_id} not here")
        ev.delete_needle(req.file_key)
        return pb.VolumeEcBlobDeleteResponse()

    def _ec_to_volume(self, req, ctx):
        self._call(self.vs._ec_to_volume, ctx,
                   {"volume": req.volume_id,
                    "collection": req.collection})
        return pb.VolumeEcShardsToVolumeResponse()

    # -- tiering / status ----------------------------------------------------

    def _tier_to_remote(self, req, ctx):
        out = self._call(self.vs._tier_upload, ctx,
                         {"volume": req.volume_id,
                          "dest": req.destination_backend_name,
                          "keep_local": req.keep_local_dat_file})
        remote = out.get("remote", {})
        yield pb.VolumeTierMoveDatToRemoteResponse(
            processed=remote.get("file_size", 0),
            processedPercentage=100.0)

    def _tier_from_remote(self, req, ctx):
        self._call(self.vs._tier_download, ctx,
                   {"volume": req.volume_id,
                    "keep_remote": req.keep_remote_dat_file})
        v = self.vs.store.find_volume(req.volume_id)
        yield pb.VolumeTierMoveDatFromRemoteResponse(
            processed=v.dat_size() if v else 0,
            processedPercentage=100.0)

    def _server_status(self, req, ctx):
        from ..stats.sysstats import disk_status, memory_status
        resp = pb.VolumeServerStatusResponse()
        for loc in self.vs.store.locations:
            d = disk_status(loc.directory)
            resp.disk_statuses.add(
                dir=d["dir"], all=d["all"], used=d["used"],
                free=d["free"], percent_free=d["percent_free"],
                percent_used=d["percent_used"])
        m = memory_status()
        resp.memory_status.CopyFrom(pb.MemStatus(
            all=m.get("vms", 0), used=m.get("rss", 0),
            self=m.get("rss", 0)))
        return resp

    def _leave(self, req, ctx):
        self._call(self.vs._admin_leave, ctx, {})
        return pb.VolumeServerLeaveResponse()
