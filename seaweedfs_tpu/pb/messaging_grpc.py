"""gRPC facade for the messaging broker: the reference's
`SeaweedMessaging` service.

Reference: weed/messaging/broker/broker_grpc_server*.go +
pb/messaging.proto.  Bridges onto the same topic logs / consistent-hash
placement the HTTP plane uses; port = HTTP port + 10000.
"""

from __future__ import annotations

import json
import time
from concurrent import futures

import grpc

from ..cluster import rpc as jrpc
from . import messaging_pb2 as pb

GRPC_PORT_DELTA = 10_000


def _status_of(e: "jrpc.RpcError"):
    return {404: grpc.StatusCode.NOT_FOUND,
            409: grpc.StatusCode.ALREADY_EXISTS,
            400: grpc.StatusCode.INVALID_ARGUMENT}.get(
        e.status, grpc.StatusCode.INTERNAL)


class MessagingGrpcServer:
    """Serves messaging_pb.SeaweedMessaging bridged to a
    MessageBroker."""

    SERVICE = "messaging_pb.SeaweedMessaging"

    # Streams hold a worker for their whole life (unlike the
    # unary-dominated master/filer planes), so the pool must exceed the
    # expected live subscriber count or unary config RPCs starve.
    def __init__(self, broker, host: str = "127.0.0.1",
                 port: int | None = None, max_workers: int = 64,
                 credentials=None):
        self.broker = broker
        self.port = port if port is not None \
            else broker.server.port + GRPC_PORT_DELTA
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        unary = grpc.unary_unary_rpc_method_handler
        handlers = {
            "DeleteTopic": unary(
                self._delete_topic,
                request_deserializer=pb.DeleteTopicRequest.FromString,
                response_serializer=(
                    pb.DeleteTopicResponse.SerializeToString)),
            "ConfigureTopic": unary(
                self._configure_topic,
                request_deserializer=(
                    pb.ConfigureTopicRequest.FromString),
                response_serializer=(
                    pb.ConfigureTopicResponse.SerializeToString)),
            "GetTopicConfiguration": unary(
                self._get_configuration,
                request_deserializer=(
                    pb.GetTopicConfigurationRequest.FromString),
                response_serializer=(
                    pb.GetTopicConfigurationResponse.SerializeToString)),
            "FindBroker": unary(
                self._find_broker,
                request_deserializer=pb.FindBrokerRequest.FromString,
                response_serializer=(
                    pb.FindBrokerResponse.SerializeToString)),
            "Publish": grpc.stream_stream_rpc_method_handler(
                self._publish,
                request_deserializer=pb.PublishRequest.FromString,
                response_serializer=(
                    pb.PublishResponse.SerializeToString)),
            "Subscribe": grpc.stream_stream_rpc_method_handler(
                self._subscribe,
                request_deserializer=pb.SubscriberMessage.FromString,
                response_serializer=(
                    pb.BrokerMessage.SerializeToString)),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(self.SERVICE,
                                                  handlers),))
        if credentials is not None:
            bound = self._server.add_secure_port(
                f"{host}:{self.port}", credentials)
        else:
            bound = self._server.add_insecure_port(
                f"{host}:{self.port}")
        if bound == 0:
            raise OSError(
                f"gRPC bind failed on {host}:{self.port} (in use?)")
        self.port = bound
        self.host = host

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)

    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- topic config --------------------------------------------------------

    def _configure_topic(self, req, ctx):
        self._bridge(ctx, self.broker._configure, json.dumps(
            {"namespace": req.namespace, "topic": req.topic,
             "partition_count":
             req.configuration.partition_count or 4}).encode())
        return pb.ConfigureTopicResponse()

    def _bridge(self, ctx, handler, body: bytes):
        try:
            return handler({}, body)
        except jrpc.RpcError as e:
            ctx.abort(_status_of(e), e.message)

    def _get_configuration(self, req, ctx):
        try:
            cfg = self.broker._load_config(req.namespace, req.topic)
        except jrpc.RpcError as e:
            ctx.abort(_status_of(e), e.message)
        return pb.GetTopicConfigurationResponse(
            configuration=pb.TopicConfiguration(
                partition_count=cfg["partition_count"]))

    def _delete_topic(self, req, ctx):
        self._bridge(ctx, self.broker._delete_topic, json.dumps(
            {"namespace": req.namespace, "topic": req.topic}).encode())
        return pb.DeleteTopicResponse()

    def _find_broker(self, req, ctx):
        owner = self.broker._owner_of(req.namespace, req.topic,
                                      req.parition)
        return pb.FindBrokerResponse(broker=owner or self.broker.url())

    # -- streams -------------------------------------------------------------

    def _publish(self, request_iterator, ctx):
        """Bidi publish: init names the topic/partition, each data
        message appends to the partition log; wrong-owner partitions
        redirect (broker_grpc_server_publish.go)."""
        ns = topic = None
        partition = 0
        for req in request_iterator:
            if req.HasField("init"):
                ns, topic = req.init.namespace, req.init.topic
                partition = req.init.partition
                try:
                    cfg = self.broker._load_config(ns, topic)
                except jrpc.RpcError as e:
                    ctx.abort(_status_of(e), e.message)
                owner = self.broker._owner_of(ns, topic, partition)
                if owner and owner != self.broker.url():
                    yield pb.PublishResponse(
                        redirect=pb.PublishResponse.RedirectMessage(
                            new_broker=owner))
                    return
                yield pb.PublishResponse(
                    config=pb.PublishResponse.ConfigMessage(
                        partition_count=cfg["partition_count"]))
                continue
            if req.data.is_close:
                yield pb.PublishResponse(is_closed=True)
                return
            if ns is None:
                ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "publish before init")
            log = self.broker._log(ns, topic, partition)
            log.append(
                req.data.key.decode("utf-8", "surrogateescape"),
                bytes(req.data.value),
                {k: v.decode("utf-8", "surrogateescape")
                 for k, v in req.data.headers.items()} or None)

    def _subscribe(self, request_iterator, ctx):
        """Bidi subscribe: init picks the start position, then the
        stream polls the partition log and pushes messages; acks are
        accepted and ignored (the poll cursor is positional, like the
        HTTP plane's since_ns)."""
        init = None
        for req in request_iterator:
            if req.HasField("init"):
                init = req.init
                break
            if req.is_close:
                return
        if init is None:
            return
        # Keep draining the request stream in the background so a
        # client's is_close (or acks) are seen while we poll the log.
        import threading
        closed = threading.Event()

        def drain():
            try:
                for req2 in request_iterator:
                    if req2.is_close:
                        closed.set()
                        return
            except Exception:  # noqa: BLE001 — client gone
                closed.set()

        threading.Thread(target=drain, daemon=True).start()
        owner = self.broker._owner_of(init.namespace, init.topic,
                                      init.partition)
        if owner and owner != self.broker.url():
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      f"partition owned by {owner}")
        log = self.broker._log(init.namespace, init.topic,
                               init.partition)
        SP = pb.SubscriberMessage.InitMessage
        if init.startPosition == SP.EARLIEST:
            cursor = 0
        elif init.startPosition == SP.TIMESTAMP:
            cursor = init.timestampNs
        else:  # LATEST
            cursor = log.last_ts_ns()
        while ctx.is_active() and not closed.is_set():
            if log.last_ts_ns() <= cursor:
                # Idle guard: last_ts_ns is memoized, read_since is a
                # filer directory scan — never poll storage while the
                # partition has nothing new.
                time.sleep(0.05)
                continue
            msgs = log.read_since(cursor, 1000)
            if not msgs:
                time.sleep(0.05)
                continue
            for m in msgs:
                value = m["value"]
                if isinstance(value, str):
                    value = value.encode()
                elif not isinstance(value, (bytes, bytearray)):
                    # HTTP publishers may send any JSON value; bytes()
                    # would corrupt ints and crash on lists/dicts.
                    value = json.dumps(value).encode()
                key = m.get("key", "")
                out = pb.Message(
                    event_time_ns=m["ts_ns"],
                    key=key.encode("utf-8", "surrogateescape")
                    if isinstance(key, str) else bytes(key),
                    value=bytes(value))
                for hk, hv in (m.get("headers") or {}).items():
                    out.headers[hk] = hv.encode() \
                        if isinstance(hv, str) else bytes(hv)
                yield pb.BrokerMessage(data=out)
                cursor = m["ts_ns"]
