"""gRPC facade for the filer: the reference's `SeaweedFiler` service.

Reference: weed/server/filer_grpc_server*.go + pb/filer.proto.  Bridges
to the SAME Filer/FilerServer internals the HTTP plane uses; the gRPC
port rides HTTP port + 10000 like the master plane.
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

from ..filer.entry import Attributes, Entry, FileChunk
from ..filer.filer import FilerError, NotFound
from . import filer_pb2 as pb

GRPC_PORT_DELTA = 10_000


def _join(directory: str, name: str) -> str:
    return (directory.rstrip("/") + "/" + name) if name else \
        (directory or "/")


# -- Entry <-> pb conversion -------------------------------------------------

def entry_to_pb(e: Entry) -> "pb.Entry":
    a = e.attributes
    out = pb.Entry(
        name=e.name, is_directory=e.is_directory,
        attributes=pb.FuseAttributes(
            file_size=e.size(), mtime=int(a.mtime),
            file_mode=a.mode, uid=a.uid, gid=a.gid,
            crtime=int(a.crtime), mime=a.mime,
            replication=a.replication, collection=a.collection,
            ttl_sec=a.ttl_sec, user_name=a.user_name,
            group_name=list(a.group_names),
            symlink_target=a.symlink_target,
            md5=bytes.fromhex(a.md5) if a.md5 else b""),
        hard_link_id=e.hard_link_id.encode("utf-8", "surrogateescape"),
        hard_link_counter=e.hard_link_counter)
    for k, v in e.extended.items():
        out.extended[k] = v.encode() if isinstance(v, str) else v
    for c in e.chunks:
        out.chunks.append(pb.FileChunk(
            file_id=c.file_id, offset=c.offset, size=c.size,
            mtime=c.mtime, e_tag=c.etag,
            cipher_key=bytes.fromhex(c.cipher_key)
            if c.cipher_key else b"",
            is_chunk_manifest=c.is_chunk_manifest))
    return out


def entry_from_pb(directory: str, p: "pb.Entry") -> Entry:
    a = p.attributes
    attrs = Attributes(
        mtime=float(a.mtime), crtime=float(a.crtime),
        mode=a.file_mode or 0o660, uid=a.uid, gid=a.gid,
        mime=a.mime, ttl_sec=a.ttl_sec, user_name=a.user_name,
        group_names=list(a.group_name),
        symlink_target=a.symlink_target,
        md5=a.md5.hex() if a.md5 else "",
        replication=a.replication, collection=a.collection)
    chunks = [FileChunk(
        file_id=c.file_id, offset=c.offset, size=c.size,
        mtime=c.mtime, etag=c.e_tag,
        is_chunk_manifest=c.is_chunk_manifest,
        cipher_key=c.cipher_key.hex() if c.cipher_key else "")
        for c in p.chunks]
    return Entry(
        path=_join(directory, p.name), is_directory=p.is_directory,
        attributes=attrs, chunks=chunks,
        extended={k: v.decode("utf-8", "surrogateescape")
                  for k, v in p.extended.items()},
        hard_link_id=p.hard_link_id.decode("utf-8", "surrogateescape")
        if p.hard_link_id else "",
        hard_link_counter=p.hard_link_counter)


class FilerGrpcServer:
    """Serves filer_pb.SeaweedFiler over grpc bridged to a
    FilerServer."""

    SERVICE = "filer_pb.SeaweedFiler"

    def __init__(self, filer_server, host: str = "127.0.0.1",
                 port: int | None = None, max_workers: int = 16,
                 credentials=None):
        self.fs = filer_server
        self.port = port if port is not None \
            else filer_server.server.port + GRPC_PORT_DELTA
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        unary = grpc.unary_unary_rpc_method_handler
        stream_out = grpc.unary_stream_rpc_method_handler
        handlers = {
            "LookupDirectoryEntry": unary(
                self._lookup_entry,
                request_deserializer=(
                    pb.LookupDirectoryEntryRequest.FromString),
                response_serializer=(
                    pb.LookupDirectoryEntryResponse.SerializeToString)),
            "ListEntries": stream_out(
                self._list_entries,
                request_deserializer=pb.ListEntriesRequest.FromString,
                response_serializer=(
                    pb.ListEntriesResponse.SerializeToString)),
            "CreateEntry": unary(
                self._create_entry,
                request_deserializer=pb.CreateEntryRequest.FromString,
                response_serializer=(
                    pb.CreateEntryResponse.SerializeToString)),
            "UpdateEntry": unary(
                self._update_entry,
                request_deserializer=pb.UpdateEntryRequest.FromString,
                response_serializer=(
                    pb.UpdateEntryResponse.SerializeToString)),
            "AppendToEntry": unary(
                self._append_to_entry,
                request_deserializer=pb.AppendToEntryRequest.FromString,
                response_serializer=(
                    pb.AppendToEntryResponse.SerializeToString)),
            "DeleteEntry": unary(
                self._delete_entry,
                request_deserializer=pb.DeleteEntryRequest.FromString,
                response_serializer=(
                    pb.DeleteEntryResponse.SerializeToString)),
            "AtomicRenameEntry": unary(
                self._rename_entry,
                request_deserializer=(
                    pb.AtomicRenameEntryRequest.FromString),
                response_serializer=(
                    pb.AtomicRenameEntryResponse.SerializeToString)),
            "AssignVolume": unary(
                self._assign_volume,
                request_deserializer=pb.AssignVolumeRequest.FromString,
                response_serializer=(
                    pb.AssignVolumeResponse.SerializeToString)),
            "LookupVolume": unary(
                self._lookup_volume,
                request_deserializer=pb.LookupVolumeRequest.FromString,
                response_serializer=(
                    pb.LookupVolumeResponse.SerializeToString)),
            "CollectionList": unary(
                self._collection_list,
                request_deserializer=pb.CollectionListRequest.FromString,
                response_serializer=(
                    pb.CollectionListResponse.SerializeToString)),
            "DeleteCollection": unary(
                self._delete_collection,
                request_deserializer=(
                    pb.DeleteCollectionRequest.FromString),
                response_serializer=(
                    pb.DeleteCollectionResponse.SerializeToString)),
            "Statistics": unary(
                self._statistics,
                request_deserializer=pb.StatisticsRequest.FromString,
                response_serializer=(
                    pb.StatisticsResponse.SerializeToString)),
            "GetFilerConfiguration": unary(
                self._get_configuration,
                request_deserializer=(
                    pb.GetFilerConfigurationRequest.FromString),
                response_serializer=(
                    pb.GetFilerConfigurationResponse.SerializeToString)),
            "SubscribeMetadata": stream_out(
                self._subscribe_metadata,
                request_deserializer=(
                    pb.SubscribeMetadataRequest.FromString),
                response_serializer=(
                    pb.SubscribeMetadataResponse.SerializeToString)),
            "SubscribeLocalMetadata": stream_out(
                self._subscribe_metadata,
                request_deserializer=(
                    pb.SubscribeMetadataRequest.FromString),
                response_serializer=(
                    pb.SubscribeMetadataResponse.SerializeToString)),
            "KeepConnected": grpc.stream_stream_rpc_method_handler(
                self._keep_connected,
                request_deserializer=pb.KeepConnectedRequest.FromString,
                response_serializer=(
                    pb.KeepConnectedResponse.SerializeToString)),
            "LocateBroker": unary(
                self._locate_broker,
                request_deserializer=pb.LocateBrokerRequest.FromString,
                response_serializer=(
                    pb.LocateBrokerResponse.SerializeToString)),
            "KvGet": unary(
                self._kv_get,
                request_deserializer=pb.KvGetRequest.FromString,
                response_serializer=pb.KvGetResponse.SerializeToString),
            "KvPut": unary(
                self._kv_put,
                request_deserializer=pb.KvPutRequest.FromString,
                response_serializer=pb.KvPutResponse.SerializeToString),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(self.SERVICE,
                                                  handlers),))
        if credentials is not None:
            bound = self._server.add_secure_port(
                f"{host}:{self.port}", credentials)
        else:
            bound = self._server.add_insecure_port(
                f"{host}:{self.port}")
        if bound == 0:
            raise OSError(
                f"gRPC bind failed on {host}:{self.port} (in use?)")
        self.port = bound
        self.host = host

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)

    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    # -- entry CRUD ----------------------------------------------------------

    def _lookup_entry(self, req, ctx):
        try:
            e = self.fs.filer.find_entry(_join(req.directory, req.name))
        except NotFound:
            ctx.abort(grpc.StatusCode.NOT_FOUND,
                      f"{req.directory}/{req.name} not found")
        return pb.LookupDirectoryEntryResponse(entry=entry_to_pb(e))

    def _list_entries(self, req, ctx):
        last = req.startFromFileName
        inclusive = req.inclusiveStartFrom
        remaining = req.limit or (1 << 31)
        while remaining > 0 and ctx.is_active():
            page_size = min(remaining, 1024)
            page = self.fs.filer.list_entries(
                req.directory or "/", last, inclusive, page_size)
            if not page:
                return
            for e in page:
                if req.prefix and not e.name.startswith(req.prefix):
                    continue
                yield pb.ListEntriesResponse(entry=entry_to_pb(e))
                remaining -= 1
                if remaining <= 0:
                    return
            last, inclusive = page[-1].name, False
            if len(page) < page_size:
                return  # a SHORT page ends the directory — a full one
                # may hide prefix-filtered entries further on

    def _signed(self, signatures):
        return self.fs.filer.with_signatures(list(signatures)) \
            if signatures else _NullCtx()

    def _create_entry(self, req, ctx):
        entry = entry_from_pb(req.directory, req.entry)
        try:
            with self._signed(req.signatures):
                self.fs.filer.create_entry(entry, o_excl=req.o_excl)
        except FilerError as e:
            return pb.CreateEntryResponse(error=str(e))
        return pb.CreateEntryResponse()

    def _update_entry(self, req, ctx):
        entry = entry_from_pb(req.directory, req.entry)
        try:
            with self._signed(req.signatures):
                self.fs.filer.update_entry(entry)
        except (NotFound, FilerError) as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.UpdateEntryResponse()

    def _append_to_entry(self, req, ctx):
        path = _join(req.directory, req.entry_name)
        try:
            e = self.fs.filer.find_entry(path).clone()
        except NotFound:
            # First append creates the file, like the reference
            # (filer_grpc_server.go AppendToEntry on ErrNotFound).
            e = Entry(path=path, attributes=Attributes(mode=0o644))
        offset = e.size()
        for c in req.chunks:
            e.chunks.append(FileChunk(
                file_id=c.file_id, offset=offset, size=c.size,
                mtime=c.mtime, etag=c.e_tag,
                cipher_key=c.cipher_key.hex() if c.cipher_key else ""))
            offset += c.size
        self.fs.filer.create_entry(e)
        return pb.AppendToEntryResponse()

    def _delete_entry(self, req, ctx):
        path = _join(req.directory, req.name)
        try:
            with self._signed(req.signatures):
                self.fs.filer.delete_entry(
                    path, recursive=req.is_recursive,
                    delete_chunks=req.is_delete_data)
        except NotFound:
            return pb.DeleteEntryResponse()  # idempotent, like the ref
        except FilerError as e:
            if req.ignore_recursive_error:
                return pb.DeleteEntryResponse()
            return pb.DeleteEntryResponse(error=str(e))
        return pb.DeleteEntryResponse()

    def _rename_entry(self, req, ctx):
        try:
            self.fs.filer.rename(_join(req.old_directory, req.old_name),
                                 _join(req.new_directory, req.new_name))
        except NotFound as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except FilerError as e:
            ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return pb.AtomicRenameEntryResponse()

    # -- volume ops ----------------------------------------------------------

    def _assign_volume(self, req, ctx):
        from ..cluster import rpc as jrpc
        # TTL grammar has no seconds unit (volume_ttl.go m/h/d/w/M/y):
        # round seconds up to minutes like the reference SecondsToTTL.
        ttl = f"{-(-req.ttl_sec // 60)}m" if req.ttl_sec else ""
        try:
            out = self.fs.client.assign(
                count=req.count or 1, collection=req.collection,
                replication=req.replication or None, ttl=ttl,
                data_center=req.data_center)
        except jrpc.RpcError as e:
            return pb.AssignVolumeResponse(error=e.message)
        return pb.AssignVolumeResponse(
            file_id=out.get("fid", ""), url=out.get("url", ""),
            public_url=out.get("publicUrl", ""),
            count=out.get("count", 1), auth=out.get("auth", ""),
            collection=req.collection, replication=req.replication)

    def _lookup_volume(self, req, ctx):
        from ..cluster import rpc as jrpc
        resp = pb.LookupVolumeResponse()
        for vid_str in req.volume_ids:
            try:
                locs = self.fs.client.lookup(
                    int(vid_str.split(",")[0]), include_ec=True)
            except (jrpc.RpcError, ValueError):
                locs = []
            entry = resp.locations_map[vid_str]
            for loc in locs:
                entry.locations.add(
                    url=loc["url"],
                    public_url=loc.get("publicUrl", loc["url"]))
        return resp

    def _collection_list(self, req, ctx):
        out = self.fs.client._master_call("/col/list")
        resp = pb.CollectionListResponse()
        for name in out.get("collections", []):
            resp.collections.add(name=name)
        return resp

    def _delete_collection(self, req, ctx):
        from ..cluster import rpc as jrpc
        try:
            jrpc.call(f"{self.fs.client.master_url}/col/delete"
                      f"?collection={req.collection}", "POST")
        except jrpc.RpcError as e:
            if e.status != 404:
                ctx.abort(grpc.StatusCode.INTERNAL, e.message)
        return pb.DeleteCollectionResponse()

    def _statistics(self, req, ctx):
        # Aggregate from the master topology dump - the filer has
        # no volume state of its own (the reference filer proxies
        # its master the same way).
        used = files = count = 0
        limit = 0
        try:
            vl = self.fs.client._master_call("/vol/list")
            for dc in vl["topology"]["data_centers"]:
                for rack in dc["racks"]:
                    for n in rack["nodes"]:
                        for v in n["volumes"]:
                            if req.collection and \
                                    v.get("collection", "") != \
                                    req.collection:
                                continue
                            used += v["size"]
                            files += v["file_count"]
                            count += 1
            limit = vl.get("volume_size_limit", 0)
        except Exception:  # noqa: BLE001 - master down: zeros
            pass
        return pb.StatisticsResponse(
            replication=req.replication, collection=req.collection,
            ttl=req.ttl, total_size=count * limit, used_size=used,
            file_count=files)

    def _get_configuration(self, req, ctx):
        BUCKETS_PATH = "/buckets"  # filer_buckets.go DirBucketsPath
        return pb.GetFilerConfigurationResponse(
            masters=list(self.fs.client.masters),
            replication=self.fs.replication or "",
            collection=self.fs.collection,
            max_mb=self.fs.chunk_size >> 20,
            dir_buckets=BUCKETS_PATH,
            cipher=self.fs.cipher,
            signature=self.fs.filer.signature)

    # -- streams / misc ------------------------------------------------------

    def _subscribe_metadata(self, req, ctx):
        from ..filer.server import _MetaTail
        tail = _MetaTail(self.fs.filer, req.since_ns,
                         req.signature, req.path_prefix)
        buf = b""
        with tail:
            while ctx.is_active():
                piece = tail.read()
                if piece == b"":
                    return
                buf += piece
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    doc = json.loads(line)
                    if doc.get("_cursor_only"):
                        continue
                    ev = pb.EventNotification(
                        signatures=doc.get("signatures", []))
                    if doc.get("old_entry"):
                        old = Entry.from_dict(doc["old_entry"])
                        ev.old_entry.CopyFrom(entry_to_pb(old))
                    if doc.get("new_entry"):
                        new = Entry.from_dict(doc["new_entry"])
                        ev.new_entry.CopyFrom(entry_to_pb(new))
                    yield pb.SubscribeMetadataResponse(
                        directory=doc.get("directory", ""),
                        event_notification=ev,
                        ts_ns=doc.get("ts_ns", 0))

    def _keep_connected(self, request_iterator, ctx):
        for _req in request_iterator:
            yield pb.KeepConnectedResponse()

    def _locate_broker(self, req, ctx):
        # Broker placement lives in filer KV under the messaging
        # convention (messaging/broker consistent-hash registry).
        raw = self.fs.filer.store.kv_get(f"broker.{req.resource}")
        if raw:
            resp = pb.LocateBrokerResponse(found=True)
            resp.resources.add(grpc_addresses=raw.decode(),
                               resource_count=1)
            return resp
        return pb.LocateBrokerResponse(found=False)

    def _kv_get(self, req, ctx):
        value = self.fs.filer.store.kv_get(
            req.key.decode("utf-8", "surrogateescape"))
        if value is None:
            return pb.KvGetResponse(error="not found")
        return pb.KvGetResponse(value=value)

    def _kv_put(self, req, ctx):
        self.fs.filer.store.kv_put(
            req.key.decode("utf-8", "surrogateescape"), req.value)
        return pb.KvPutResponse()


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
