"""Wire-compatible gRPC control plane (reference: weed/pb/).

`master.proto` mirrors the reference's `Seaweed` service shapes so
`weed`-style gRPC clients port over; `master_grpc.MasterGrpcServer`
serves it as a facade over the same master internals the JSON/HTTP
plane uses.  Generated code (`master_pb2.py`) is checked in; regenerate
with `protoc --python_out=. master.proto` in this directory.
"""
