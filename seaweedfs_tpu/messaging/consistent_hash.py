"""Consistent-hash ring for topic-partition -> broker placement.

Reference: weed/messaging/broker/consistent_distribution.go (buraksezer/
consistent with xxhash there; a from-scratch virtual-node ring here).
Adding/removing a broker moves only ~1/n of the partitions.
"""

from __future__ import annotations

import bisect
import hashlib

VNODES = 64


def _hash(key: str) -> int:
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    def __init__(self, members: list[str] | None = None):
        self._ring: list[tuple[int, str]] = []
        self._keys: list[int] = []
        for m in members or []:
            self.add(m)

    def add(self, member: str) -> None:
        for i in range(VNODES):
            h = _hash(f"{member}#{i}")
            idx = bisect.bisect(self._keys, h)
            self._keys.insert(idx, h)
            self._ring.insert(idx, (h, member))

    def remove(self, member: str) -> None:
        keep = [(h, m) for h, m in self._ring if m != member]
        self._ring = keep
        self._keys = [h for h, _ in keep]

    def members(self) -> list[str]:
        return sorted({m for _, m in self._ring})

    def locate(self, key: str) -> str | None:
        if not self._ring:
            return None
        h = _hash(key)
        idx = bisect.bisect(self._keys, h) % len(self._ring)
        return self._ring[idx][1]
