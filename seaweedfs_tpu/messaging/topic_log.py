"""Per-partition message log: in-memory tail + filer segment files.

Reference: weed/messaging/broker/topic_manager.go (TopicControl wrapping
a util/log_buffer.LogBuffer) + broker_grpc_server_subscribe.go (replay
persisted filer files, then tail the live buffer).  Segments live in
the filer at /topics/<ns>/<topic>/<partition>/<first_ts>.seg as JSONL,
so any broker (or a restarted one) can replay history — the filer IS
the durable log.
"""

from __future__ import annotations

import base64
import json
import threading
import time

from ..filer.client import FilerProxy

FLUSH_BYTES = 1 << 20
FLUSH_SECONDS = 2.0


def partition_dir(namespace: str, topic: str, partition: int) -> str:
    return f"/topics/{namespace}/{topic}/{partition:04d}"


def encode_message(m: dict) -> dict:
    out = dict(m)
    if isinstance(out.get("value"), (bytes, bytearray)):
        out["value"] = base64.b64encode(bytes(out["value"])).decode()
        out["value_b64"] = True
    return out


def decode_message(m: dict) -> dict:
    out = dict(m)
    if out.pop("value_b64", False):
        out["value"] = base64.b64decode(out["value"])
    return out


class TopicPartitionLog:
    """One partition's log on one broker."""

    def __init__(self, filer: FilerProxy, namespace: str, topic: str,
                 partition: int, flush_bytes: int = FLUSH_BYTES,
                 flush_seconds: float = FLUSH_SECONDS):
        self.filer = filer
        self.dir = partition_dir(namespace, topic, partition)
        self.flush_bytes = flush_bytes
        self.flush_seconds = flush_seconds
        self._tail: list[dict] = []  # encoded messages, ts order
        self._tail_bytes = 0
        self._last_flush = time.monotonic()
        self._lock = threading.RLock()
        self._last_ts = 0
        self._history_scanned = False

    # -- write ---------------------------------------------------------------

    def append(self, key: str, value, headers: dict | None = None) -> int:
        with self._lock:
            ts = max(time.time_ns(), self._last_ts + 1)  # strictly
            self._last_ts = ts                           # increasing
            m = encode_message({"ts_ns": ts, "key": key, "value": value,
                                "headers": headers or {}})
            line = json.dumps(m, separators=(",", ":"))
            self._tail.append(m)
            self._tail_bytes += len(line)
            if self._tail_bytes >= self.flush_bytes or \
                    time.monotonic() - self._last_flush \
                    >= self.flush_seconds:
                self._flush_locked()
            return ts

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def maybe_flush(self) -> None:
        """Background-flusher entry: persist a tail that has aged past
        flush_seconds (appends alone only flush on the next append, so
        a quiet partition would otherwise hold its tail forever)."""
        with self._lock:
            if self._tail and time.monotonic() - self._last_flush \
                    >= self.flush_seconds:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._tail:
            self._last_flush = time.monotonic()
            return
        first_ts = self._tail[0]["ts_ns"]
        body = "\n".join(json.dumps(m, separators=(",", ":"))
                         for m in self._tail).encode() + b"\n"
        self.filer.put(f"{self.dir}/{first_ts:020d}.seg", body,
                       "application/x-ndjson")
        self._tail = []
        self._tail_bytes = 0
        self._last_flush = time.monotonic()

    # -- read ----------------------------------------------------------------

    def read_since(self, since_ns: int, limit: int = 1000) -> list[dict]:
        """Messages with ts_ns > since_ns: persisted segments first,
        then the in-memory tail (replay-then-tail)."""
        with self._lock:
            tail = list(self._tail)
        tail_first = tail[0]["ts_ns"] if tail else None
        out: list[dict] = []
        segs = sorted(e["name"] for e in self.filer.list_all(self.dir)
                      if e["name"].endswith(".seg"))
        # Skip whole segments older than since_ns via the next segment's
        # first-ts filename (same trick as the filer meta log).
        keep = []
        for i, name in enumerate(segs):
            nxt = int(segs[i + 1].split(".")[0]) if i + 1 < len(segs) \
                else None
            if nxt is None or nxt > since_ns:
                keep.append(name)
        for name in keep:
            with self.filer.get(f"{self.dir}/{name}") as resp:
                for raw in resp.read().splitlines():
                    if not raw.strip():
                        continue
                    m = json.loads(raw)
                    if m["ts_ns"] <= since_ns:
                        continue
                    if tail_first is not None and \
                            m["ts_ns"] >= tail_first:
                        break  # covered by the in-memory tail
                    out.append(decode_message(m))
                    if len(out) >= limit:
                        return out
        for m in tail:
            if m["ts_ns"] > since_ns:
                out.append(decode_message(m))
                if len(out) >= limit:
                    break
        return out

    def last_ts_ns(self) -> int:
        with self._lock:
            if self._last_ts or self._history_scanned:
                return self._last_ts
        # Cold partition (fresh broker): one full replay, memoized so
        # subscriber polls don't rescan every segment per request —
        # the scanned flag also memoizes the empty-partition answer.
        msgs = self.read_since(0, limit=1 << 30)
        last = msgs[-1]["ts_ns"] if msgs else 0
        with self._lock:
            self._history_scanned = True
            self._last_ts = max(self._last_ts, last)
            return self._last_ts
