"""Messaging client: publisher/subscriber following broker redirects.

Reference: weed/messaging/msgclient/ — producers and consumers locate
the owning broker per (topic, partition) via FindBroker and follow
redirects when placement moves.
"""

from __future__ import annotations

import base64
import json
import time
from typing import Callable

from ..cluster import rpc


class MessagingClient:
    def __init__(self, broker_url: str):
        self.broker_url = broker_url.rstrip("/")

    # -- admin ---------------------------------------------------------------

    def configure_topic(self, namespace: str, topic: str,
                        partition_count: int = 4) -> dict:
        return rpc.call_json(
            self.broker_url + "/topics/configure",
            payload={"namespace": namespace, "topic": topic,
                     "partition_count": partition_count})

    def delete_topic(self, namespace: str, topic: str) -> dict:
        return rpc.call_json(self.broker_url + "/topics/delete",
                             payload={"namespace": namespace,
                                      "topic": topic})

    def topic_config(self, namespace: str, topic: str) -> dict:
        return rpc.call(self.broker_url + "/topics/config"
                        f"?namespace={namespace}&topic={topic}")

    # -- produce -------------------------------------------------------------

    def publish(self, namespace: str, topic: str, value,
                key: str = "", headers: dict | None = None) -> dict:
        payload = {"namespace": namespace, "topic": topic, "key": key,
                   "headers": headers or {}}
        if isinstance(value, (bytes, bytearray)):
            payload["value"] = base64.b64encode(bytes(value)).decode()
            payload["value_b64"] = True
        else:
            payload["value"] = value
        url = self.broker_url
        for _hop in range(3):  # follow placement redirects
            try:
                out = rpc.call_json(url + "/publish", payload=payload)
            except OSError:
                # Redirect target died but its registration hasn't
                # expired yet: retryable until the ring re-forms.
                raise rpc.RpcError(
                    503, f"partition owner {url} unreachable; "
                    f"retry after placement settles") from None
            if "redirect" not in out:
                return out
            # Pin the partition the redirecting broker chose: keyless
            # publishes roll a random partition per broker, so without
            # this the next hop can re-roll and bounce us back.
            payload["partition"] = out["partition"]
            url = out["redirect"].rstrip("/")
        raise rpc.RpcError(503, "publish redirect loop")

    # -- consume -------------------------------------------------------------

    def fetch(self, namespace: str, topic: str, partition: int,
              since_ns: int = 0, limit: int = 1000) -> dict:
        url = self.broker_url
        for _hop in range(3):
            try:
                out = rpc.call(
                    url + f"/subscribe?namespace={namespace}"
                    f"&topic={topic}&partition={partition}"
                    f"&since_ns={since_ns}&limit={limit}")
            except OSError:
                raise rpc.RpcError(
                    503, f"partition owner {url} unreachable; "
                    f"retry after placement settles") from None
            if "redirect" not in out:
                for m in out["messages"]:
                    if m.pop("value_b64", False):
                        m["value"] = base64.b64decode(m["value"])
                return out
            url = out["redirect"].rstrip("/")
        raise rpc.RpcError(503, "subscribe redirect loop")

    def subscribe(self, namespace: str, topic: str, partition: int,
                  fn: Callable[[dict], None], since_ns: int = 0,
                  poll_interval: float = 0.2,
                  stop_check: Callable[[], bool] | None = None) -> None:
        """Poll-tail one partition, invoking fn per message (blocking;
        the streaming Subscribe RPC as a poll loop)."""
        offset = since_ns
        while stop_check is None or not stop_check():
            out = self.fetch(namespace, topic, partition, offset)
            for m in out["messages"]:
                fn(m)
            new_off = out.get("last_ns", offset)
            if new_off <= offset:
                time.sleep(poll_interval)
            offset = new_off
