"""Pub/sub messaging on filer infrastructure (`weed msg.broker`).

Reference: weed/messaging/broker/ — brokers expose publish/subscribe
streams; each topic is partitioned, every partition is an append-only
log living *in the filer* (in-memory LogBuffer tail + flushed segment
files under /topics/<namespace>/<topic>/<partition>/), and topic
partitions map to brokers by consistent hashing
(consistent_distribution.go).
"""

from .broker import MessageBroker  # noqa: F401
from .client import MessagingClient  # noqa: F401
from .consistent_hash import HashRing  # noqa: F401
from .topic_log import TopicPartitionLog  # noqa: F401
