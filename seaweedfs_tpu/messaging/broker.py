"""Message broker server.

Reference: weed/messaging/broker/broker_server.go (MessageBroker),
broker_grpc_server_publish.go / _subscribe.go (the 6 SeaweedMessaging
RPCs, pb/messaging.proto:11-29), topic_manager.go.  Broker liveness
rides the filer: each broker registers itself under
/topics/.system/brokers/ and FindBroker consistent-hashes the live set.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time

from ..cluster import rpc
from ..filer.client import FilerProxy
from .consistent_hash import HashRing
from .topic_log import TopicPartitionLog, partition_dir

BROKER_DIR = "/topics/.system/brokers"
LIVENESS_TTL = 10.0


class MessageBroker:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 0, register_interval: float = 3.0,
                 ssl_context=None):
        self.filer = FilerProxy(filer_url)
        self.server = rpc.JsonHttpServer(host, port,
                                         ssl_context=ssl_context)
        self.register_interval = register_interval
        self._logs: dict[tuple[str, str, int], TopicPartitionLog] = {}
        self._lock = threading.Lock()
        # Hot-path caches: publish/fetch would otherwise hit the filer
        # N+1 times per message (config GET + registry list + one GET
        # per broker).  Both TTLs sit well under LIVENESS_TTL.
        self._config_cache: dict[tuple[str, str],
                                 tuple[float, dict]] = {}
        self._ring_cache: tuple[float, dict[str, str]] | None = None
        self._stop = threading.Event()
        s = self.server
        s.route("POST", "/topics/configure", self._configure)
        s.route("POST", "/topics/delete", self._delete_topic)
        s.route("GET", "/topics/config", self._get_config)
        s.route("POST", "/publish", self._publish)
        s.route("GET", "/subscribe", self._subscribe)
        s.route("GET", "/find_broker", self._find_broker)
        s.route("GET", "/status", self._status)
        self._register_thread = threading.Thread(
            target=self._register_loop, daemon=True,
            name="broker-register")
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True, name="broker-flush")

    # -- lifecycle -----------------------------------------------------------

    def _flush_loop(self) -> None:
        """Persist aged partition tails (log_buffer's interval flush)."""
        while not self._stop.wait(1.0):
            with self._lock:
                logs = list(self._logs.values())
            for log in logs:
                try:
                    log.maybe_flush()
                except Exception:  # noqa: BLE001 — filer hiccup; the
                    pass           # tail stays buffered for next tick

    def start(self) -> None:
        self.server.start()
        self._register_once()
        self._register_thread.start()
        self._flush_thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            logs = list(self._logs.values())
        for log in logs:
            try:
                log.flush()
            except Exception:  # noqa: BLE001 — shutdown best effort
                pass
        try:
            self.filer.delete(f"{BROKER_DIR}/{self._id()}")
        except Exception:  # noqa: BLE001
            pass
        self.server.stop()

    def url(self) -> str:
        return self.server.url()

    def _id(self) -> str:
        return f"{self.server.host}:{self.server.port}"

    # -- broker registry (LocateBroker / KeepConnected analog) ---------------

    def _register_once(self) -> None:
        self.filer.put(f"{BROKER_DIR}/{self._id()}",
                       json.dumps({"url": self.url(),
                                   "ts": time.time()}).encode(),
                       "application/json")

    def _register_loop(self) -> None:
        while not self._stop.wait(self.register_interval):
            try:
                self._register_once()
            except Exception:  # noqa: BLE001 — filer down; retry
                pass

    def live_brokers(self) -> dict[str, str]:
        """id -> url for brokers whose registration is fresh (cached
        ~1s so placement checks stay off the filer hot path)."""
        with self._lock:
            cached = self._ring_cache
        if cached is not None and time.monotonic() - cached[0] < 1.0:
            return cached[1]
        out = self._scan_live_brokers()
        with self._lock:
            self._ring_cache = (time.monotonic(), out)
        return out

    def _scan_live_brokers(self) -> dict[str, str]:
        out: dict[str, str] = {}
        now = time.time()
        for e in self.filer.list_all(BROKER_DIR):
            try:
                with self.filer.get(f"{BROKER_DIR}/{e['name']}") as r:
                    d = json.loads(r.read())
                if now - d.get("ts", 0) <= LIVENESS_TTL:
                    out[e["name"]] = d["url"]
            except Exception:  # noqa: BLE001 — racing dereg
                continue
        return out

    # -- topic config (ConfigureTopic / GetTopicConfiguration) ---------------

    @staticmethod
    def _config_path(namespace: str, topic: str) -> str:
        return f"/topics/{namespace}/{topic}/.config"

    def _configure(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        ns, topic = req["namespace"], req["topic"]
        cfg = {"namespace": ns, "topic": topic,
               "partition_count": int(req.get("partition_count", 4))}
        self.filer.put(self._config_path(ns, topic),
                       json.dumps(cfg).encode(), "application/json")
        with self._lock:
            self._config_cache.pop((ns, topic), None)
        return cfg

    def _load_config(self, ns: str, topic: str) -> dict:
        with self._lock:
            hit = self._config_cache.get((ns, topic))
        if hit is not None and time.monotonic() - hit[0] < 5.0:
            return hit[1]
        try:
            with self.filer.get(self._config_path(ns, topic)) as r:
                cfg = json.loads(r.read())
        except Exception:  # noqa: BLE001
            raise rpc.RpcError(
                404, f"topic {ns}/{topic} not configured") from None
        with self._lock:
            self._config_cache[(ns, topic)] = (time.monotonic(), cfg)
        return cfg

    def _get_config(self, query: dict, body: bytes) -> dict:
        return self._load_config(query["namespace"], query["topic"])

    def _delete_topic(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        ns, topic = req["namespace"], req["topic"]
        with self._lock:
            for key in [k for k in self._logs
                        if k[0] == ns and k[1] == topic]:
                del self._logs[key]
            self._config_cache.pop((ns, topic), None)
        self.filer.delete(f"/topics/{ns}/{topic}", recursive=True)
        return {"deleted": f"{ns}/{topic}"}

    # -- publish / subscribe -------------------------------------------------

    def _log(self, ns: str, topic: str, partition: int
             ) -> TopicPartitionLog:
        with self._lock:
            key = (ns, topic, partition)
            log = self._logs.get(key)
            if log is None:
                log = TopicPartitionLog(self.filer, ns, topic, partition)
                self._logs[key] = log
            return log

    def _partition_of(self, key: str, count: int) -> int:
        if not key:
            return int(time.time_ns()) % count
        return int.from_bytes(
            hashlib.md5(key.encode()).digest()[:4], "big") % count

    def _publish(self, query: dict, body: bytes) -> dict:
        req = json.loads(body)
        ns, topic = req["namespace"], req["topic"]
        cfg = self._load_config(ns, topic)
        key = req.get("key", "")
        partition = req.get("partition")
        if partition is None:
            partition = self._partition_of(key,
                                           cfg["partition_count"])
        owner = self._owner_of(ns, topic, partition)
        if owner and owner != self.url():
            # Not this broker's partition: redirect the producer
            # (broker_grpc_server_publish.go redirects via broker list).
            return {"redirect": owner, "partition": partition}
        value = req.get("value", "")
        if req.get("value_b64"):
            import base64
            value = base64.b64decode(value)
        ts = self._log(ns, topic, partition).append(
            key, value, req.get("headers"))
        return {"partition": partition, "ts_ns": ts}

    def _subscribe(self, query: dict, body: bytes) -> dict:
        ns, topic = query["namespace"], query["topic"]
        partition = int(query.get("partition", 0))
        since = int(query.get("since_ns", 0))
        limit = int(query.get("limit", 1000))
        owner = self._owner_of(ns, topic, partition)
        if owner and owner != self.url():
            return {"redirect": owner, "partition": partition}
        log = self._log(ns, topic, partition)
        # Snapshot the head BEFORE scanning: a message appended mid-scan
        # must not advance the cursor past itself unseen.
        head = log.last_ts_ns()
        msgs = log.read_since(since, limit)
        out = []
        for m in msgs:
            v = m["value"]
            if isinstance(v, (bytes, bytearray)):
                import base64
                m = dict(m)
                m["value"] = base64.b64encode(bytes(v)).decode()
                m["value_b64"] = True
            out.append(m)
        return {"messages": out,
                "last_ns": msgs[-1]["ts_ns"] if msgs else
                max(since, head)}

    # -- placement (FindBroker) ----------------------------------------------

    def _owner_of(self, ns: str, topic: str, partition: int
                  ) -> str | None:
        brokers = self.live_brokers()
        if not brokers:
            return None
        ring = HashRing(sorted(brokers.values()))
        return ring.locate(f"{ns}/{topic}/{partition}")

    def _find_broker(self, query: dict, body: bytes) -> dict:
        ns, topic = query["namespace"], query["topic"]
        partition = int(query.get("partition", 0))
        owner = self._owner_of(ns, topic, partition)
        if owner is None:
            raise rpc.RpcError(503, "no live brokers")
        return {"broker": owner, "partition": partition}

    def _status(self, query: dict, body: bytes) -> dict:
        return {"id": self._id(), "brokers": self.live_brokers(),
                "local_partitions": [
                    {"namespace": k[0], "topic": k[1], "partition": k[2]}
                    for k in self._logs]}
