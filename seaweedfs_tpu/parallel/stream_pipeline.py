"""Bounded-depth three-stage stream pipeline: prefetch | device | drain.

The serialized batch EC loop pays sum(stages) per chunk — stack the
next batch, THEN dispatch the matmul, THEN fence and write.  This
pipeline overlaps them so per-chunk wall time approaches max(stage):

    producer thread:  items() generator — fetch/pread/stack chunk k+2
                      (IO + numpy, runs while the device computes)
    caller thread:    dispatch(item) — H2D + kernel launch for k+1
                      (async on device backends: returns a handle)
    drain thread:     drain(handle) — fence (D2H) + shard writes /
                      scatter for chunk k

Bounded queues on both sides cap live chunks at depth per side, so a
30GB volume batch never holds more than ~2*depth stacked chunks in
host memory — the "reusable pinned host buffer" discipline is the
caller's (cluster_encode keeps a buffer pool sized to the pipeline
depth and recycles a buffer only after its chunk drains).

``depth=0`` degenerates to the fully serialized loop — the measured
baseline `bench_e2e.py` compares against.

The ``recorder`` hook exists for the overlap regression test: every
stage transition is recorded with an injectable clock (no sleeps, no
wall-time flakiness) so a test can assert the next H2D was issued
before the previous device step completed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable


class PipelineRecorder:
    """Thread-safe (event, index, t) log with an injectable clock.

    Tests inject a counter clock so event ordering is exact sequence
    order; production leaves it None (events aren't recorded at all on
    the hot path unless a recorder is passed)."""

    def __init__(self, clock: Callable[[], float] | None = None):
        self.clock = clock or time.monotonic
        self._events: list[tuple[str, int, float]] = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def record(self, event: str, index: int) -> None:
        with self._cond:
            self._events.append((event, index, self.clock()))
            self._cond.notify_all()

    def events(self) -> list[tuple[str, int, float]]:
        with self._lock:
            return list(self._events)

    def seen(self, event: str, index: int) -> bool:
        with self._lock:
            return any(e == event and i == index
                       for e, i, _t in self._events)

    def wait_for(self, event: str, index: int,
                 timeout: float = 30.0) -> bool:
        """Block until (event, index) is recorded — lets a fake device
        gate its completion on pipeline progress without sleeping."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not any(e == event and i == index
                          for e, i, _t in self._events):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def first_time(self, event: str, index: int) -> float | None:
        with self._lock:
            for e, i, t in self._events:
                if e == event and i == index:
                    return t
        return None


class _Stop:
    """End-of-stream / error sentinel."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException | None = None):
        self.error = error


def run_pipeline(items: Iterable[Any],
                 dispatch: Callable[[Any], Any],
                 drain: Callable[[Any], None],
                 depth: int = 2,
                 recorder: PipelineRecorder | None = None,
                 cancel: threading.Event | None = None) -> int:
    """Drive items through dispatch -> drain with `depth` in flight.

    Returns the number of items processed.  Exceptions from any stage
    cancel the others and re-raise on the caller thread (producer
    blocked on a full queue is unblocked — never deadlocks).

    `cancel` (optional) is used as the internal cancellation flag, so a
    producer that blocks on resources OUTSIDE the pipeline's queues
    (e.g. a bounded buffer pool whose buffers are released by drain)
    can share it: when any stage dies, the flag is set and the
    producer's own blocking waits can observe it instead of waiting on
    a release that will never come."""
    if depth <= 0:
        n = 0
        for i, item in enumerate(items):
            if recorder:
                recorder.record("produced", i)
                recorder.record("dispatched", i)
            handle = dispatch(item)
            drain(handle)
            if recorder:
                recorder.record("drained", i)
            n += 1
        return n

    q_in: "queue.Queue" = queue.Queue(maxsize=depth)
    q_out: "queue.Queue" = queue.Queue(maxsize=depth)
    cancelled = cancel if cancel is not None else threading.Event()
    errors: list[BaseException] = []

    # Every blocking queue op polls the cancel flag: whichever stage
    # dies, the other two always unblock (no sleep-free deadlock path —
    # the 0.2s poll only runs during shutdown/error, never steady state).
    def _put(q, obj) -> bool:
        while not cancelled.is_set():
            try:
                q.put(obj, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _get(q):
        while True:
            try:
                return q.get(timeout=0.2)
            except queue.Empty:
                if cancelled.is_set():
                    return _Stop()

    def producer() -> None:
        try:
            for i, item in enumerate(items):
                if recorder:
                    recorder.record("produced", i)
                if not _put(q_in, (i, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            cancelled.set()
        finally:
            _put(q_in, _Stop())

    def drainer() -> None:
        try:
            while True:
                got = _get(q_out)
                if isinstance(got, _Stop):
                    return
                i, handle = got
                drain(handle)
                if recorder:
                    recorder.record("drained", i)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            cancelled.set()

    t_prod = threading.Thread(target=producer, daemon=True,
                              name="ecpipe-prefetch")
    t_drain = threading.Thread(target=drainer, daemon=True,
                               name="ecpipe-drain")
    t_prod.start()
    t_drain.start()
    n = 0
    try:
        while True:
            got = _get(q_in)
            if isinstance(got, _Stop) or cancelled.is_set():
                break
            i, item = got
            handle = dispatch(item)
            if recorder:
                recorder.record("dispatched", i)
            if not _put(q_out, (i, handle)):
                break
            n += 1
    except BaseException:
        cancelled.set()
        raise
    finally:
        # Orderly finish: deliver the stop sentinel so the drainer
        # fences and writes every in-flight handle FIFO (a full q_out
        # blocks until it makes room); on error paths the cancel flag
        # short-circuits the wait.  Then free a producer stuck on a
        # full q_in, and join both sides before surfacing anything.
        _put(q_out, _Stop())
        cancelled.set()
        while True:
            try:
                q_in.get_nowait()
            except queue.Empty:
                break
        t_prod.join()
        t_drain.join()
    if errors:
        raise errors[0]
    return n
