"""Bounded-depth three-stage stream pipeline: prefetch | device | drain.

The serialized batch EC loop pays sum(stages) per chunk — stack the
next batch, THEN dispatch the matmul, THEN fence and write.  This
pipeline overlaps them so per-chunk wall time approaches max(stage):

    producer thread:  items() generator — fetch/pread/stack chunk k+2
                      (IO + numpy, runs while the device computes)
    caller thread:    dispatch(item) — H2D + kernel launch for k+1
                      (async on device backends: returns a handle)
    drain thread:     drain(handle) — fence (D2H) + shard writes /
                      scatter for chunk k

Bounded queues on both sides cap live chunks at depth per side, so a
30GB volume batch never holds more than ~2*depth stacked chunks in
host memory — the "reusable pinned host buffer" discipline is the
caller's (cluster_encode keeps a buffer pool sized to the pipeline
depth and recycles a buffer only after its chunk drains).

``depth=0`` degenerates to the fully serialized loop — the measured
baseline `bench_e2e.py` compares against.

The ``recorder`` hook exists for the overlap regression test: every
stage transition is recorded with an injectable clock (no sleeps, no
wall-time flakiness) so a test can assert the next H2D was issued
before the previous device step completed.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable


class PipelineRecorder:
    """Thread-safe, bounded (event, index, t) log plus per-batch stage
    spans, with an injectable clock.

    Originally a test helper for the overlap regression; now also the
    always-on production recorder inside cluster_encode/cluster_rebuild
    (the device roofline plane's occupancy source).  Both stores are
    bounded rings so an arbitrarily long streamed run holds constant
    memory: transition events keep the overlap regression exact, and
    `note_span()` feeds the gantt / device-occupancy / bubble readers.

    Tests inject a counter clock so event ordering is exact sequence
    order; production uses the default monotonic clock."""

    def __init__(self, clock: Callable[[], float] | None = None,
                 maxlen: int = 4096):
        self.clock = clock or time.monotonic
        self._events: deque = deque(maxlen=maxlen)
        # (stage, index, t0, t1) — stages: stack|dispatch|device|drain
        self._spans: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def record(self, event: str, index: int) -> None:
        with self._cond:
            self._events.append((event, index, self.clock()))
            self._cond.notify_all()

    def note_span(self, stage: str, index: int, t0: float,
                  t1: float) -> None:
        """One completed stage interval for batch `index` (caller's
        clock values, so fenced device walls and injected test clocks
        both work)."""
        with self._lock:
            self._spans.append((stage, index, float(t0), float(t1)))

    def events(self) -> list[tuple[str, int, float]]:
        with self._lock:
            return list(self._events)

    def spans(self) -> list[tuple[str, int, float, float]]:
        with self._lock:
            return list(self._spans)

    # -- occupancy / gantt read side ------------------------------------
    # Everything below computes at read time from the bounded span ring
    # — nothing here runs on the pipeline hot path.

    def gantt(self, last: int = 8) -> list[dict]:
        """Per-batch stage timeline for the most recent `last` batches:
        [{"index": i, "stages": {stage: [t0, t1]}}] ordered by index.
        A stage noted twice for one index keeps the widest interval."""
        rows: dict[int, dict] = {}
        for stage, i, t0, t1 in self.spans():
            st = rows.setdefault(i, {})
            if stage in st:
                st[stage] = [min(st[stage][0], t0), max(st[stage][1], t1)]
            else:
                st[stage] = [t0, t1]
        idxs = sorted(rows)[-last:]
        return [{"index": i, "stages": rows[i]} for i in idxs]

    @staticmethod
    def _union(intervals: list[tuple[float, float]]) -> list[list[float]]:
        merged: list[list[float]] = []
        for t0, t1 in sorted(intervals):
            if t1 <= t0:
                continue
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        return merged

    def device_occupancy(self) -> dict:
        """Fraction of the recorded window the device was busy (union
        of `device` spans over [first span start, last span end]), plus
        each stage's active share of the same window."""
        spans = self.spans()
        if not spans:
            return {"window": None, "busy_seconds": 0.0,
                    "fraction": None, "stages": {}}
        lo = min(t0 for _s, _i, t0, _t1 in spans)
        hi = max(t1 for _s, _i, _t0, t1 in spans)
        window = max(hi - lo, 1e-12)
        by_stage: dict[str, list] = {}
        for stage, _i, t0, t1 in spans:
            by_stage.setdefault(stage, []).append((t0, t1))
        shares = {stage: round(sum(b - a for a, b in
                                   self._union(iv)) / window, 6)
                  for stage, iv in sorted(by_stage.items())}
        busy = sum(b - a for a, b in
                   self._union(by_stage.get("device", [])))
        return {"window": [lo, hi],
                "busy_seconds": round(busy, 9),
                "fraction": round(busy / window, 6),
                "stages": shares}

    def bubble_attribution(self) -> dict:
        """Where the device idled: gaps in the device-busy union are
        attributed to whichever non-device stages were active during
        the gap (the stage the device was waiting on); gap time no
        stage covers is `idle`.  `starving_stage` names the biggest
        contributor — the thing to widen next."""
        spans = self.spans()
        device = self._union([(t0, t1) for s, _i, t0, t1 in spans
                              if s == "device"])
        if not device:
            return {"bubble_seconds": 0.0, "by_stage": {},
                    "starving_stage": ""}
        lo = min(t0 for _s, _i, t0, _t1 in spans)
        hi = max(t1 for _s, _i, _t0, t1 in spans)
        gaps: list[tuple[float, float]] = []
        cur = lo
        for a, b in device:
            if a > cur:
                gaps.append((cur, a))
            cur = max(cur, b)
        if hi > cur:
            gaps.append((cur, hi))
        others: dict[str, list[list[float]]] = {}
        for s, _i, t0, t1 in spans:
            if s != "device":
                others.setdefault(s, []).append((t0, t1))
        others = {s: self._union(iv) for s, iv in others.items()}
        by_stage: dict[str, float] = {}
        covered = 0.0
        total = sum(b - a for a, b in gaps)
        for g0, g1 in gaps:
            for stage, iv in others.items():
                ov = sum(min(b, g1) - max(a, g0) for a, b in iv
                         if min(b, g1) > max(a, g0))
                if ov > 0.0:
                    by_stage[stage] = by_stage.get(stage, 0.0) + ov
                    covered += ov
        idle = total - min(covered, total)
        if idle > 1e-12:
            by_stage["idle"] = by_stage.get("idle", 0.0) + idle
        starving = ""
        if by_stage:
            starving = max(sorted(by_stage), key=lambda s: by_stage[s])
        return {"bubble_seconds": round(total, 9),
                "by_stage": {s: round(v, 9)
                             for s, v in sorted(by_stage.items())},
                "starving_stage": starving}

    def seen(self, event: str, index: int) -> bool:
        with self._lock:
            return any(e == event and i == index
                       for e, i, _t in self._events)

    def wait_for(self, event: str, index: int,
                 timeout: float = 30.0) -> bool:
        """Block until (event, index) is recorded — lets a fake device
        gate its completion on pipeline progress without sleeping."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while not any(e == event and i == index
                          for e, i, _t in self._events):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def first_time(self, event: str, index: int) -> float | None:
        with self._lock:
            for e, i, t in self._events:
                if e == event and i == index:
                    return t
        return None


class _Stop:
    """End-of-stream / error sentinel."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException | None = None):
        self.error = error


def run_pipeline(items: Iterable[Any],
                 dispatch: Callable[[Any], Any],
                 drain: Callable[[Any], None],
                 depth: int = 2,
                 recorder: PipelineRecorder | None = None,
                 cancel: threading.Event | None = None) -> int:
    """Drive items through dispatch -> drain with `depth` in flight.

    Returns the number of items processed.  Exceptions from any stage
    cancel the others and re-raise on the caller thread (producer
    blocked on a full queue is unblocked — never deadlocks).

    `cancel` (optional) is used as the internal cancellation flag, so a
    producer that blocks on resources OUTSIDE the pipeline's queues
    (e.g. a bounded buffer pool whose buffers are released by drain)
    can share it: when any stage dies, the flag is set and the
    producer's own blocking waits can observe it instead of waiting on
    a release that will never come."""
    if depth <= 0:
        n = 0
        for i, item in enumerate(items):
            if recorder:
                recorder.record("produced", i)
                recorder.record("dispatched", i)
            handle = dispatch(item)
            drain(handle)
            if recorder:
                recorder.record("drained", i)
            n += 1
        return n

    q_in: "queue.Queue" = queue.Queue(maxsize=depth)
    q_out: "queue.Queue" = queue.Queue(maxsize=depth)
    cancelled = cancel if cancel is not None else threading.Event()
    errors: list[BaseException] = []

    # Every blocking queue op polls the cancel flag: whichever stage
    # dies, the other two always unblock (no sleep-free deadlock path —
    # the 0.2s poll only runs during shutdown/error, never steady state).
    def _put(q, obj) -> bool:
        while not cancelled.is_set():
            try:
                q.put(obj, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _get(q):
        while True:
            try:
                return q.get(timeout=0.2)
            except queue.Empty:
                if cancelled.is_set():
                    return _Stop()

    def producer() -> None:
        try:
            for i, item in enumerate(items):
                if recorder:
                    recorder.record("produced", i)
                if not _put(q_in, (i, item)):
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            cancelled.set()
        finally:
            _put(q_in, _Stop())

    def drainer() -> None:
        try:
            while True:
                got = _get(q_out)
                if isinstance(got, _Stop):
                    return
                i, handle = got
                drain(handle)
                if recorder:
                    recorder.record("drained", i)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            cancelled.set()

    t_prod = threading.Thread(target=producer, daemon=True,
                              name="ecpipe-prefetch")
    t_drain = threading.Thread(target=drainer, daemon=True,
                               name="ecpipe-drain")
    t_prod.start()
    t_drain.start()
    n = 0
    try:
        while True:
            got = _get(q_in)
            if isinstance(got, _Stop) or cancelled.is_set():
                break
            i, item = got
            handle = dispatch(item)
            if recorder:
                recorder.record("dispatched", i)
            if not _put(q_out, (i, handle)):
                break
            n += 1
    except BaseException:
        cancelled.set()
        raise
    finally:
        # Orderly finish: deliver the stop sentinel so the drainer
        # fences and writes every in-flight handle FIFO (a full q_out
        # blocks until it makes room); on error paths the cancel flag
        # short-circuits the wait.  Then free a producer stuck on a
        # full q_in, and join both sides before surfacing anything.
        _put(q_out, _Stop())
        cancelled.set()
        while True:
            try:
                q_in.get_nowait()
            except queue.Empty:
                break
        t_prod.join()
        t_drain.join()
    if errors:
        raise errors[0]
    return n
