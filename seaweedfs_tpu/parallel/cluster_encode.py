"""Cluster-integrated batched EC encode: many volumes, one mesh step.

The encode mirror of `cluster_rebuild`: pull quiet/full volumes'
`.dat`/`.idx` from their servers, stack stripe chunks from MANY volumes
on the mesh's "vol" axis, compute all parity in batched GF(2)
bit-matmuls (`sharded_codec.batched_encode_with_crc` — shard_map over
("vol", "col"), zero collectives), then scatter the shards + `.ecx`
across the cluster, mount them, and delete the original replicas.

The data path is STREAMED, not lockstep (ROADMAP 1): a prefetch thread
stacks the next chunk batch into a reusable host buffer while the
device computes the current one and a drain thread fences completed
parity and appends shard files — per-chunk wall time approaches
max(stage) instead of sum(stages) (stream_pipeline.py; the overlap is
visible in the `batch_*` stage histograms, whose per-stage sums exceed
the wall clock).  The encode kernel also emits every shard's per-block
CRC32-C on device (ops/crc_fold.py), so the `.ecc` sidecar ships to
each holder ready-made and `receive_shard` skips its CPU re-read of
the pushed bytes.

The reference encodes one volume at a time ON its own server
(weed/shell/command_ec_encode.go:92-264 →
VolumeEcShardsGenerate, server/volume_grpc_erasure_coding.go:40); this
is the SURVEY §2.3 "shard scatter after encode" mapping instead —
encoding N quiet volumes is embarrassingly data-parallel over chips,
and the per-volume chunking reuses the exact `_chunk_reader` the local
encoder uses, so shard bytes stay byte-identical to `ec.encode`
(the golden-gate layout).

Shell entry point: `ec.encode -batch` (shell/command_ec.py).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import tempfile
import threading
import time

import numpy as np

from ..cluster import rpc
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats import flows as _flows
from ..stats.metrics import observe_batch_stage, stage_attrs
from ..trace import root_span
from ..codecs import get_codec
from ..ec import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext
from ..ec.encoder import (DEFAULT_CHUNK, _chunk_reader,
                          write_sorted_file_from_idx)
from ..ec.volume_info import update_volume_info
from ..ops import crc_fold
from ..stats import roofline as _roofline
from .cluster_rebuild import _pad_to, make_mesh
from .sharded_codec import (batched_encode, batched_encode_with_crc,
                            record_fenced_batch)
from .stream_pipeline import PipelineRecorder, run_pipeline

# Column padding granularity — matches cluster_rebuild: keeps the
# jitted matmul's N lane-aligned and divisible by any col axis <= 16,
# and collapses ragged tail-chunk widths onto few compiled shapes.
_COL_ALIGN = 2048


def pipeline_depth(depth: int | None = None) -> int:
    """Chunks in flight between prefetch and drain.  0 = the fully
    serialized legacy loop (the measured baseline in bench_e2e.py)."""
    if depth is not None:
        return depth
    return int(os.environ.get("SEAWEEDFS_TPU_EC_PIPELINE_DEPTH", "2"))


fused_crc_enabled = crc_fold.fused_crc_enabled


def scatter_budget_bytes() -> int:
    """Cap on concurrent in-flight shard payload bytes during scatter —
    a 30GB volume batch must not hold ~14 whole shard files in memory
    at once (shards are read inside the budgeted workers, not up
    front)."""
    return int(os.environ.get("SEAWEEDFS_TPU_EC_SCATTER_BUDGET",
                              str(256 << 20)))


class _ByteBudget:
    """Blocking byte-count semaphore; a request larger than the cap is
    clamped so a single huge shard can always proceed alone."""

    def __init__(self, cap: int):
        self.cap = max(1, cap)
        self._used = 0
        self._cond = threading.Condition()

    def acquire(self, nbytes: int) -> int:
        take = min(nbytes, self.cap)
        with self._cond:
            while self._used + take > self.cap:
                self._cond.wait()
            self._used += take
        return take

    def release(self, taken: int) -> None:
        with self._cond:
            self._used -= taken
            self._cond.notify_all()


class _BufferPool:
    """Reusable host staging buffers for the stacked chunk batches.

    The pipeline recycles a buffer only after its chunk has been fenced
    and written (drain), so at most `slots` stacked batches exist — the
    bounded-memory half of the double-buffering story."""

    def __init__(self, slots: int, shape: tuple[int, int, int],
                 cancel: threading.Event | None = None):
        self._free: list[np.ndarray] = []
        self._slots = slots
        self._shape = shape
        self._cond = threading.Condition()
        self._made = 0
        # Shared with the stream pipeline: if the drain stage dies, no
        # release() is ever coming — a producer blocked here must
        # observe the cancellation instead of deadlocking the
        # pipeline's final join.
        self._cancel = cancel

    def acquire(self) -> np.ndarray:
        with self._cond:
            while not self._free and self._made >= self._slots:
                if self._cancel is not None and self._cancel.is_set():
                    raise RuntimeError("encode pipeline cancelled")
                self._cond.wait(0.2)
            if self._free:
                # Recycled buffers keep their stale bytes: the producer
                # zeroes exactly the padding regions of the view it
                # stacks into (row tails past each chunk's width, rows
                # past the live volume count) — a full fill(0) here
                # would cost an extra whole-buffer memory pass per
                # chunk batch on the host hot path.
                buf = self._free.pop()
            else:
                self._made += 1
                buf = np.zeros(self._shape, np.uint8)
        return buf

    def release(self, buf: np.ndarray) -> None:
        with self._cond:
            self._free.append(buf)
            self._cond.notify()


def batch_encode(env, vids, mesh=None, max_batch_bytes=1 << 28,
                 workers: int = 8, chunk_size: int = DEFAULT_CHUNK,
                 progress=None, codec=None,
                 depth: int | None = None) -> list[str]:
    """EC-encode `vids` across the cluster in mesh-batched steps.
    Returns one human-readable line per volume.  `codec` selects the
    erasure codec ("rs" default / "lrc"): the generator matrix, shard
    count, and the .vif codec id pushed to every holder derive from it.
    `depth` overrides the stream-pipeline depth (0 = serialized).

    env: duck-typed cluster view (shell CommandEnv): volume_locations,
    data_nodes, vs_call.
    """
    if not SMALL_BLOCK_SIZE <= chunk_size <= LARGE_BLOCK_SIZE:
        # The staging-buffer capacity is sized to min(chunk_size,
        # LARGE_BLOCK_SIZE), but the small-row reader yields widths up
        # to chunk_size — a larger value would broadcast-fail
        # mid-encode AFTER replicas were frozen.  Refuse up front.
        raise ValueError(
            f"chunk_size {chunk_size} must be within "
            f"[{SMALL_BLOCK_SIZE}, {LARGE_BLOCK_SIZE}]")
    if LARGE_BLOCK_SIZE % chunk_size != 0:
        # _chunk_reader enforces this mid-stream on the first
        # large-block row — same refuse-before-freeze rationale.
        raise ValueError(
            f"chunk_size {chunk_size} must divide the large block "
            f"size {LARGE_BLOCK_SIZE}")
    codec = get_codec(codec)
    depth = pipeline_depth(depth)
    if mesh is None:
        mesh = make_mesh()
    # One size map per batch call — not an O(volumes x nodes) rescan
    # of the full topology per volume.
    sizes: dict[int, int] = {}
    for n in env.data_nodes():
        for v in n["volumes"]:
            sizes.setdefault(v["id"], int(v["size"]))
    targets: list[tuple[int, list[str]]] = []
    messages: list[str] = []
    for vid in vids:
        try:
            locs = env.volume_locations(vid)
        except rpc.RpcError as e:
            if e.status != 404:
                raise
            locs = []
        if not locs:
            messages.append(f"volume {vid}: SKIPPED — no locations")
            continue
        targets.append((vid, locs))
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=workers)
    try:
        i = 0
        while i < len(targets):
            batch, total = [], 0
            while i < len(targets) and (not batch
                                        or total < max_batch_bytes):
                batch.append(targets[i])
                total += sizes.get(targets[i][0], 0)
                i += 1
            messages += _encode_batch_group(env, mesh, pool, batch,
                                            chunk_size, progress,
                                            codec, depth)
    finally:
        # cancel_futures: queued fetch/scatter work from a failed batch
        # must not keep running (and keep connections pinned) after the
        # exception has already unwound to the caller.
        pool.shutdown(wait=False, cancel_futures=True)
    return messages


def _fetch_volume(tmpdir: str, vid: int, locs: list[str]) -> str:
    """Freeze + pull one volume's .dat/.idx to local temp files,
    failing over across replicas.  Returns the local base path."""
    base = os.path.join(tmpdir, str(vid))
    errors = []
    for url in locs:
        try:
            if _fault.ARMED:
                _fault.hit("ec.fetch_shard", holder=url, vid=vid)
            _h = {**rpc.PRIORITY_LOW, **_flows.tag("ec.gather")}
            rpc.call_to_file(
                f"http://{url}/admin/volume_file?volume={vid}&ext=.idx",
                base + ".idx", headers=_h)
            rpc.call_to_file(
                f"http://{url}/admin/volume_file?volume={vid}&ext=.dat",
                base + ".dat", headers=_h)
            return base
        except Exception as e:  # noqa: BLE001 — next replica
            errors.append(f"{url}: {type(e).__name__}: {e}")
    raise rpc.RpcError(
        502, f"volume {vid}: cannot fetch .dat/.idx: "
             + "; ".join(errors[:4]))


def _encode_batch_group(env, mesh, pool, batch, chunk_size,
                        progress, codec, depth) -> list[str]:
    """Fetch, mesh-encode, scatter one sub-batch of volumes — journaled
    as ec.encode.start/finish with per-stage byte/second attrs, under a
    root span so the timeline row links to a /debug/traces trace."""
    vids = [vid for vid, _locs in batch]
    with root_span("ec.batch_encode", "ec", volumes=len(vids),
                   codec=codec.name):
        emit_event("ec.encode.start", volumes=vids, batch=True,
                   codec=codec.name)
        t0 = time.perf_counter()
        stages: dict[str, list[float]] = {}  # stage -> [seconds, bytes]
        try:
            out = _encode_batch_group_inner(env, mesh, pool, batch,
                                            chunk_size, progress,
                                            stages, codec, depth)
        except Exception as e:
            emit_event("ec.encode.finish", severity="error",
                       volumes=vids, batch=True, codec=codec.name,
                       seconds=round(time.perf_counter() - t0, 6),
                       error=f"{type(e).__name__}: {e}",
                       **stage_attrs(stages))
            raise
        emit_event("ec.encode.finish", volumes=vids, batch=True,
                   codec=codec.name, pipeline_depth=depth,
                   seconds=round(time.perf_counter() - t0, 6),
                   **stage_attrs(stages))
        return out


def _encode_batch_group_inner(env, mesh, pool, batch, chunk_size,
                              progress, stages, codec, depth) -> list[str]:
    """Fetch, stream-encode, scatter one sub-batch of volumes."""
    from ..shell.command_ec import balanced_distribution, collect_ec_nodes
    vol_axis = mesh.shape["vol"]
    col_axis = mesh.shape["col"]
    # Fused device CRCs need every stacked width to cover whole `.ecc`
    # blocks per mesh column; `_chunk_reader` widths are always 1MB
    # multiples when chunk_size is, so the only cost is column padding
    # up to BLOCK x col instead of 2048 x col.
    fused = fused_crc_enabled() and chunk_size % SMALL_BLOCK_SIZE == 0
    align = SMALL_BLOCK_SIZE * col_axis if fused \
        else _pad_to(_COL_ALIGN, col_axis * 8)
    out: list[str] = []
    with tempfile.TemporaryDirectory(prefix="ec_batch_encode_") as tmp:
        # 1. Freeze every replica, then pull .dat/.idx in parallel.
        for vid, locs in batch:
            for url in locs:
                env.vs_call(url, "/admin/readonly",
                            {"volume": vid, "readonly": True})
        t_fetch = time.perf_counter()
        bases = list(pool.map(
            lambda t: _fetch_volume(tmp, *t), batch))
        observe_batch_stage(
            stages, "batch_fetch", time.perf_counter() - t_fetch,
            sum(os.path.getsize(b + ".dat") for b in bases))

        # 2. Stream-encode: stripe chunks stacked on "vol", prefetch /
        # device / drain overlapped (module docstring).  Each volume's
        # chunk sequence is the exact local-encoder chunking
        # (byte-identical shards); columns are zero-padded (parity is
        # columnwise for every codec, so padded columns are discarded
        # zeros, never corruption).
        writers = [_ShardWriter(b, codec.total_shards) for b in bases]
        # Per-volume, per-shard `.ecc` block CRCs from the device.
        vol_crcs: list[list[list[int]]] = \
            [[[] for _ in range(codec.total_shards)] for _ in bases]
        dats = [open(b + ".dat", "rb") for b in bases]
        n_cap = _pad_to(max(SMALL_BLOCK_SIZE,
                            min(chunk_size, LARGE_BLOCK_SIZE)), align)
        v_cap = _pad_to(len(bases), vol_axis)
        cancel = threading.Event()
        buffers = _BufferPool(max(2, depth + 1),
                              (v_cap, DATA_SHARDS, n_cap),
                              cancel=cancel)
        # Always-on (bounded) production recorder: per-batch stage
        # spans feed the roofline plane's occupancy/gantt surfaces.
        rec = PipelineRecorder(maxlen=1024) if _roofline.ARMED else None
        try:
            iters = [
                _chunk_reader(d, os.path.getsize(b + ".dat"),
                              LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                              chunk_size)
                for d, b in zip(dats, bases)]

            def produce():
                active = list(range(len(iters)))
                bi = 0
                while active:
                    t_stack = time.perf_counter()
                    chunks, produced = [], []
                    for v in active:
                        try:
                            chunks.append(next(iters[v]))
                            produced.append(v)
                        except StopIteration:
                            pass
                    if not chunks:
                        break
                    widths = [c.shape[1] for c in chunks]
                    n_pad = _pad_to(max(widths), align)
                    v_pad = _pad_to(len(chunks), vol_axis)
                    # Backpressure wait (drain hasn't recycled a buffer
                    # yet) is pipeline idle time, not stacking work —
                    # keep it out of the batch_stack histogram or a
                    # device-bound run reads as stack-bound.
                    t_wait0 = time.perf_counter()
                    buf = buffers.acquire()
                    t_wait1 = time.perf_counter()
                    t_wait = t_wait1 - t_wait0
                    stacked = buf[:v_pad, :, :n_pad]
                    for j, c in enumerate(chunks):
                        stacked[j, :, :c.shape[1]] = c
                        stacked[j, :, c.shape[1]:] = 0
                    stacked[len(chunks):] = 0
                    t_end = time.perf_counter()
                    observe_batch_stage(
                        stages, "batch_stack",
                        t_end - t_stack - t_wait,
                        sum(widths) * DATA_SHARDS)
                    if rec is not None:
                        # Two segments: the buffer-pool wait between
                        # them is idle backpressure, not stack work.
                        rec.note_span("stack", bi, t_stack, t_wait0)
                        rec.note_span("stack", bi, t_wait1, t_end)
                    yield (buf, stacked, list(produced), widths, bi)
                    bi += 1
                    active = produced

            def dispatch(item):
                buf, stacked, active, widths, bi = item
                t_d0 = time.perf_counter()
                if fused:
                    parity, crcs = batched_encode_with_crc(
                        stacked, mesh, codec=codec.name)
                else:
                    parity = batched_encode(stacked, mesh,
                                            codec=codec.name)
                    crcs = None
                t_d1 = time.perf_counter()
                if rec is not None:
                    rec.note_span("dispatch", bi, t_d0, t_d1)
                return (buf, parity, crcs, active, widths,
                        stacked.nbytes, bi, t_d0, t_d1)

            def drain(handle):
                (buf, parity, crcs, active, widths, nbytes, bi,
                 t_d0, t_d1) = handle
                # np.asarray fences the dispatch (device->host copy):
                # this stage is the EXPOSED device+transfer wait — with
                # the pipeline overlapping, its per-batch sum exceeds
                # the wall-clock share it actually costs.
                t_dev = time.perf_counter()
                parity = np.asarray(parity)
                if crcs is not None:
                    crcs = np.asarray(crcs)
                t_fence = time.perf_counter()
                observe_batch_stage(stages, "batch_encode_device",
                                    t_fence - t_dev, nbytes)
                if rec is not None:
                    # Device busy is observable only as [dispatch end,
                    # drain fence]: includes q_out queueing, so it is
                    # an upper bound on true kernel occupancy.
                    rec.note_span("device", bi, t_d1, t_fence)
                if _roofline.ARMED:
                    record_fenced_batch(
                        "batch_encode", codec.name,
                        out_rows=int(parity.shape[1]),
                        in_rows=DATA_SHARDS, n=int(parity.shape[2]),
                        batch=int(parity.shape[0]),
                        crc=crcs is not None,
                        seconds=t_fence - t_d0,
                        measured_bytes=int(nbytes) + parity.nbytes)
                t_wr = time.perf_counter()
                written = 0
                for j, v in enumerate(active):
                    w = widths[j]
                    writers[v].write(buf[j, :, :w], parity[j, :, :w])
                    written += w * (DATA_SHARDS + parity.shape[1])
                    if crcs is not None:
                        nb = w // SMALL_BLOCK_SIZE
                        for sid in range(codec.total_shards):
                            vol_crcs[v][sid].extend(
                                int(c) for c in crcs[j, sid, :nb])
                t_wr1 = time.perf_counter()
                observe_batch_stage(stages, "batch_write",
                                    t_wr1 - t_wr, written)
                if rec is not None:
                    rec.note_span("drain", bi, t_wr, t_wr1)
                buffers.release(buf)

            run_pipeline(produce(), dispatch, drain, depth=depth,
                         cancel=cancel, recorder=rec)
            for w in writers:
                w.finish()
            if rec is not None:
                _roofline.LEDGER.note_pipeline("encode", rec)
        finally:
            for d in dats:
                d.close()

        # 3. .ecx from the fetched .idx (WriteSortedFileFromIdx), and
        # a .vif carrying the needle version + codec id — every shard
        # holder must know which generator matrix made its shards.
        for base in bases:
            write_sorted_file_from_idx(base)
            with open(base + ".dat", "rb") as f:
                version = f.read(1)[0]
            update_volume_info(base, version=version, codec=codec.name)

        # 4. Scatter: balanced placement; push the device-computed
        # `.ecc` fragment FIRST (so receive_shard skips its CPU CRC
        # pass over the pushed bytes), then shards under the byte
        # budget, then .ecx/.vif, mount, delete the originals
        # (command_ec_encode.go flow).
        budget = _ByteBudget(scatter_budget_bytes())
        for b_idx, ((vid, locs), base) in enumerate(zip(batch, bases)):
            plan = balanced_distribution(collect_ec_nodes(env),
                                         n_shards=codec.total_shards)
            t_scatter = time.perf_counter()
            pusher = _ecc_push_plan(
                vid, ((url, sid, vol_crcs[b_idx][sid])
                      for url, sids in plan.items()
                      for sid in sids)) if fused else None
            futs = []
            for url, shard_ids in plan.items():
                for sid in shard_ids:
                    futs.append(pool.submit(
                        _scatter_shard, url, vid, sid,
                        base + to_ext(sid), budget, pusher))
            scattered = sum(f.result() for f in futs)
            observe_batch_stage(stages, "batch_scatter",
                           time.perf_counter() - t_scatter, scattered)
            with open(base + ".ecx", "rb") as f:
                ecx = f.read()
            with open(base + ".vif", "rb") as f:
                vif = f.read()
            for url in plan:
                _h = {**rpc.PRIORITY_LOW,
                      **_flows.tag("ec.scatter")}
                rpc.call(f"http://{url}/admin/ec/receive_file?"
                         f"volume={vid}&ext=.ecx", "POST", ecx, 600.0,
                         headers=_h)
                rpc.call(f"http://{url}/admin/ec/receive_file?"
                         f"volume={vid}&ext=.vif", "POST", vif, 600.0,
                         headers=_h)
                env.vs_call(url, "/admin/ec/mount", {"volume": vid})
            for url in locs:
                env.vs_call(url, "/admin/delete_volume", {"volume": vid})
            line = (f"volume {vid} -> ec shards on {len(plan)} "
                    "servers: "
                    + ", ".join(f"{u}:{s}"
                                for u, s in sorted(plan.items())))
            out.append(line)
            if progress:
                progress(line)
    return out


class _EccOncePush:
    """Once-per-holder push of the kernel-computed `.ecc` fragment,
    run lazily inside the scatter workers: the first shard worker bound
    for a holder ships that holder's fragment under its lock — so the
    entries land BEFORE any shard body and receive_shard can skip its
    CPU pass — while workers for other holders proceed in parallel.  A
    slow/unresponsive holder stalls only its own shard pushes, never
    the drain thread or the whole scatter loop (the fragments are
    best-effort: a holder that missed its fragment just fingerprints
    the pushed bodies as before)."""

    def __init__(self, vid: int, docs: dict[str, dict]):
        self._vid = vid
        self._docs = docs
        self._locks = {u: threading.Lock() for u in docs}

    def ensure(self, url: str) -> None:
        lock = self._locks.get(url)
        if lock is None:
            return
        with lock:
            doc = self._docs.pop(url, None)
            if doc is None:
                return  # already shipped (or the attempt failed)
            try:
                rpc.call(
                    f"http://{url}/admin/ec/receive_ecc?"
                    f"volume={self._vid}", "POST",
                    json.dumps(doc).encode(), 60.0,
                    headers={**rpc.PRIORITY_LOW,
                             **_flows.tag("ec.scatter")})
            except (rpc.RpcError, OSError):
                # Best effort: holder recomputes from the body.  OSError
                # covers connection-level failures (ConnectError,
                # resets, socket timeouts) that are NOT RpcError — a
                # flaky holder must not abort the whole scatter over an
                # optimization.
                pass


def _ecc_push_plan(vid: int, entries) -> _EccOncePush:
    """Build the per-holder `.ecc` fragments from `(holder_url, sid,
    crcs)` triples — the ONE place the fragment wire format (block key,
    8-hex-digit CRCs) is written, shared by encode scatter and rebuild
    scatter.  The CRCs come from the encode kernel, i.e. the intended
    bytes, so wire or disk divergence after this point is detectable by
    the first scrub."""
    docs: dict[str, dict] = {}
    for url, sid, crcs in entries:
        doc = docs.setdefault(
            url, {"block": SMALL_BLOCK_SIZE, "shards": {}})
        doc["shards"][str(sid)] = [f"{c:08x}" for c in crcs]
    return _EccOncePush(vid, docs)


def _scatter_shard(url: str, vid: int, sid: int, path: str,
                   budget: _ByteBudget,
                   ecc_push: _EccOncePush | None = None) -> int:
    """Push one encoded shard to its placement target.  The file is
    read HERE, inside the budgeted worker — the submit loop never holds
    payload bytes, and `budget` caps total in-flight bytes."""
    # Fragment first, BEFORE taking budget or reading the file: workers
    # queued on a slow holder's _EccOncePush lock must idle empty-handed
    # — holding budget bytes there would starve pushes to healthy
    # holders of the 256MB cap.
    if ecc_push is not None:
        ecc_push.ensure(url)
    size = os.path.getsize(path)
    taken = budget.acquire(size)
    try:
        with open(path, "rb") as f:
            payload = f.read()
        if _fault.ARMED:
            _fault.hit("ec.scatter", target=url, vid=vid, shard=sid)
        rpc.call(f"http://{url}/admin/ec/receive_shard?"
                 f"volume={vid}&shard={sid}", "POST", payload, 600.0,
                 headers={**rpc.PRIORITY_LOW,
                          **_flows.tag("ec.scatter")})
        return size
    finally:
        budget.release(taken)


class _ShardWriter:
    """Appends stripe chunks to the codec's local shard files of one
    volume in arrival order — the same order `write_ec_files` writes
    them."""

    def __init__(self, base: str, total_shards: int):
        self.files = [open(base + to_ext(i), "wb")
                      for i in range(total_shards)]

    def write(self, data: np.ndarray, parity: np.ndarray) -> None:
        for i in range(DATA_SHARDS):
            self.files[i].write(data[i].tobytes())
        for p in range(parity.shape[0]):
            self.files[DATA_SHARDS + p].write(parity[p].tobytes())

    def finish(self) -> None:
        for f in self.files:
            f.close()
