"""Cluster-integrated batched EC encode: many volumes, one mesh step.

The encode mirror of `cluster_rebuild`: pull quiet/full volumes'
`.dat`/`.idx` from their servers, stack stripe chunks from MANY volumes
on the mesh's "vol" axis, compute all parity in batched jitted GF(2)
bit-matmuls (`sharded_codec.batched_encode` — byte columns sharded over
"col", zero collectives), then scatter the 14 shards + `.ecx` across
the cluster, mount them, and delete the original replicas.

The reference encodes one volume at a time ON its own server
(weed/shell/command_ec_encode.go:92-264 →
VolumeEcShardsGenerate, server/volume_grpc_erasure_coding.go:40); this
is the SURVEY §2.3 "shard scatter after encode" mapping instead —
encoding N quiet volumes is embarrassingly data-parallel over chips,
and the per-volume chunking reuses the exact `_chunk_reader` the local
encoder uses, so shard bytes stay byte-identical to `ec.encode`
(the golden-gate layout).

Shell entry point: `ec.encode -batch` (shell/command_ec.py).
"""

from __future__ import annotations

import concurrent.futures
import os
import tempfile
import time

import numpy as np

from ..cluster import rpc
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats.metrics import observe_batch_stage, stage_attrs
from ..trace import root_span
from ..codecs import get_codec
from ..ec import DATA_SHARDS, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE, to_ext
from ..ec.encoder import (DEFAULT_CHUNK, _chunk_reader,
                          write_sorted_file_from_idx)
from ..ec.volume_info import update_volume_info
from .cluster_rebuild import _pad_to, make_mesh
from .sharded_codec import batched_encode

# Column padding granularity — matches cluster_rebuild: keeps the
# jitted matmul's N lane-aligned and divisible by any col axis <= 16,
# and collapses ragged tail-chunk widths onto few compiled shapes.
_COL_ALIGN = 2048


def batch_encode(env, vids, mesh=None, max_batch_bytes=1 << 28,
                 workers: int = 8, chunk_size: int = DEFAULT_CHUNK,
                 progress=None, codec=None) -> list[str]:
    """EC-encode `vids` across the cluster in mesh-batched steps.
    Returns one human-readable line per volume.  `codec` selects the
    erasure codec ("rs" default / "lrc"): the generator matrix, shard
    count, and the .vif codec id pushed to every holder derive from it.

    env: duck-typed cluster view (shell CommandEnv): volume_locations,
    data_nodes, vs_call.
    """
    codec = get_codec(codec)
    if mesh is None:
        mesh = make_mesh()
    targets: list[tuple[int, list[str]]] = []
    messages: list[str] = []
    for vid in vids:
        try:
            locs = env.volume_locations(vid)
        except rpc.RpcError as e:
            if e.status != 404:
                raise
            locs = []
        if not locs:
            messages.append(f"volume {vid}: SKIPPED — no locations")
            continue
        targets.append((vid, locs))
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=workers)
    try:
        i = 0
        while i < len(targets):
            batch, total = [], 0
            while i < len(targets) and (not batch
                                        or total < max_batch_bytes):
                batch.append(targets[i])
                total += _dat_size(env, *targets[i])
                i += 1
            messages += _encode_batch_group(env, mesh, pool, batch,
                                            chunk_size, progress, codec)
    finally:
        pool.shutdown(wait=False)
    return messages


def _dat_size(env, vid: int, locs: list[str]) -> int:
    for n in env.data_nodes():
        for v in n["volumes"]:
            if v["id"] == vid:
                return int(v["size"])
    return 0


def _fetch_volume(tmpdir: str, vid: int, locs: list[str]) -> str:
    """Freeze + pull one volume's .dat/.idx to local temp files,
    failing over across replicas.  Returns the local base path."""
    base = os.path.join(tmpdir, str(vid))
    errors = []
    for url in locs:
        try:
            if _fault.ARMED:
                _fault.hit("ec.fetch_shard", holder=url, vid=vid)
            rpc.call_to_file(
                f"http://{url}/admin/volume_file?volume={vid}&ext=.idx",
                base + ".idx", headers=rpc.PRIORITY_LOW)
            rpc.call_to_file(
                f"http://{url}/admin/volume_file?volume={vid}&ext=.dat",
                base + ".dat", headers=rpc.PRIORITY_LOW)
            return base
        except Exception as e:  # noqa: BLE001 — next replica
            errors.append(f"{url}: {type(e).__name__}: {e}")
    raise rpc.RpcError(
        502, f"volume {vid}: cannot fetch .dat/.idx: "
             + "; ".join(errors[:4]))


def _encode_batch_group(env, mesh, pool, batch, chunk_size,
                        progress, codec) -> list[str]:
    """Fetch, mesh-encode, scatter one sub-batch of volumes — journaled
    as ec.encode.start/finish with per-stage byte/second attrs, under a
    root span so the timeline row links to a /debug/traces trace."""
    vids = [vid for vid, _locs in batch]
    with root_span("ec.batch_encode", "ec", volumes=len(vids),
                   codec=codec.name):
        emit_event("ec.encode.start", volumes=vids, batch=True,
                   codec=codec.name)
        t0 = time.perf_counter()
        stages: dict[str, list[float]] = {}  # stage -> [seconds, bytes]
        try:
            out = _encode_batch_group_inner(env, mesh, pool, batch,
                                            chunk_size, progress,
                                            stages, codec)
        except Exception as e:
            emit_event("ec.encode.finish", severity="error",
                       volumes=vids, batch=True, codec=codec.name,
                       seconds=round(time.perf_counter() - t0, 6),
                       error=f"{type(e).__name__}: {e}",
                       **stage_attrs(stages))
            raise
        emit_event("ec.encode.finish", volumes=vids, batch=True,
                   codec=codec.name,
                   seconds=round(time.perf_counter() - t0, 6),
                   **stage_attrs(stages))
        return out


def _encode_batch_group_inner(env, mesh, pool, batch, chunk_size,
                              progress, stages, codec) -> list[str]:
    """Fetch, mesh-encode, scatter one sub-batch of volumes."""
    from ..shell.command_ec import balanced_distribution, collect_ec_nodes
    vol_axis = mesh.shape["vol"]
    col_axis = mesh.shape["col"]
    align = _pad_to(_COL_ALIGN, col_axis * 8)
    out: list[str] = []
    with tempfile.TemporaryDirectory(prefix="ec_batch_encode_") as tmp:
        # 1. Freeze every replica, then pull .dat/.idx in parallel.
        for vid, locs in batch:
            for url in locs:
                env.vs_call(url, "/admin/readonly",
                            {"volume": vid, "readonly": True})
        t_fetch = time.perf_counter()
        bases = list(pool.map(
            lambda t: _fetch_volume(tmp, *t), batch))
        observe_batch_stage(
            stages, "batch_fetch", time.perf_counter() - t_fetch,
            sum(os.path.getsize(b + ".dat") for b in bases))

        # 2. Mesh-encode: lockstep stripe chunks across volumes.  Each
        # volume's chunk sequence is the exact local-encoder chunking
        # (byte-identical shards); chunks are stacked on "vol" and
        # column-padded with zeros (parity is columnwise for every
        # codec, so padded columns are discarded zeros, never
        # corruption).
        writers = [_ShardWriter(b, codec.total_shards) for b in bases]
        dats = [open(b + ".dat", "rb") for b in bases]
        try:
            iters = [
                _chunk_reader(d, os.path.getsize(b + ".dat"),
                              LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                              chunk_size)
                for d, b in zip(dats, bases)]
            active = list(range(len(iters)))
            while active:
                chunks, produced = [], []
                for v in active:
                    try:
                        chunks.append(next(iters[v]))
                        produced.append(v)
                    except StopIteration:
                        writers[v].finish()
                active = produced
                if not chunks:
                    break
                widths = [c.shape[1] for c in chunks]
                n_pad = _pad_to(max(widths), align)
                v_pad = _pad_to(len(chunks), vol_axis)
                stacked = np.zeros((v_pad, DATA_SHARDS, n_pad),
                                   np.uint8)
                for j, c in enumerate(chunks):
                    stacked[j, :, :c.shape[1]] = c
                # np.asarray fences the dispatch (device→host copy), so
                # this is execution-fenced device+staging time for the
                # batched GF(2) matmul.
                t_dev = time.perf_counter()
                parity = np.asarray(batched_encode(stacked, mesh,
                                                   codec=codec))
                observe_batch_stage(stages, "batch_encode_device",
                               time.perf_counter() - t_dev,
                               stacked.nbytes)
                for j, v in enumerate(active):
                    writers[v].write(chunks[j],
                                     parity[j, :, :widths[j]])
        finally:
            for d in dats:
                d.close()

        # 3. .ecx from the fetched .idx (WriteSortedFileFromIdx), and
        # a .vif carrying the needle version + codec id — every shard
        # holder must know which generator matrix made its shards.
        for base in bases:
            write_sorted_file_from_idx(base)
            with open(base + ".dat", "rb") as f:
                version = f.read(1)[0]
            update_volume_info(base, version=version, codec=codec.name)

        # 4. Scatter: balanced placement, push shards + .ecx/.vif,
        # mount, then delete the original replicas
        # (command_ec_encode.go flow).
        for (vid, locs), base in zip(batch, bases):
            plan = balanced_distribution(collect_ec_nodes(env),
                                         n_shards=codec.total_shards)
            futs = []
            t_scatter = time.perf_counter()
            scattered = 0
            for url, shards in plan.items():
                for sid in shards:
                    with open(base + to_ext(sid), "rb") as f:
                        payload = f.read()
                    scattered += len(payload)
                    futs.append(pool.submit(
                        _scatter_shard, url, vid, sid, payload))
            for f in futs:
                f.result()
            observe_batch_stage(stages, "batch_scatter",
                           time.perf_counter() - t_scatter, scattered)
            with open(base + ".ecx", "rb") as f:
                ecx = f.read()
            with open(base + ".vif", "rb") as f:
                vif = f.read()
            for url in plan:
                rpc.call(f"http://{url}/admin/ec/receive_file?"
                         f"volume={vid}&ext=.ecx", "POST", ecx, 600.0,
                         headers=rpc.PRIORITY_LOW)
                rpc.call(f"http://{url}/admin/ec/receive_file?"
                         f"volume={vid}&ext=.vif", "POST", vif, 600.0,
                         headers=rpc.PRIORITY_LOW)
                env.vs_call(url, "/admin/ec/mount", {"volume": vid})
            for url in locs:
                env.vs_call(url, "/admin/delete_volume", {"volume": vid})
            line = (f"volume {vid} -> ec shards on {len(plan)} "
                    "servers: "
                    + ", ".join(f"{u}:{s}"
                                for u, s in sorted(plan.items())))
            out.append(line)
            if progress:
                progress(line)
    return out


def _scatter_shard(url: str, vid: int, sid: int,
                   payload: bytes) -> None:
    """Push one encoded shard to its placement target."""
    if _fault.ARMED:
        _fault.hit("ec.scatter", target=url, vid=vid, shard=sid)
    rpc.call(f"http://{url}/admin/ec/receive_shard?"
             f"volume={vid}&shard={sid}", "POST", payload, 600.0,
             headers=rpc.PRIORITY_LOW)


class _ShardWriter:
    """Appends stripe chunks to the codec's local shard files of one
    volume in arrival order — the same order `write_ec_files` writes
    them."""

    def __init__(self, base: str, total_shards: int):
        self.files = [open(base + to_ext(i), "wb")
                      for i in range(total_shards)]

    def write(self, data: np.ndarray, parity: np.ndarray) -> None:
        for i in range(DATA_SHARDS):
            self.files[i].write(data[i].tobytes())
        for p in range(parity.shape[0]):
            self.files[DATA_SHARDS + p].write(parity[p].tobytes())

    def finish(self) -> None:
        for f in self.files:
            f.close()
