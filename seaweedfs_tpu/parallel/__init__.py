"""Multi-chip scaling: mesh-sharded erasure coding with XLA collectives.

Maps the reference's distributed mechanisms onto a TPU pod mesh
(SURVEY.md §2.3 table):

- replica/shard spread across volume servers  -> mesh axes over chips
- parallel remote-shard fetch for reconstruction (store_ec.go:322)
  -> `lax.all_to_all` resharding of survivor rows over ICI
- batched multi-volume rebuild (shell ec.rebuild over many volumes)
  -> one pjit'd batched GF(2) matmul, volumes data-parallel over the mesh
- few-shard rebuild with shard-major survivors
  -> `sharded_codec.ring_reconstruct`: ppermute ring reduce-scatter of
     partial products (the ring-attention rotate-and-accumulate shape);
     moves W·N instead of (K/D)·N per chip — wins for W small
"""
