"""Mesh-sharded batched RS coding — the pod-scale EC engine.

Three entry points, all jittable over a `jax.sharding.Mesh`:

- `batched_encode`:     (V, k, N) -> (V, p, N) parity for V volumes at once.
  Volumes shard over "vol", byte columns over "col"; zero collectives.

- `batched_reconstruct`: (V, S, N) survivor stacks -> (V, W, N) rebuilt
  shards, same sharding story (the driver for `ec.rebuild` of many volumes
  — BASELINE config #3: 256 volumes on a v5e-8).

- `all_to_all_reconstruct`: survivors laid out shard-major (each chip holds
  whole shard rows, as hosts do in a cluster), internally resharded to
  column-major over ICI with `lax.all_to_all` — the SPMD equivalent of the
  reference's parallel remote-shard fetch (store_ec.go:322-376) — then
  decoded locally.  This is the design that scales to pod slices: the
  gather rides ICI, the matmul rides the MXU.

All paths share the plane-major GF(2) bit-matmul from ops/coder_jax.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import rs_bitmatrix
from ..ops.coder_jax import apply_bitmatrix, plane_major

# jax.shard_map landed as a top-level API after 0.4.x; on the 0.4
# toolchain the same function lives under jax.experimental.shard_map.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover — exercised on the 0.4.x image
    from jax.experimental.shard_map import shard_map as _shard_map


def _mm_dtype():
    """Bit-matrix matmul dtype for the batch paths: bf16 feeds the MXU
    on TPU; off-TPU, XLA emulates bf16 slowly in software while f32 is
    exactly as correct for 0/1 bit planes (counts < 2^24 accumulate
    exactly either way) and measured ~1.7x faster on the CPU backend."""
    try:
        platform = jax.devices()[0].platform
    except Exception:  # pragma: no cover — backend init failure
        platform = "cpu"
    return jnp.bfloat16 if platform == "tpu" else jnp.float32


def mm_name() -> str:
    """Roofline dtype label for the batch paths' matmul dtype."""
    return "bf16" if _mm_dtype() == jnp.bfloat16 else "f32"


def record_fenced_batch(kernel: str, codec_name: str, *,
                        out_rows: int, in_rows: int, n: int,
                        batch: int, crc: bool, seconds: float,
                        measured_bytes: int | None = None,
                        node: str = "") -> None:
    """Roofline record for a batched kernel invocation.  The batch
    entry points above return ASYNC device arrays on purpose (fencing
    inside dispatch would serialize the stream pipeline), so the
    caller invokes this from its drain site, AFTER the host
    materialization that fences the kernel — `seconds` must be the
    fenced wall.  Callers gate on `roofline.ARMED` themselves so the
    disarmed cost stays one flag read."""
    try:
        from ..stats import roofline as _roofline
        _roofline.LEDGER.record(
            kernel, codec_name, mm_name(), out_rows=out_rows,
            in_rows=in_rows, n=n, batch=batch, crc=crc,
            seconds=seconds, measured_bytes=measured_bytes, node=node)
    except Exception:  # noqa: BLE001 — accounting never breaks encode
        pass


def _codec_of(data_shards: int, parity_shards: int, matrix_kind: str,
              codec):
    """Resolve the scheme: an explicit codec wins, else ad-hoc RS from
    the shard-count arguments (the pre-codec call signature)."""
    from ..codecs import get_codec, rs_codec
    if codec is None:
        return rs_codec(data_shards, parity_shards, matrix_kind)
    return get_codec(codec)


def _parity_pm(data_shards: int, parity_shards: int,
               kind: str = "vandermonde") -> np.ndarray:
    pb = rs_bitmatrix.parity_bitmatrix(
        data_shards, data_shards + parity_shards, kind)
    return plane_major(pb, parity_shards, data_shards)


@functools.partial(jax.jit, static_argnames=("parity_shards",))
def _encode_batch(bmat_pm, data, parity_shards: int):
    return jax.vmap(lambda d: apply_bitmatrix(bmat_pm, d, parity_shards))(data)


def _check_mesh_divisible(mesh: Mesh, v: int, n: int) -> None:
    if v % mesh.shape["vol"]:
        raise ValueError(
            f"batch of {v} volumes must divide over vol axis "
            f"{mesh.shape['vol']}")
    if n % mesh.shape["col"]:
        raise ValueError(
            f"byte width {n} must divide over col axis "
            f"{mesh.shape['col']}")


def _local_map(fn, mesh: Mesh):
    """shard_map a (bmat, (V_loc, R, N_loc)) -> pytree-of-(V_loc, *,
    N_loc) volume-batch function over the ("vol", "col") mesh: the bit
    matrix rides along replicated, data shards over volumes/columns.
    Every chip computes ONLY its own volume/column block — by
    construction there are ZERO collectives in the lowered program
    (asserted by tests/test_ecpipe.py on the compiled HLO).  check_rep
    is off: no output claims replication, and the 0.4.x rep-rewriter
    chokes on jitted decode matrices.

    Callers MUST route through the `_mapped_*` lru_cached factories
    below (never wrap a fresh closure per call): jax.jit caches by
    callable identity, so an uncached wrapper would retrace + XLA
    compile on EVERY dispatched chunk batch of the stream pipeline."""
    try:
        mapped = _shard_map(fn, mesh=mesh,
                            in_specs=(P(None, None),
                                      P("vol", None, "col")),
                            out_specs=P("vol", None, "col"),
                            check_rep=False)
    except TypeError:  # pragma: no cover — newer API dropped check_rep
        mapped = _shard_map(fn, mesh=mesh,
                            in_specs=(P(None, None),
                                      P("vol", None, "col")),
                            out_specs=P("vol", None, "col"))
    return jax.jit(mapped)


@functools.lru_cache(maxsize=64)
def _mapped_encode(mesh: Mesh, parity_shards: int):
    return _local_map(
        lambda bmat, d: _encode_batch(bmat, d, parity_shards), mesh)


@functools.lru_cache(maxsize=64)
def _mapped_reconstruct(mesh: Mesh, wanted_count: int):
    return _local_map(
        lambda pm, s: _reconstruct_batch(pm, s, wanted_count), mesh)


def _crc_local(parity_shards: int, tile: int, block: int):
    from ..ops import crc_fold

    def fn(bmat, d):
        parity = _encode_batch(bmat, d, parity_shards)
        rows = jnp.concatenate([d, parity], axis=1)
        crcs = jax.vmap(
            lambda r: crc_fold.block_crcs_jnp(r, tile, block))(rows)
        return parity, crcs
    return fn


@functools.lru_cache(maxsize=64)
def _mapped_encode_crc(mesh: Mesh | None, parity_shards: int,
                       tile: int, block: int):
    fn = _crc_local(parity_shards, tile, block)
    if mesh is None:
        return jax.jit(fn)
    return _local_map(fn, mesh)


def _crc_reconstruct_local(wanted_count: int, tile: int, block: int):
    from ..ops import crc_fold

    def fn(pm, s):
        rebuilt = _reconstruct_batch(pm, s, wanted_count)
        crcs = jax.vmap(
            lambda r: crc_fold.block_crcs_jnp(r, tile, block))(rebuilt)
        return rebuilt, crcs
    return fn


@functools.lru_cache(maxsize=64)
def _mapped_reconstruct_crc(mesh: Mesh | None, wanted_count: int,
                            tile: int, block: int):
    fn = _crc_reconstruct_local(wanted_count, tile, block)
    if mesh is None:
        return jax.jit(fn)
    return _local_map(fn, mesh)


def batched_encode(data, mesh: Mesh | None = None,
                   data_shards: int = 10, parity_shards: int = 4,
                   matrix_kind: str = "vandermonde", codec=None):
    """(V, data_shards, N) uint8 -> (V, parity_shards, N) parity.

    With a mesh the batch runs under `shard_map` on the ("vol", "col")
    axes: volumes data-parallel over "vol", byte columns over "col",
    each chip encoding its own block with zero collectives (parity is
    columnwise for every codec, so no cross-chip bytes exist to move).
    `codec` swaps the generator matrix (e.g. "lrc"); the kernel and
    sharding story are identical.
    """
    cd = _codec_of(data_shards, parity_shards, matrix_kind, codec)
    bmat = jnp.asarray(
        plane_major(cd.parity_bitmatrix(), cd.parity_shards,
                    cd.data_shards), _mm_dtype())
    data = jnp.asarray(data, jnp.uint8)
    if mesh is None:
        return _encode_batch(bmat, data, cd.parity_shards)
    _check_mesh_divisible(mesh, data.shape[0], data.shape[2])
    data = jax.device_put(
        data, NamedSharding(mesh, P("vol", None, "col")))
    return _mapped_encode(mesh, cd.parity_shards)(bmat, data)


def batched_encode_with_crc(data, mesh: Mesh | None = None,
                            codec=None, crc_tile: int | None = None):
    """batched_encode plus per-`.ecc`-block CRC32-C of EVERY shard row
    (data rows first, then parity), computed on device in the same
    compiled step (ops/crc_fold.py).

    data: (V, k, N) uint8 with N a multiple of the `.ecc` block
    (1MB) times the mesh col axis — zero-padded tail blocks simply
    yield the crc of a zero block and are sliced off by true width.
    Returns (parity (V, p, N) uint8, crcs (V, k+p, N//BLOCK) uint32).
    """
    from ..ops import crc_fold
    cd = _codec_of(10, 4, "vandermonde", codec)
    bmat = jnp.asarray(
        plane_major(cd.parity_bitmatrix(), cd.parity_shards,
                    cd.data_shards), _mm_dtype())
    tile = crc_tile or crc_fold.JNP_TILE
    data = jnp.asarray(data, jnp.uint8)
    v, _k, n = data.shape
    block = crc_fold.BLOCK
    cols = mesh.shape["col"] if mesh is not None else 1
    if n % (block * cols):
        raise ValueError(
            f"byte width {n} must be a multiple of the .ecc block "
            f"{block} x col axis {cols}")

    fn = _mapped_encode_crc(mesh, cd.parity_shards, tile, block)
    if mesh is None:
        return fn(bmat, data)
    _check_mesh_divisible(mesh, v, n)
    data = jax.device_put(
        data, NamedSharding(mesh, P("vol", None, "col")))
    return fn(bmat, data)


@functools.partial(jax.jit, static_argnames=("wanted_count",))
def _reconstruct_batch(bmat_pm, stacked, wanted_count: int):
    return jax.vmap(
        lambda s: apply_bitmatrix(bmat_pm, s, wanted_count))(stacked)


def batched_reconstruct(stacked, present: tuple[int, ...],
                        wanted: tuple[int, ...],
                        mesh: Mesh | None = None,
                        data_shards: int = 10, parity_shards: int = 4,
                        matrix_kind: str = "vandermonde", codec=None):
    """Rebuild `wanted` shards for V volumes that all lost the same shards.

    stacked: (V, len(used), N) — the codec's `used` survivor rows
    (codec.decode_matrix(present, wanted)[1], stacked in that order)
    for each volume; for RS that is the first data_shards survivors
    sorted by id, for LRC the planned minimal read set (5 rows for an
    in-group loss).  Returns (V, len(wanted), N).
    """
    cd = _codec_of(data_shards, parity_shards, matrix_kind, codec)
    bmat, used = cd.decode_bitmatrix(tuple(present), tuple(wanted))
    pm = jnp.asarray(plane_major(np.asarray(bmat), len(wanted), len(used)),
                     _mm_dtype())
    stacked = jnp.asarray(stacked, jnp.uint8)
    if stacked.shape[1] != len(used):
        raise ValueError(
            f"stacked must carry the {len(used)} used survivor rows "
            f"({[int(u) for u in used]}), got {stacked.shape[1]}")
    if mesh is None:
        return _reconstruct_batch(pm, stacked, len(wanted))
    _check_mesh_divisible(mesh, stacked.shape[0], stacked.shape[2])
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P("vol", None, "col")))
    return _mapped_reconstruct(mesh, len(wanted))(pm, stacked)


def batched_reconstruct_with_crc(stacked, present: tuple[int, ...],
                                 wanted: tuple[int, ...],
                                 mesh: Mesh | None = None, codec=None,
                                 crc_tile: int | None = None):
    """batched_reconstruct plus per-`.ecc`-block CRC32-C of every
    REBUILT row, on device in the same compiled step — the scatter
    ships ready-made sidecar entries instead of each holder re-reading
    the pushed bytes.  Returns (rebuilt (V, W, N) uint8,
    crcs (V, W, N//BLOCK) uint32).  N must be a multiple of the `.ecc`
    block times the mesh col axis."""
    from ..ops import crc_fold
    cd = _codec_of(10, 4, "vandermonde", codec)
    bmat, used = cd.decode_bitmatrix(tuple(present), tuple(wanted))
    pm = jnp.asarray(plane_major(np.asarray(bmat), len(wanted), len(used)),
                     _mm_dtype())
    tile = crc_tile or crc_fold.JNP_TILE
    stacked = jnp.asarray(stacked, jnp.uint8)
    if stacked.shape[1] != len(used):
        raise ValueError(
            f"stacked must carry the {len(used)} used survivor rows "
            f"({[int(u) for u in used]}), got {stacked.shape[1]}")
    v, _s, n = stacked.shape
    block = crc_fold.BLOCK
    cols = mesh.shape["col"] if mesh is not None else 1
    if n % (block * cols):
        raise ValueError(
            f"byte width {n} must be a multiple of the .ecc block "
            f"{block} x col axis {cols}")

    fn = _mapped_reconstruct_crc(mesh, len(wanted), tile, block)
    if mesh is None:
        return fn(pm, stacked)
    _check_mesh_divisible(mesh, v, n)
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P("vol", None, "col")))
    return fn(pm, stacked)


def _shard_major_prep(stacked, present, wanted, mesh,
                      data_shards, parity_shards, matrix_kind):
    """Shared prologue for the shard-major reconstruction paths:
    decode bit-matrix in plane-major bf16, survivors validated and
    placed (vol, col, None) on the mesh.  Returns
    (pm, stacked, n_axis_chips, chunk_bytes)."""
    total = data_shards + parity_shards
    bmat, _used = rs_bitmatrix.decode_bitmatrix(
        data_shards, total, tuple(present), tuple(wanted), matrix_kind)
    pm = jnp.asarray(plane_major(np.asarray(bmat), len(wanted),
                                 data_shards), _mm_dtype())
    n_axis = mesh.shape["col"]
    if data_shards % n_axis != 0:
        raise ValueError(
            f"data_shards {data_shards} must divide over mesh col axis "
            f"{n_axis}")
    stacked = jnp.asarray(stacked, jnp.uint8)
    _v, s, n = stacked.shape
    if s != data_shards:
        raise ValueError(
            f"stacked must carry the {data_shards} used survivor rows, "
            f"got {s}")
    if n % n_axis != 0:
        raise ValueError(f"byte length {n} must divide over {n_axis}")
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P("vol", "col", None)))
    return pm, stacked, n_axis, n // n_axis


def all_to_all_reconstruct(stacked, present: tuple[int, ...],
                           wanted: tuple[int, ...], mesh: Mesh,
                           data_shards: int = 10, parity_shards: int = 4,
                           matrix_kind: str = "vandermonde"):
    """Reconstruction when survivors live shard-major on the mesh.

    stacked: (V, data_shards, N) placed with the *shard* axis sharded over
    the mesh's "col" axis — each chip holds complete rows (= whole shards),
    the cluster-natural layout after DMAing shards from their home hosts.
    Internally `lax.all_to_all` swaps shard-axis for column-axis over ICI
    (every chip sends each other chip its rows' slice of their columns),
    then each chip solves its column block locally and the output comes
    back column-sharded.
    """
    pm, stacked, n_shard_chips, _chunk = _shard_major_prep(
        stacked, present, wanted, mesh, data_shards, parity_shards,
        matrix_kind)
    wanted_count = len(wanted)
    s = data_shards

    def local(block):  # block: (v_loc, s/D, N) on each chip
        # Reshard: split columns D-ways, trade shard rows for column blocks.
        v_loc, s_loc, n_full = block.shape
        chunk = n_full // n_shard_chips
        parts = block.reshape(v_loc, s_loc, n_shard_chips, chunk)
        # all_to_all: concat shard axis, split column axis. -> (v, s, chunk)
        gathered = jax.lax.all_to_all(
            parts, "col", split_axis=2, concat_axis=1, tiled=False)
        gathered = gathered.reshape(v_loc, s, chunk)
        out = jax.vmap(
            lambda x: apply_bitmatrix(pm, x, wanted_count))(gathered)
        return out  # (v_loc, wanted, chunk) — column-sharded result

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=P("vol", "col", None),
        out_specs=P("vol", None, "col")))
    return fn(stacked)


def ring_reconstruct(stacked, present: tuple[int, ...],
                     wanted: tuple[int, ...], mesh: Mesh,
                     data_shards: int = 10, parity_shards: int = 4,
                     matrix_kind: str = "vandermonde"):
    """Ring-pipelined reconstruction: ppermute reduce-scatter of partial
    GF(2) products — the storage-domain analog of ring attention's
    rotate-and-accumulate (SURVEY §5 long-context mapping).

    Same input layout as `all_to_all_reconstruct` (survivor rows
    shard-major over the mesh "col" axis), but instead of resharding the
    SURVIVORS, each chip multiplies only its local rows against the
    matching column slice of the decode matrix — GF(2) linearity makes
    the full output the XOR of these partials — and the PARTIAL OUTPUTS
    ride the ring: D-1 `lax.ppermute` hops, each overlapping the next
    local XOR, until every chip holds the fully-reduced chunk for its
    own column slice.

    Traffic per chip: ring moves (D-1)/D · W·N partial bytes vs
    all_to_all's (D-1)/D · (K/D)·N survivor bytes — ring wins when
    W < K/D, i.e. rebuilding FEW shards on a SMALL mesh axis: the
    common `ec.rebuild` of one lost shard (W=1) moves 2.5x less than
    all_to_all on a D=4 axis at K=10.  Compute is also strictly local:
    each chip does 1/D of the matmul, no redundant work.
    """
    pm, stacked, n_ring, chunk = _shard_major_prep(
        stacked, present, wanted, mesh, data_shards, parity_shards,
        matrix_kind)
    wanted_count = len(wanted)
    rows_local = data_shards // n_ring

    # Plane-major columns are s*K + j; reshaped (8W, 8, K) the last axis
    # is the input-shard index, so a chip's row block [d*L, (d+1)*L) is
    # one dynamic slice.
    pm3 = pm.reshape(8 * wanted_count, 8, data_shards)

    def local(block):  # (v_loc, rows_local, N) on each chip
        d = jax.lax.axis_index("col")
        pm_local = jax.lax.dynamic_slice(
            pm3, (0, 0, d * rows_local),
            (8 * wanted_count, 8, rows_local)
        ).reshape(8 * wanted_count, 8 * rows_local)

        def partial_one(rows):  # (rows_local, N) -> (W, N) partial bytes
            return apply_bitmatrix(pm_local, rows, wanted_count)
        partial = jax.vmap(partial_one)(block)  # (v_loc, W, N)

        def take(idx):  # column chunk `idx` of the partial
            return jax.lax.dynamic_slice(
                partial, (0, 0, idx * chunk),
                (partial.shape[0], wanted_count, chunk))

        perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]
        # Ring reduce-scatter over XOR: the acc created on chip j
        # targets chunk (j-1); after D-1 hops it lands on its target
        # having absorbed every chip's contribution exactly once.
        acc = take((d - 1) % n_ring)

        def step(t, acc):
            acc = jax.lax.ppermute(acc, "col", perm)
            return jnp.bitwise_xor(acc, take((d - t - 1) % n_ring))
        acc = jax.lax.fori_loop(1, n_ring, step, acc)
        return acc  # chip d holds the reduced chunk d

    fn = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=P("vol", "col", None),
        out_specs=P("vol", None, "col")))
    return fn(stacked)


def assert_no_collectives(mesh: Mesh, parity_shards: int,
                          shape: tuple[int, int, int]) -> str:
    """Compile the sharded batch-encode step for `shape` and assert the
    HLO contains no cross-chip collectives — parity and CRCs are
    columnwise, so no cross-chip bytes should exist to move.  Shared by
    the ecpipe test suite and bench_e2e's MULTICHIP row (one copy, one
    collective-name list).  Returns the HLO text."""
    import re

    from ..codecs import get_codec

    cd = get_codec("rs")
    bmat = jnp.asarray(
        plane_major(cd.parity_bitmatrix(), parity_shards,
                    cd.data_shards), _mm_dtype())
    fn = _mapped_encode(mesh, parity_shards)
    hlo = fn.lower(bmat, jax.ShapeDtypeStruct(shape, np.uint8)) \
        .compile().as_text()
    found = re.search(
        r"all-reduce|all-gather|all-to-all|collective-permute|"
        r"reduce-scatter", hlo)
    if found:
        raise AssertionError(
            f"collective found in sharded encode HLO: {found.group(0)}")
    return hlo
