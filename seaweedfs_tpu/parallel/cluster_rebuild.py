"""Cluster-integrated batched EC rebuild: many volumes, one mesh step.

This is the production bridge between the cluster RPC world and the
mesh codec (`sharded_codec.batched_reconstruct`): gather survivor
shards from their volume-server holders over HTTP, stack volumes on
the `vol` mesh axis, rebuild EVERY missing shard of EVERY volume in
one jitted GF(2) bit-matmul per survivor-signature group, then scatter
the rebuilt shards back onto cluster nodes and mount them.

The reference rebuilds one volume at a time on one node
(weed/shell/command_ec_rebuild.go:57 — copy survivors to a rebuilder,
local Go RS decode, weed/storage/store_ec.go:322-376); here the decode
is batched over a `jax.sharding.Mesh` so a 256-volume rebuild is a
handful of compiled steps with volumes data-parallel over chips and
byte columns sharded over the `col` axis (BASELINE configs #3/#5).

Shell entry point: `ec.rebuild -batch` (shell/command_ec.py).
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import dataclass, field

import numpy as np

from ..cluster import rpc
from ..codecs import Codec, get_codec
from ..ec import SMALL_BLOCK_SIZE
from ..ec.shard_bits import ShardBits
from ..events import emit as emit_event
from ..fault import registry as _fault
from ..stats import flows as _flows
from ..stats import roofline as _roofline
from ..stats.metrics import (ec_repair_read_bytes_total,
                             observe_batch_stage, stage_attrs)
from ..trace import root_span
from ..utils import env_float as _env_float
from .sharded_codec import (batched_reconstruct,
                            batched_reconstruct_with_crc,
                            record_fenced_batch)
from .stream_pipeline import PipelineRecorder, run_pipeline

# Column padding granularity: keeps the jitted matmul's N divisible by
# the mesh col axis and lane-aligned (128 lanes) for any mesh <= 16 wide.
_COL_ALIGN = 2048


# Shard-fetch budgets: each holder attempt gets a bounded slice of a
# total per-shard deadline, so one dead holder costs one attempt
# timeout — never a 600s hang that stalls the whole batch (the old
# behavior: a single all-purpose 600s timeout per call).
FETCH_ATTEMPT_TIMEOUT = _env_float(
    "SEAWEEDFS_TPU_EC_FETCH_TIMEOUT", 30.0)
FETCH_TOTAL_DEADLINE = _env_float(
    "SEAWEEDFS_TPU_EC_FETCH_DEADLINE", 180.0)


def make_mesh(devices=None):
    """Default rebuild mesh over the available chips: volumes
    data-parallel on "vol", byte columns on "col"."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    col = 2 if n % 2 == 0 else 1
    vol = n // col
    return Mesh(np.array(devices[:vol * col]).reshape(vol, col),
                ("vol", "col"))


@dataclass
class RebuildPlan:
    """Volumes grouped by (codec, survivor signature): every volume in
    a group shares a codec and lost the same shards, so one decode
    matrix (and one compiled step) covers the whole group."""

    groups: dict[tuple[str, tuple[int, ...], tuple[int, ...]],
                 list[tuple[int, dict[int, list[str]]]]] = \
        field(default_factory=dict)
    skipped: list[tuple[int, str]] = field(default_factory=list)


def plan_rebuilds(env, vids=None) -> RebuildPlan:
    """Group rebuildable EC volumes by (codec, present, missing).
    Shard counts and decodability derive from each volume's codec —
    a mixed-codec cluster must never plan an LRC volume with RS
    literals (or vice versa).  Codec ids come from the /vol/list
    payload already in hand (heartbeats put "codec" on every ec_shards
    entry), with env.ec_codec(vid) as the per-volume fallback; a
    volume whose codec cannot be DETERMINED is skipped, never guessed
    — decoding LRC shards with RS matrices would scatter silently
    corrupt bytes cluster-wide."""
    plan = RebuildPlan()
    codecs: dict[int, str] = {}
    try:
        nodes = env.data_nodes()
    except Exception:  # noqa: BLE001 — fall back to per-vid lookups
        nodes = []
    for n in nodes:
        for e in n.get("ec_shards", []):
            if e.get("codec"):
                codecs[e["id"]] = e["codec"]
    if vids is None:
        vids = sorted({e["id"] for n in nodes for e in n["ec_shards"]})
    for vid in vids:
        name = codecs.get(vid)
        if name is None:
            getter = getattr(env, "ec_codec", None)
            if getter is None:  # duck-typed env predating codecs: rs
                name = "rs"
            else:
                try:
                    name = getter(vid) or "rs"
                except Exception as e:  # noqa: BLE001 — master hiccup
                    plan.skipped.append(
                        (vid, f"cannot determine codec: "
                              f"{type(e).__name__}: {e}"))
                    continue
        try:
            codec = get_codec(name)
        except ValueError:
            plan.skipped.append((vid, f"unknown codec {name!r}"))
            continue
        locs = env.ec_shard_locations(vid)
        present = tuple(sorted(locs))
        missing = tuple(s for s in range(codec.total_shards)
                        if s not in locs)
        if not missing:
            continue
        try:
            codec.repair_plan(present, list(missing))
        except ValueError:
            plan.skipped.append(
                (vid, f"only {len(present)} shards survive "
                      f"({codec.name}: unrecoverable pattern)"))
            continue
        plan.groups.setdefault((codec.name, present, missing),
                               []).append((vid, locs))
    return plan


def plan_repair_reads(codec: Codec, present, missing) -> dict:
    """Repair-bandwidth plan for one volume: per-missing-shard minimal
    read sets (local group first, global fallback) plus the
    planned-vs-RS accounting the rebuild reports — RS(k) reads
    data_shards survivors once to rebuild everything, so the saving is
    union-of-planned-reads vs data_shards."""
    plans = codec.repair_plan(tuple(present), list(missing))
    union: set[int] = set()
    for p in plans:
        union.update(p.reads)
    return {
        "codec": codec.name,
        "reads": {p.sid: list(p.reads) for p in plans},
        "union_reads": sorted(union),
        "planned_read_shards": len(union),
        "rs_read_shards": codec.data_shards,
        "local_repairs": sum(1 for p in plans if p.local),
    }


def _fetch_shard(holders: list[str], vid: int, sid: int,
                 attempt_timeout: float | None = None,
                 total_deadline: float | None = None) -> bytes:
    """Fetch one shard, failing over across EVERY holder of it (the
    reference read path walks all sourceDataNodes,
    store_ec.go:264-320) with a second retry round for transient
    errors — one flaky node must not fail a whole batch.

    Every holder attempt runs under `attempt_timeout`, and all attempts
    together under `total_deadline`: a dead holder costs one bounded
    attempt before failover, and a shard with only dead holders fails
    the batch within the deadline instead of hanging it."""
    attempt_timeout = attempt_timeout or FETCH_ATTEMPT_TIMEOUT
    total_deadline = total_deadline or FETCH_TOTAL_DEADLINE
    deadline = time.monotonic() + total_deadline
    errors: list[str] = []
    permanent: set[str] = set()
    for attempt in range(2):
        for url in holders:
            if url in permanent:
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                errors.append(f"deadline {total_deadline:g}s exhausted")
                raise rpc.RpcError(
                    502, f"shard {vid}.{sid} unreachable within "
                         f"deadline: " + "; ".join(errors[:6]))
            try:
                if _fault.ARMED:
                    _fault.hit("ec.fetch_shard", holder=url, vid=vid,
                               shard=sid)
                data = rpc.call(
                    f"http://{url}/admin/ec/shard_file?volume={vid}"
                    f"&shard={sid}",
                    timeout=min(attempt_timeout, remaining),
                    headers={**rpc.PRIORITY_LOW,
                             **_flows.tag("ec.gather")})
                if not isinstance(data, (bytes, bytearray)):
                    raise rpc.RpcError(
                        410, f"shard {vid}.{sid}: non-binary reply")
                return bytes(data)
            except rpc.RpcError as e:
                # A definitive HTTP answer (4xx: the holder does not
                # have the shard) will not change on a retry — but a
                # 429 admission shed is the holder saying "later", not
                # "never": keep it in the failover rotation.
                if (400 <= e.status < 500 or e.status == 410) \
                        and e.status != 429:
                    permanent.add(url)
                errors.append(f"{url} (try {attempt + 1}): {e}")
            except Exception as e:  # noqa: BLE001 — transient: next
                errors.append(
                    f"{url} (try {attempt + 1}): {type(e).__name__}: {e}")
    raise rpc.RpcError(
        502, f"shard {vid}.{sid} unreachable on any holder: "
             + "; ".join(errors[:6]))


class _TargetPicker:
    """Free-slot balanced placement for rebuilt shards, preferring nodes
    that hold nothing of the volume (maximises survivors on node loss —
    the same objective as balancedEcDistribution)."""

    def __init__(self, env):
        self.free: dict[str, int] = {}
        for n in env.data_nodes():
            held = sum(ShardBits(e["shard_bits"]).shard_id_count()
                       for e in n["ec_shards"])
            free = n["max_volume_count"] * 10 - len(n["volumes"]) * 10 \
                - held
            self.free[n["url"]] = max(free, 0)

    def pick(self, holders: set[str]) -> str:
        if not self.free:
            raise rpc.RpcError(503, "no data nodes for rebuilt shards")
        fresh = {u: f for u, f in self.free.items() if u not in holders}
        pool = fresh if any(f > 0 for f in fresh.values()) else self.free
        url = max(pool, key=lambda u: pool[u])
        self.free[url] -= 1
        return url


def _pad_to(n: int, align: int) -> int:
    return -(-n // align) * align


def batch_rebuild(env, vids=None, mesh=None, max_batch_bytes=1 << 28,
                  workers: int = 16, matrix_kind: str = "vandermonde",
                  progress=None, depth: int | None = None) -> list[str]:
    """Rebuild all missing EC shards across the cluster in mesh-batched
    compiled steps.  Returns one human-readable line per volume.
    `depth` overrides the stream-pipeline depth (0 = serialized).

    env: duck-typed cluster view (shell CommandEnv): ec_shard_locations,
    data_nodes, vs_call.
    """
    plan = plan_rebuilds(env, vids)
    messages = [f"volume {vid}: SKIPPED — {why}; cannot rebuild"
                for vid, why in plan.skipped]
    if not plan.groups:
        return messages
    if mesh is None:
        mesh = make_mesh()
    picker = _TargetPicker(env)
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=workers)
    try:
        for (codec_name, present, missing), entries in \
                sorted(plan.groups.items()):
            messages += _rebuild_group(
                env, mesh, pool, picker, get_codec(codec_name),
                present, missing, entries, max_batch_bytes,
                matrix_kind, progress, depth)
    finally:
        # cancel_futures: a failed group must not leave queued shard
        # fetches/pushes running (and holders busy) after the
        # exception has unwound.
        pool.shutdown(wait=False, cancel_futures=True)
    return messages


def _rebuild_group(env, mesh, pool, picker, codec, present, missing,
                   entries, max_batch_bytes, matrix_kind,
                   progress, depth: int | None = None) -> list[str]:
    """One (codec, survivor-signature) group — journaled as
    ec.rebuild.start/finish with per-stage byte/second attrs plus the
    planner's planned-vs-RS read accounting, under a root span so the
    timeline row links to a /debug/traces trace."""
    vids = [vid for vid, _locs in entries]
    report = plan_repair_reads(codec, present, missing)
    with root_span("ec.batch_rebuild", "ec", volumes=len(vids),
                   missing=list(missing), codec=codec.name):
        emit_event("ec.rebuild.start", volumes=vids, batch=True,
                   missing=list(missing), codec=codec.name,
                   planned_read_shards=report["planned_read_shards"],
                   rs_read_shards=report["rs_read_shards"])
        t0 = time.perf_counter()
        stages: dict[str, list[float]] = {}  # stage -> [seconds, bytes]
        try:
            out = _rebuild_group_inner(env, mesh, pool, picker, codec,
                                       present, missing, entries,
                                       max_batch_bytes, matrix_kind,
                                       progress, stages, report, depth)
        except Exception as e:
            emit_event("ec.rebuild.finish", severity="error",
                       volumes=vids, batch=True, missing=list(missing),
                       codec=codec.name,
                       seconds=round(time.perf_counter() - t0, 6),
                       error=f"{type(e).__name__}: {e}",
                       **stage_attrs(stages))
            raise
        emit_event("ec.rebuild.finish", volumes=vids, batch=True,
                   missing=list(missing), codec=codec.name,
                   planned_read_shards=report["planned_read_shards"],
                   rs_read_shards=report["rs_read_shards"],
                   seconds=round(time.perf_counter() - t0, 6),
                   **stage_attrs(stages))
        return out


def _rebuild_group_inner(env, mesh, pool, picker, codec, present,
                         missing, entries, max_batch_bytes, matrix_kind,
                         progress, stages, report,
                         depth: int | None = None) -> list[str]:
    """Streamed rebuild of one survivor-signature group: the producer
    gathers + stacks the NEXT sub-batch's shards over HTTP while the
    device decodes the current one and the drain thread scatters
    completed shards — gather, decode and scatter overlap instead of
    serializing (stream_pipeline.py; sums of the batch_* stage
    histograms exceed the wall clock when the overlap is working)."""
    from .cluster_encode import fused_crc_enabled, pipeline_depth
    # The codec's planned read set, not "first data_shards survivors":
    # an in-group LRC loss gathers 5 shards per volume instead of 10.
    _mat, used = codec.decode_matrix(present, missing)
    all_local = bool(report["local_repairs"]) and \
        report["local_repairs"] == len(missing)
    vol_axis = mesh.shape["vol"]
    col_axis = mesh.shape["col"]
    fused = fused_crc_enabled()
    block = SMALL_BLOCK_SIZE
    align = block * col_axis if fused \
        else _pad_to(_COL_ALIGN, col_axis * 8)
    depth = pipeline_depth(depth)
    out: list[str] = []
    saved = f" ({codec.name}: read {len(used)} shards vs " \
            f"{codec.data_shards} for RS)" \
        if len(used) < codec.data_shards else ""

    # Always-on (bounded) production recorder: per-batch stage spans
    # feed the roofline plane's occupancy/gantt surfaces.
    rec = PipelineRecorder(maxlen=1024) if _roofline.ARMED else None

    def produce():
        i = 0
        bi = 0
        while i < len(entries):
            # Probe the first volume's shard size to bound the
            # sub-batch.
            t_gather = time.perf_counter()
            vid0, locs0 = entries[i]
            rows0 = _fetch_rows(pool, vid0, locs0, used)
            shard_bytes = len(rows0[0])
            per_vol = shard_bytes * (len(used) + len(missing))
            chunk_v = max(1, min(len(entries) - i,
                                 int(max_batch_bytes
                                     // max(per_vol, 1))))
            chunk = entries[i:i + chunk_v]
            # Flat fan-out of every (volume, shard) fetch — nested
            # submits from inside pool workers would deadlock a
            # bounded pool.
            futs = [[pool.submit(_fetch_shard, locs[sid], vid, sid)
                     for sid in used] for vid, locs in chunk[1:]]
            fetched = [rows0] + [[f.result() for f in row]
                                 for row in futs]
            gathered = sum(len(row) for rows in fetched for row in rows)
            ec_repair_read_bytes_total.inc(gathered, codec=codec.name)
            sizes = [len(rows[0]) for rows in fetched]
            n_pad = _pad_to(max(sizes), align)
            v_pad = _pad_to(len(chunk), vol_axis)
            stacked = np.zeros((v_pad, len(used), n_pad), np.uint8)
            for v, rows in enumerate(fetched):
                for r, row in enumerate(rows):
                    if len(row) != sizes[v]:
                        raise rpc.RpcError(
                            502, f"volume {chunk[v][0]}: survivor "
                            f"shards disagree on size "
                            f"({len(row)} vs {sizes[v]})")
                    stacked[v, r, :len(row)] = np.frombuffer(row,
                                                             np.uint8)
            t_gend = time.perf_counter()
            observe_batch_stage(stages, "batch_gather",
                                t_gend - t_gather, gathered)
            if rec is not None:
                rec.note_span("stack", bi, t_gather, t_gend)
            yield (stacked, chunk, sizes, bi)
            bi += 1
            i += chunk_v

    def dispatch(item):
        stacked, chunk, sizes, bi = item
        t_d0 = time.perf_counter()
        # Device CRCs for the rebuilt rows ride along when every shard
        # in the sub-batch covers whole `.ecc` blocks (they always do:
        # shard files are 1MB-block padded by construction).
        use_crc = fused and all(s % block == 0 for s in sizes)
        if use_crc:
            rebuilt, crcs = batched_reconstruct_with_crc(
                stacked, present, missing, mesh, codec=codec)
        else:
            rebuilt = batched_reconstruct(
                stacked, present, missing, mesh,
                matrix_kind=matrix_kind, codec=codec)
            crcs = None
        t_d1 = time.perf_counter()
        if rec is not None:
            rec.note_span("dispatch", bi, t_d0, t_d1)
        return (rebuilt, crcs, chunk, sizes, stacked.nbytes, bi,
                t_d0, t_d1)

    def drain(handle):
        rebuilt, crcs, chunk, sizes, nbytes, bi, t_d0, t_d1 = handle
        # np.asarray fences the dispatch — the EXPOSED device wait.
        t_dev = time.perf_counter()
        rebuilt = np.asarray(rebuilt)
        if crcs is not None:
            crcs = np.asarray(crcs)
        t_fence = time.perf_counter()
        observe_batch_stage(stages, "batch_rebuild_device",
                            t_fence - t_dev, nbytes)
        if rec is not None:
            rec.note_span("device", bi, t_d1, t_fence)
        if _roofline.ARMED:
            record_fenced_batch(
                "batch_reconstruct", codec.name,
                out_rows=int(rebuilt.shape[1]),
                in_rows=len(used), n=int(rebuilt.shape[2]),
                batch=int(rebuilt.shape[0]), crc=crcs is not None,
                seconds=t_fence - t_d0,
                measured_bytes=int(nbytes) + rebuilt.nbytes)
        t_scatter = time.perf_counter()
        scattered = 0
        for v, (vid, locs) in enumerate(chunk):
            shards = [rebuilt[v, m, :sizes[v]].tobytes()
                      for m in range(len(missing))]
            scattered += sum(len(s) for s in shards)
            shard_crcs = None
            if crcs is not None:
                nb = sizes[v] // block
                shard_crcs = [[int(c) for c in crcs[v, m, :nb]]
                              for m in range(len(missing))]
            placed = _scatter_volume(
                env, pool, picker, vid, locs, missing, shards,
                shard_crcs=shard_crcs)
            if all_local:
                emit_event("ec.repair.local", vid=vid,
                           codec=codec.name, shard=list(missing),
                           reads=len(used),
                           bytes=sizes[v] * len(used))
            out.append(f"volume {vid}: rebuilt shards "
                       f"{list(missing)} -> " +
                       ", ".join(f"{s}@{u}" for s, u in placed)
                       + saved)
            if progress:
                progress(out[-1])
        t_send = time.perf_counter()
        observe_batch_stage(stages, "batch_scatter",
                            t_send - t_scatter, scattered)
        if rec is not None:
            rec.note_span("drain", bi, t_scatter, t_send)

    run_pipeline(produce(), dispatch, drain, depth=depth, recorder=rec)
    if rec is not None:
        _roofline.LEDGER.note_pipeline("rebuild", rec)
    return out


def _fetch_rows(pool, vid, locs, used) -> list[bytes]:
    """Parallel-fetch the `used` survivor shards of one volume (each
    failing over across its holders) — the client-side analog of the
    reference's parallel shard reads (store_ec.go:322-376)."""
    futs = [pool.submit(_fetch_shard, locs[sid], vid, sid)
            for sid in used]
    return [f.result() for f in futs]


def _push_shard(vid: int, sid: int, payload: bytes, target: str,
                sources: list[str], ecc_push=None) -> None:
    """Push one rebuilt shard; the target pulls the .ecx index from a
    source holder, so fail over across sources — a stale/dead entry in
    the location map must not sink the scatter."""
    if ecc_push is not None:
        # Ship the target its kernel-computed `.ecc` entries before the
        # first shard body lands (once per target, inside this worker —
        # a slow target can't stall the drain thread; cluster_encode.
        # _EccOncePush).
        ecc_push.ensure(target)
    errors: list[str] = []
    for src in sources:
        try:
            if _fault.ARMED:
                _fault.hit("ec.scatter", target=target, vid=vid,
                           shard=sid)
            rpc.call(
                f"http://{target}/admin/ec/receive_shard?volume={vid}"
                f"&shard={sid}&ecx_source={src}",
                "POST", payload, 600.0,
                headers={**rpc.PRIORITY_LOW,
                         **_flows.tag("ec.scatter")})
            return
        except rpc.RpcError as e:
            # The target responded: the failure may be its ecx pull
            # from this source — another source can fix that.
            errors.append(f"via {src}: {e}")
        except Exception as e:
            # Can't reach the target at all: no ecx_source choice will
            # help, and re-sending the full shard payload per source
            # would multiply a dead node into hours of timeouts.
            raise rpc.RpcError(
                502, f"cannot place rebuilt shard {vid}.{sid}: target "
                     f"{target} unreachable: {type(e).__name__}: {e}"
            ) from None
    raise rpc.RpcError(
        502, f"cannot place rebuilt shard {vid}.{sid} on {target}: "
             + "; ".join(errors[:4]))


def _scatter_volume(env, pool, picker, vid, locs, missing,
                    shards: list[bytes],
                    shard_crcs=None) -> list[tuple[int, str]]:
    """Push rebuilt shards to balanced targets, pulling the .ecx index
    alongside, then mount.  When `shard_crcs` carries the device-
    computed per-block CRC32-C of each rebuilt shard, the target gets
    its `.ecc` entries FIRST so receive_shard skips the CPU re-read of
    the pushed payload (and wire corruption of the push itself is
    scrub-detectable)."""
    holders = {u for urls in locs.values() for u in urls}
    sources = sorted(holders)
    placed = [(sid, picker.pick(holders)) for sid in missing]
    pusher = None
    if shard_crcs is not None:
        from .cluster_encode import _ecc_push_plan
        pusher = _ecc_push_plan(
            vid, ((target, sid, crcs)
                  for (sid, target), crcs in zip(placed, shard_crcs)))
    futs = [pool.submit(_push_shard, vid, sid, payload, target,
                        sources, pusher)
            for (sid, target), payload in zip(placed, shards)]
    for f in futs:
        f.result()
    for _sid, target in placed:
        env.vs_call(target, "/admin/ec/mount", {"volume": vid})
    return placed
