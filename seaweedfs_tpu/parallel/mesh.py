"""Mesh construction helpers.

Axes:
- "vol":  data-parallel over volumes (batched encode/rebuild)
- "col":  byte-column parallelism within a volume (the long-context analog:
  one huge byte-stream split across chips, like sequence/context
  parallelism splits a long sequence)
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None, vol_axis: int | None = None
              ) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if vol_axis is None:
        # Favor volume-parallelism; fall back to column splits.
        vol_axis = n
    col_axis = n // vol_axis
    grid = np.array(devices).reshape(vol_axis, col_axis)
    return Mesh(grid, axis_names=("vol", "col"))


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """(V, k, N) batched volumes: V over "vol", N over "col"."""
    return NamedSharding(mesh, P("vol", None, "col"))


def shard_row_sharding(mesh: Mesh) -> NamedSharding:
    """(V, S, N) survivor stacks with shard rows S over "col" — the layout
    where each chip holds whole shards (as hosts do in the cluster)."""
    return NamedSharding(mesh, P("vol", "col", None))
