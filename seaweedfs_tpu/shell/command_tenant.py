"""tenant.ls / tenant.quota / cluster.tenants — the tenancy plane.

`cluster.tenants` renders the master's cluster-wide usage rollup (the
same `/cluster/tenants` surface quota enforcement reads), one row per
tenant with its matched rule and verdict.  `tenant.ls` walks every
reachable server's `/debug/tenants` for the LIVE view — per-node stored
ledgers and sliding req/s / bytes/s meters.  `tenant.quota` shows the
declared rules and, per tenant, usage against each limit.
"""

from __future__ import annotations

from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError


def _human(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _rule_str(rule: dict | None) -> str:
    if not rule:
        return "-"
    parts = []
    if rule.get("max_bytes"):
        parts.append(f"bytes<={_human(rule['max_bytes'])}")
    if rule.get("max_objects"):
        parts.append(f"objects<={rule['max_objects']}")
    if rule.get("max_rps"):
        parts.append(f"rps<={rule['max_rps']:g}")
    if rule.get("max_mbps"):
        parts.append(f"mbps<={rule['max_mbps']:g}")
    if rule.get("soft"):
        parts.append("soft")
    if rule.get("weight", 1.0) != 1.0:
        parts.append(f"weight={rule['weight']:g}")
    return ",".join(parts) or "-"


def _rollup(env: CommandEnv) -> dict:
    try:
        out = rpc.call(f"{env.master_url}/cluster/tenants", timeout=5.0)
    except Exception as e:  # noqa: BLE001
        raise ShellError(f"master /cluster/tenants failed: {e}") from e
    if not isinstance(out, dict):
        raise ShellError("unexpected /cluster/tenants answer")
    return out


@register
class ClusterTenants(Command):
    name = "cluster.tenants"
    help = ("cluster.tenants — master-side per-tenant usage rollup "
            "(the view quota enforcement reads), with rule + verdict")

    def do(self, args: list[str], env: CommandEnv) -> str:
        out = _rollup(env)
        tenants = out.get("tenants", {})
        if not tenants:
            return "no tenant usage reported yet"
        lines = [f"{'TENANT':16} {'BYTES':>10} {'OBJECTS':>8} "
                 f"{'COLLECTIONS':>11}  {'RULE':28} VERDICT"]
        for t in sorted(tenants):
            row = tenants[t]
            over = row.get("over_quota") or []
            verdict = "ok" if not over else \
                f"over:{','.join(over)} ({row.get('enforcement', '?')})"
            ncoll = len(row.get("collections", {}))
            lines.append(
                f"{t:16} {_human(row.get('bytes', 0)):>10} "
                f"{row.get('objects', 0):>8} {ncoll:>11}  "
                f"{_rule_str(row.get('rule')):28} {verdict}")
        lines.append(f"({len(tenants)} tenants, "
                     f"{len(out.get('rules', []))} rules, "
                     f"leader {out.get('leader', env.master_url)})")
        return "\n".join(lines)


@register
class TenantLs(Command):
    name = "tenant.ls"
    help = ("tenant.ls [-server host:port] — live per-node tenant "
            "ledgers: stored bytes/objects and sliding req/s meters "
            "from every reachable /debug/tenants")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        lines: list[str] = []
        reached = 0
        for url in env.debug_servers(flags):
            try:
                out = rpc.call(f"{url}/debug/tenants", timeout=5.0)
            except Exception:  # noqa: BLE001 — role without the route
                continue
            if not isinstance(out, dict) or "stored" not in out:
                continue
            reached += 1
            lines.append(f"{out.get('node', url)}:")
            rows = out.get("stored", [])
            rates = out.get("rates", {})
            if not rows and not rates:
                lines.append("  (no tenant activity)")
            for r in rows:
                coll = r.get("collection") or "(default)"
                lines.append(
                    f"  {r['tenant']:16} {coll:12} "
                    f"{_human(r.get('bytes', 0)):>10} "
                    f"{r.get('objects', 0):>7} objects")
            for t in sorted(rates):
                m = rates[t]
                lines.append(
                    f"  {t:16} {'[rates]':12} "
                    f"{m.get('req_s', 0):.1f} req/s "
                    f"r {_human(m.get('read_bps', 0))}/s "
                    f"w {_human(m.get('write_bps', 0))}/s")
        if not reached:
            raise ShellError("no server answered /debug/tenants")
        return "\n".join(lines)


@register
class TenantQuota(Command):
    name = "tenant.quota"
    help = ("tenant.quota [tenant] — declared quota rules and usage "
            "against each limit (from the master rollup)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        _flags, rest = self.parse_flags(args)
        want = rest[0] if rest else ""
        out = _rollup(env)
        rules = out.get("rules", [])
        tenants = out.get("tenants", {})
        if want:
            rules = [r for r in rules
                     if r.get("tenant") in (want, "*")]
            tenants = {t: v for t, v in tenants.items() if t == want}
            if not rules and not tenants:
                raise ShellError(f"no rule or usage for {want!r}")
        lines = [f"{len(rules)} rules:"]
        for r in rules:
            lines.append(f"  {r.get('tenant', '?'):16} {_rule_str(r)}")
        if tenants:
            lines.append("usage:")
            for t in sorted(tenants):
                row = tenants[t]
                rule = row.get("rule") or {}
                b, o = row.get("bytes", 0), row.get("objects", 0)
                cap_b = rule.get("max_bytes", 0)
                cap_o = rule.get("max_objects", 0)
                use = [f"{_human(b)}"
                       + (f"/{_human(cap_b)}" if cap_b else ""),
                       f"{o}" + (f"/{cap_o}" if cap_o else "")
                       + " objects"]
                over = row.get("over_quota") or []
                if over:
                    use.append(f"OVER ({row.get('enforcement', '?')}: "
                               f"{','.join(over)})")
                lines.append(f"  {t:16} " + "  ".join(use))
        return "\n".join(lines)
