"""Command interface + registry (reference: weed/shell/commands.go:35-53).

Each command is a subclass with `name`, `help`, and
`do(args, env) -> str` returning its printed output.  `run_command`
parses a shell line and dispatches.
"""

from __future__ import annotations

import shlex

from .env import CommandEnv, ShellError

COMMANDS: dict[str, "Command"] = {}


class Command:
    name = ""
    help = ""

    def do(self, args: list[str], env: CommandEnv) -> str:
        raise NotImplementedError

    # -- tiny flag parser (the reference uses Go's flag.FlagSet) ------------

    @staticmethod
    def parse_flags(args: list[str]) -> tuple[dict[str, str], list[str]]:
        """-key value / -key=value pairs -> dict; the rest positional."""
        flags: dict[str, str] = {}
        rest: list[str] = []
        i = 0
        while i < len(args):
            a = args[i]
            if a.startswith("-") and len(a) > 1 and not a[1].isdigit():
                key = a.lstrip("-")
                if "=" in key:
                    key, val = key.split("=", 1)
                    flags[key] = val
                elif i + 1 < len(args) and not args[i + 1].startswith("-"):
                    flags[key] = args[i + 1]
                    i += 1
                else:
                    flags[key] = "true"
            else:
                rest.append(a)
            i += 1
        return flags, rest


def register(cls: type[Command]) -> type[Command]:
    COMMANDS[cls.name] = cls()
    return cls


def run_command(env: CommandEnv, line: str) -> str:
    parts = shlex.split(line)
    if not parts:
        return ""
    name, args = parts[0], parts[1:]
    if name in ("help", "?"):
        if args and args[0] in COMMANDS:
            return COMMANDS[args[0]].help
        return "\n".join(sorted(COMMANDS))
    cmd = COMMANDS.get(name)
    if cmd is None:
        raise ShellError(f"unknown command: {name} (try `help`)")
    return cmd.do(args, env)
