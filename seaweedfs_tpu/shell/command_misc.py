"""lock/unlock, collection.*, cluster status commands.

Reference: weed/shell/command_fs_lock_unlock.go, command_collection_*.go,
command_cluster_ps-style status.
"""

from __future__ import annotations

from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError


@register
class Lock(Command):
    name = "lock"
    help = "lock — acquire the exclusive admin lock (required by mutators)"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.lock()
        return "locked"


@register
class Unlock(Command):
    name = "unlock"
    help = "unlock — release the exclusive admin lock"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.unlock()
        return "unlocked"


@register
class CollectionList(Command):
    name = "collection.list"
    help = "collection.list"

    def do(self, args: list[str], env: CommandEnv) -> str:
        resp = rpc.call(f"{env.master_url}/col/list")
        cols = resp.get("collections", [])
        return "\n".join(c or "(default)" for c in cols) or "no collections"


@register
class CollectionDelete(Command):
    name = "collection.delete"
    help = "collection.delete -collection <name>"

    def do(self, args: list[str], env: CommandEnv) -> str:
        env.confirm_is_locked()
        flags, rest = self.parse_flags(args)
        name = flags.get("collection") or (rest[0] if rest else "")
        if not name:
            # An empty name would match the default collection and delete
            # every non-collection volume in the cluster.
            raise ShellError(
                "collection.delete requires -collection <name>")
        resp = rpc.call_json(
            f"{env.master_url}/col/delete?collection={name}")
        return (f"deleted collection {name!r} "
                f"({resp.get('deleted_replicas', 0)} replicas)")


@register
class ClusterStatus(Command):
    name = "cluster.status"
    help = "cluster.status — leader + basic cluster info"

    def do(self, args: list[str], env: CommandEnv) -> str:
        resp = rpc.call(f"{env.master_url}/cluster/status")
        return "\n".join(f"{k}: {v}" for k, v in sorted(resp.items()))
