"""events.ls / cluster.check — the cluster timeline and health rollup.

Events are recorded per process into a bounded ring (events/journal.py)
and served by each server's `/debug/events`.  `events.ls` aggregates
across every reachable server — master, all registered volume servers,
and the filer when configured — deduplicating by each journal's
(token, seq) identity, because roles sharing one process (test stacks,
`weed server`) share one journal.  `cluster.check` renders the master's
`/cluster/healthz` rollup: per-node liveness (heartbeat age, breaker
state, disk fill) and per-volume/EC-volume health.
"""

from __future__ import annotations

import time

from ..cluster import rpc
from ..events import TYPES
from .commands import Command, register
from .env import CommandEnv, ShellError


@register
class EventsLs(Command):
    name = "events.ls"
    help = ("events.ls [-type T] [-severity S] [-since TS] [-limit N] "
            "[-server host:port] [-types] — one cluster timeline "
            "merged from every reachable server's /debug/events")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        if flags.get("types"):
            lines = [f"{'TYPE':22}  DESCRIPTION"]
            for name in sorted(TYPES):
                lines.append(f"{name:22}  {TYPES[name]}")
            return "\n".join(lines)
        type_ = flags.get("type", "")
        if type_ and type_ not in TYPES:
            raise ShellError(f"unknown event type {type_!r} "
                             "(events.ls -types)")
        limit = int(flags.get("limit", "50"))
        qs_parts = [f"type={type_}" if type_ else "",
                    f"severity={flags['severity']}"
                    if flags.get("severity") else "",
                    f"since={flags['since']}"
                    if flags.get("since") else ""]
        qs = "&".join(p for p in qs_parts if p)
        merged: dict[tuple, dict] = {}
        reached = 0
        for url in env.debug_servers(flags):
            try:
                out = rpc.call(f"{url}/debug/events"
                               + (f"?{qs}" if qs else ""), timeout=5.0)
            except Exception:  # noqa: BLE001 — endpoint off / gone
                continue
            if not isinstance(out, dict):
                continue
            reached += 1
            token = out.get("token", url)
            for ev in out.get("events", []):
                merged.setdefault((token, ev.get("seq", 0)), ev)
        if not reached:
            raise ShellError("no /debug/events endpoint reachable")
        rows = sorted(merged.values(), key=lambda e: e["ts"])[-limit:]
        if not rows:
            return "no events recorded"
        lines = [f"{'AT':12}  {'SEV':5}  {'TYPE':22}  {'NODE':21}  "
                 "ATTRS"]
        for ev in rows:
            at = time.strftime("%H:%M:%S",
                               time.localtime(ev["ts"])) \
                + f".{int(ev['ts'] % 1 * 1000):03d}"
            attrs = " ".join(f"{k}={v}" for k, v in
                             sorted(ev.get("attrs", {}).items()))
            if ev.get("trace_id"):
                attrs += f"  trace={ev['trace_id']}"
            lines.append(f"{at:12}  {ev['severity']:5}  "
                         f"{ev['type']:22}  "
                         f"{ev.get('node', '') or '-':21}  {attrs}")
        return "\n".join(lines)


@register
class ClusterDrain(Command):
    name = "cluster.drain"
    help = ("cluster.drain -node host:port [-grace N] — gracefully "
            "drain one volume server: it refuses new writes (503 + "
            "Retry-After), finishes in-flight requests up to the "
            "grace, then goodbyes the master (unregistered "
            "immediately, no dead-sweep window).  The rolling-upgrade "
            "step: drain, restart the process, verify with "
            "cluster.check, move to the next node")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        node = flags.get("node", "")
        if not node:
            raise ShellError("cluster.drain -node host:port is "
                             "required")
        grace = float(flags.get("grace", "30"))
        base = node if "://" in node else f"http://{node}"
        try:
            out = rpc.call_json(f"{base}/admin/drain", "POST",
                                {"grace": grace},
                                timeout=grace + 10.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(
                f"cannot drain {node}: {e}") from None
        if out.get("already"):
            return f"{node} was already draining"
        return (f"{node} drained: new writes refused, "
                f"{out.get('inflight', 0)} request(s) still in flight "
                f"at goodbye; safe to stop/upgrade the process")


@register
class ClusterHot(Command):
    name = "cluster.hot"
    help = ("cluster.hot [-k N] [-dimension volume|needle|client] "
            "[-node host:port] — heavy hitters from every volume "
            "server's /debug/hot (space-saving top-k): the hot "
            "volumes, needles, and client IPs that decide where a "
            "cache or small-file pack pays off.  The true cluster "
            "count of a KEY lies within [count-err, count+err]")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        k = int(flags.get("k", "10"))
        want_dim = flags.get("dimension", "")
        if flags.get("node"):
            nodes = [flags["node"]]
        else:
            try:
                nodes = [n["url"] for n in env.data_nodes()]
            except Exception as e:  # noqa: BLE001
                raise ShellError(f"cannot list volume servers: {e}") \
                    from None
        # Pull each node's FULL table, then merge per (dimension, op).
        # A key a full node evicted may hold up to that node's minimum
        # counter there — fold that into the key's error (as under-
        # count slack) instead of pretending the sum is still a pure
        # upper bound; a non-full table means absence = exactly zero.
        node_tables: list[dict] = []
        reached = 0
        for node in nodes:
            base = node if "://" in node else f"http://{node}"
            try:
                out = rpc.call(f"{base}/debug/hot?k=1000000",
                               timeout=5.0)
            except Exception:  # noqa: BLE001 — node gone
                continue
            if isinstance(out, dict):
                reached += 1
                node_tables.append(out)
        if not reached:
            raise ShellError("no /debug/hot endpoint reachable")
        # First pass: per (dimension, op), each node's table + the
        # slack a full table implies for keys it evicted.
        per_dim: dict[tuple[str, str], list[tuple[dict, int]]] = {}
        totals: dict[tuple[str, str], int] = {}
        for out in node_tables:
            capacity = out.get("capacity", 0)
            for dim, ops in out.get("dimensions", {}).items():
                for op, data in ops.items():
                    dkey = (dim, op)
                    totals[dkey] = totals.get(dkey, 0) \
                        + data.get("total", 0)
                    rows = data.get("top", [])
                    table = {str(r["key"]): r for r in rows}
                    full = capacity and len(rows) >= capacity
                    node_min = min((r["count"] for r in rows),
                                   default=0) if full else 0
                    per_dim.setdefault(dkey, []).append(
                        (table, node_min))
        # Second pass: union of keys; a node that tracks the key
        # contributes its count+error, a full node that evicted it
        # contributes up to its minimum counter as error slack.
        merged: dict[tuple[str, str], dict] = {}
        for dkey, tables in per_dim.items():
            bucket = merged.setdefault(dkey, {})
            union: set[str] = set()
            for table, _ in tables:
                union.update(table)
            for key in union:
                count = err = 0
                for table, node_min in tables:
                    r = table.get(key)
                    if r is not None:
                        count += r["count"]
                        err += r["error"]
                    else:
                        err += node_min
                bucket[key] = [count, err]
        lines = []
        for (dim, op) in sorted(merged):
            if want_dim and dim != want_dim:
                continue
            total = totals.get((dim, op), 0)
            if not total:
                continue
            lines.append(f"{dim} ({op}, {total} ops):")
            lines.append(f"  {'KEY':24} {'COUNT':>9} {'ERR':>7}  SHARE")
            rows = sorted(merged[(dim, op)].items(),
                          key=lambda kv: kv[1][0], reverse=True)[:k]
            for key, (count, err) in rows:
                share = 100.0 * count / total if total else 0.0
                lines.append(f"  {key:24} {count:9d} {err:7d}  "
                             f"{share:5.1f}%")
        return "\n".join(lines) if lines else \
            "no traffic recorded yet"


@register
class ClusterConns(Command):
    name = "cluster.conns"
    help = ("cluster.conns [-node host:port] [-limit N] — open-"
            "connection census from every reachable server's "
            "/debug/conns: transport, per-state counts (idle / "
            "reading / handling), and the oldest connections.  The "
            "front-door dashboard: a slow-loris flood shows up as "
            "piles of 'reading' conns, a worker-pool stall as "
            "'handling' ones")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        limit = int(flags.get("limit", "5"))
        if flags.get("node"):
            nodes = [flags["node"]]
        else:
            nodes = [env.master_url]
            try:
                nodes += [n["url"] for n in env.data_nodes()]
            except Exception:  # noqa: BLE001 — master-only census
                pass
        lines = [f"{'NODE':21}  {'TRANSPORT':9}  {'OPEN':>5}  STATES"]
        detail: list[str] = []
        reached = 0
        for node in nodes:
            base = node if "://" in node else f"http://{node}"
            try:
                out = rpc.call(f"{base}/debug/conns?limit={limit}",
                               timeout=5.0)
            except Exception:  # noqa: BLE001 — node gone
                continue
            if not isinstance(out, dict):
                continue
            reached += 1
            name = base.split("://", 1)[1]
            states = ",".join(f"{k}={v}" for k, v in
                              sorted(out.get("states", {}).items())) \
                or "-"
            lines.append(f"{name:21}  {out.get('transport', '?'):9}  "
                         f"{out.get('open', 0):5d}  {states}")
            for c in out.get("conns", []):
                detail.append(
                    f"  {name:21}  {c.get('peer', '?'):21} "
                    f"{c.get('state', '?'):9} "
                    f"age={c.get('age_s', 0.0):7.1f}s "
                    f"idle={c.get('idle_s', 0.0):6.1f}s "
                    f"reqs={c.get('requests', 0)}")
        if not reached:
            raise ShellError("no /debug/conns endpoint reachable")
        if detail:
            lines.append("")
            lines.append(f"oldest {limit} per node:")
            lines.extend(detail)
        return "\n".join(lines)


@register
class ClusterFlows(Command):
    name = "cluster.flows"
    help = ("cluster.flows [-purpose P] [-watch] [-interval S] "
            "[-count N] — the wire-flow traffic matrix from the "
            "master's /cluster/flows: per-link per-purpose bytes "
            "(user.read, replicate.fanout, ec.gather, ...), rates "
            "from successive heartbeat samples, top-talker links, "
            "bandwidth-budget status, and the conservation verdict "
            "(every sender's count must match its receiver within "
            "1%).  -watch repolls every -interval seconds (default "
            "2) until interrupted (or -count polls)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        purpose = flags.get("purpose", "")
        watch = flags.get("watch") == "true"
        interval = float(flags.get("interval", "2"))
        count = int(flags.get("count", "0"))
        q = f"?purpose={purpose}" if purpose else ""
        if not watch:
            return self._render(self._fetch(env, q))
        import time as _time
        polls = 0
        out = ""
        try:
            while True:
                out = self._render(self._fetch(env, q))
                polls += 1
                if count and polls >= count:
                    break
                print(out)
                print("---")
                _time.sleep(interval)
        except KeyboardInterrupt:
            pass
        return out

    @staticmethod
    def _fetch(env: CommandEnv, q: str) -> dict:
        try:
            doc = rpc.call(f"{env.master_url}/cluster/flows{q}",
                           timeout=10.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(
                f"cannot reach {env.master_url}/cluster/flows: "
                f"{e}") from None
        if not isinstance(doc, dict):
            raise ShellError(f"unexpected /cluster/flows reply: "
                             f"{doc!r}")
        return doc

    @staticmethod
    def _render(doc: dict) -> str:
        cons = doc.get("conservation", {})
        lines = [f"nodes={len(doc.get('nodes', []))}  "
                 f"cells={len(doc.get('cells', []))}  conservation="
                 + ("OK" if cons.get("ok") else "VIOLATED")
                 + f" ({cons.get('paired_cells', 0)} paired)"]
        for v in cons.get("violations", []):
            lines.append(f"  !! {v['src']} -> {v['dst']} "
                         f"[{v['purpose']}]: sent={v['sent']} "
                         f"recv={v['recv']} skew={v['skew']}")
        purposes = doc.get("purposes", {})
        if purposes:
            lines.append("")
            lines.append(f"{'PURPOSE':18}  {'GB':>12}")
            for p, ent in purposes.items():
                lines.append(f"{p:18}  {ent['gb']:12.6f}")
        cells = doc.get("cells", [])
        if cells:
            lines.append("")
            lines.append(f"{'SRC':21}  {'DST':21}  {'PURPOSE':18}  "
                         f"{'SENT':>12}  {'RECV':>12}  {'B/S':>10}  "
                         f"{'OPS':>6}")
            for c in cells:
                sent = c.get("sent_bytes")
                recv = c.get("recv_bytes")
                ops = max(c.get("sent_ops", 0), c.get("recv_ops", 0))
                lines.append(
                    f"{c['src']:21}  {c['dst']:21}  "
                    f"{c['purpose']:18}  "
                    f"{'-' if sent is None else sent:>12}  "
                    f"{'-' if recv is None else recv:>12}  "
                    f"{c.get('rate_bps', 0.0):10.0f}  {ops:6d}")
        top = doc.get("top_talkers", [])
        if top:
            lines.append("")
            lines.append("top talkers: " + ", ".join(
                f"{t['src']}->{t['dst']} ({t['bytes']}B)"
                for t in top[:5]))
        breached = []
        for node, status in sorted(doc.get("budgets", {}).items()):
            for p, st in sorted(status.items()):
                state = "BREACH" if st.get("breached") else "ok"
                breached.append(
                    f"  {node}  {p}: {st.get('rate_bps', 0):.0f} of "
                    f"{st.get('limit_bps', 0):.0f} B/s [{state}]")
        if breached:
            lines.append("")
            lines.append("budgets:")
            lines.extend(breached)
        return "\n".join(lines)


@register
class ClusterRoofline(Command):
    name = "cluster.roofline"
    help = ("cluster.roofline [-node host:port] [-kernel K] [-codec C] "
            "[-save out.json] [-diff baseline.json] — the device "
            "roofline rollup from the master's /cluster/device (or one "
            "node's /debug/device with -node): probed peaks, the "
            "per-kernel table (count, seconds, bytes, GF(2) work, "
            "achieved fraction of roofline p50/p95), per-node pipeline "
            "occupancy with bubble attribution, and collapse "
            "warnings.  -save writes the table as JSON; -diff ranks "
            "achieved-fraction deltas vs a saved baseline (the "
            "kernel-regression gate)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        import json as _json
        if flags.get("node"):
            node = flags["node"]
            base = node if "://" in node else f"http://{node}"
            url = f"{base}/debug/device"
        else:
            q = []
            if flags.get("kernel"):
                q.append(f"kernel={flags['kernel']}")
            if flags.get("codec"):
                q.append(f"codec={flags['codec']}")
            qs = ("?" + "&".join(q)) if q else ""
            url = f"{env.master_url}/cluster/device{qs}"
        try:
            doc = rpc.call(url, timeout=15.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(f"cannot reach {url}: {e}") from None
        if not isinstance(doc, dict):
            raise ShellError(f"unexpected reply from {url}: {doc!r}")
        table = doc.get("kernels", [])
        if flags.get("node"):
            # /debug/device rows are unmerged; apply filters locally.
            if flags.get("kernel"):
                table = [r for r in table
                         if r["kernel"] == flags["kernel"]]
            if flags.get("codec"):
                table = [r for r in table
                         if r["codec"] == flags["codec"]]
        lines = []
        peaks = doc.get("peaks") or {}
        mm = peaks.get("matmul_flops") or {}
        if peaks:
            mmtxt = "  ".join(
                f"{d}={v / 1e9:.1f}GF/s" for d, v in sorted(mm.items())
                if v)
            lines.append(
                f"peaks[{peaks.get('backend', '?')}]: {mmtxt}  "
                f"membw={peaks.get('membw_bps', 0) / 1e9:.2f}GB/s  "
                f"h2d={peaks.get('h2d_bps', 0) / 1e9:.2f}GB/s")
        if table:
            lines.append("")
            lines.append(f"{'KERNEL':22} {'CODEC':12} {'DTYPE':5} "
                         f"{'GEOMETRY':16} {'COUNT':>7} {'SECONDS':>9} "
                         f"{'BYTES':>13} {'WORK':>15} {'P50':>6} "
                         f"{'P95':>6}")
            for r in table:
                p50, p95 = r.get("achieved_p50"), r.get("achieved_p95")
                lines.append(
                    f"{r['kernel']:22} {r['codec']:12} {r['dtype']:5} "
                    f"{r['geometry']:16} {r['count']:7d} "
                    f"{r['seconds']:9.4f} {r['bytes']:13d} "
                    f"{r['work']:15d} "
                    f"{'-' if p50 is None else format(p50, '6.3f')} "
                    f"{'-' if p95 is None else format(p95, '6.3f')}")
        else:
            lines.append("no kernel invocations recorded yet")
        occ_lines = []
        if flags.get("node"):
            occ = (doc.get("occupancy") or {}).get("latest", {})
            for kind, ent in sorted(occ.items()):
                frac = ent.get("fraction")
                occ_lines.append(
                    f"  {doc.get('node', '?'):21} {kind:8} "
                    f"{'-' if frac is None else format(frac, '.0%'):>5}"
                    f"  starved by {ent.get('starving_stage') or '-'}")
        else:
            for nurl, nd in sorted((doc.get("nodes") or {}).items()):
                occ = (nd.get("occupancy") or {}).get("latest", {})
                for kind, ent in sorted(occ.items()):
                    frac = ent.get("fraction")
                    occ_lines.append(
                        f"  {nurl:21} {kind:8} "
                        f"{'-' if frac is None else format(frac, '.0%'):>5}"
                        f"  starved by {ent.get('starving_stage') or '-'}")
        if occ_lines:
            lines.append("")
            lines.append("pipeline occupancy (device stage):")
            lines.extend(occ_lines)
        for w in doc.get("warnings", []):
            lines.append(f"  !! {w}")
        if flags.get("save"):
            with open(flags["save"], "w") as f:
                _json.dump({"ts": time.time(), "kernels": table}, f,
                           indent=2, sort_keys=True)
            lines.append("")
            lines.append(f"wrote {len(table)} kernel rows to "
                         f"{flags['save']}")
        if flags.get("diff"):
            try:
                with open(flags["diff"]) as f:
                    base_doc = _json.load(f)
            except (OSError, ValueError) as e:
                raise ShellError(
                    f"cannot read baseline {flags['diff']}: {e}") \
                    from None
            base = {(r["kernel"], r["codec"], r["dtype"],
                     r["geometry"]): r
                    for r in base_doc.get("kernels", [])}
            cur = {(r["kernel"], r["codec"], r["dtype"],
                    r["geometry"]): r for r in table}
            deltas = []
            for key in set(base) | set(cur):
                b = (base.get(key) or {}).get("achieved_p50")
                c = (cur.get(key) or {}).get("achieved_p50")
                if b is None and c is None or b == c:
                    continue
                deltas.append((key, b, c,
                               (c or 0.0) - (b or 0.0)))
            deltas.sort(key=lambda d: d[3])
            lines.append("")
            lines.append(f"{'DELTA':>7}  {'BASE':>6}  {'NOW':>6}  "
                         "KERNEL/CODEC/DTYPE/GEOMETRY (achieved p50; "
                         "negative = regression)")
            for key, b, c, d in deltas:
                lines.append(
                    f"{d:+7.3f}  "
                    f"{'-' if b is None else format(b, '6.3f')}  "
                    f"{'-' if c is None else format(c, '6.3f')}  "
                    f"{'/'.join(key)}")
            if not deltas:
                lines.append("no achieved-fraction movement vs "
                             "baseline")
        return "\n".join(lines)


@register
class ClusterCheck(Command):
    name = "cluster.check"
    help = ("cluster.check — health rollup from the master's "
            "/cluster/healthz: node liveness, disk fill, volume and "
            "EC-shard health; exit text is HEALTHY or the problem list")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        url = flags.get("server")
        base = (url if "://" in url else f"http://{url}") if url \
            else env.master_url
        try:
            status, doc = rpc.call_status(f"{base}/cluster/healthz",
                                          timeout=10.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(
                f"cannot reach {base}/cluster/healthz: {e}") from None
        if not isinstance(doc, dict):
            raise ShellError(f"unexpected healthz reply: {doc!r}")
        lines = [("HEALTHY" if doc.get("healthy")
                  else f"UNHEALTHY (HTTP {status})")
                 + f"  leader={doc.get('leader', '?')}"]
        for p in doc.get("problems", []):
            lines.append(f"  !! {p}")
        nodes = doc.get("nodes", [])
        if nodes:
            lines.append("")
            lines.append(f"{'NODE':21}  {'HB AGE':>7}  {'BREAKER':9}  "
                         f"{'VOLS':>4}  {'EC':>3}  DISK")
            for n in nodes:
                disk = ", ".join(
                    f"{d.get('dir', '?')} {d.get('percent_used', 0):.0f}%"
                    for d in n.get("disks", [])) or "-"
                lines.append(
                    f"{n['node']:21}  {n['heartbeat_age']:7.1f}  "
                    f"{n['breaker']:9}  {n['volumes']:4d}  "
                    f"{n['ec_shards']:3d}  {disk}")
        ec = doc.get("ec_volumes", [])
        if ec:
            lines.append("")
            lines.append(f"{'EC VOLUME':>9}  {'SHARDS':>6}  MISSING")
            for v in ec:
                missing = ",".join(map(str, v["missing"])) or "-"
                lines.append(f"{v['id']:9d}  {v['present']:6d}  "
                             f"{missing}")
        ro = [v for v in doc.get("volumes", []) if v.get("read_only")]
        if ro:
            lines.append("")
            lines.append("readonly volumes: " + ", ".join(
                f"{v['id']}@{v['node']}" for v in ro))
        placement = (doc.get("placement") or {}).get("warnings", [])
        if placement:
            lines.append("")
            for w in placement:
                lines.append(f"  ~ placement: {w}")
        rep = doc.get("repair") or {}
        if rep:
            state = "armed" if rep.get("enabled") else "disarmed"
            if rep.get("paused"):
                state += ", paused"
            lines.append("")
            lines.append(f"repair autopilot: {state}  "
                         f"queue={rep.get('queue', 0)}  "
                         f"inflight={rep.get('inflight', 0)}")
        filers = (doc.get("filers") or {}).get("nodes", [])
        if filers:
            lines.append("")
            lines.append(f"{'FILER':29}  {'HB AGE':>7}  {'PRIMARY OF':>10}")
            for f in filers:
                mark = "" if f.get("alive") else "  !! dead"
                lines.append(
                    f"{f['url']:29}  {f['age_seconds']:7.1f}  "
                    f"{f['shards_primary']:10d}{mark}")
        return "\n".join(lines)


@register
class ClusterRepair(Command):
    name = "cluster.repair"
    help = ("cluster.repair [status|run|pause|resume] [-kind="
            "replicate|ec] — the durability autopilot: `status` "
            "renders the risk-ranked repair queue, in-flight repairs "
            "with phase, the dry-run plan (with hysteresis/suppression "
            "annotations) and the MTTR histogram; `run` drains one "
            "synchronous repair pass (works while the daemon is "
            "disarmed); `pause`/`resume` gate the armed daemon's "
            "executors at runtime")

    @staticmethod
    def _render_rows(title: str, rows: list[dict]) -> list[str]:
        lines = ["", f"{title} ({len(rows)}):"]
        for r in rows:
            extra = f" missing={len(r.get('missing', []))}" \
                if r.get("kind") == "ec" else \
                f" rp={r.get('replication', '?')}"
            note = ""
            if r.get("suppressed"):
                note = "  (drain-fenced)"
            elif "degraded_for" in r:
                note = f"  degraded {r['degraded_for']:.1f}s"
            lines.append(
                f"  risk={r['risk']}  {r['kind']:9}  "
                f"volume {r['volume']:6d}  {r.get('have', '?')}/"
                f"{r.get('want', '?')}  phase={r['phase']}"
                f"{extra}{note}")
        return lines

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, rest = self.parse_flags(args)
        sub = rest[0] if rest else "status"
        base = env.master_url
        if sub == "status":
            doc = rpc.call(f"{base}/cluster/repair", timeout=30.0)
            state = "armed" if doc.get("enabled") else "disarmed"
            if doc.get("paused"):
                state += ", PAUSED"
            lines = [f"durability autopilot: {state}  "
                     f"delay={doc.get('delay_seconds', 0):.0f}s  "
                     f"concurrent={doc.get('concurrent', 0)}"]
            if doc.get("queue"):
                lines += self._render_rows("queued", doc["queue"])
            if doc.get("inflight"):
                lines += self._render_rows("in flight",
                                           doc["inflight"])
            if doc.get("plan"):
                lines += self._render_rows("plan (live scan)",
                                           doc["plan"])
            m = doc.get("mttr") or {}
            if m.get("count"):
                lines.append("")
                lines.append(
                    f"MTTR over last {m['count']} repairs: "
                    f"mean {m['mean_seconds']}s, "
                    f"max {m['max_seconds']}s")
                hist = m.get("histogram") or {}
                lines.append("  " + "  ".join(
                    f"{k.removeprefix('le_')}s:{v}"
                    for k, v in hist.items() if v))
            if len(lines) == 1:
                lines.append("nothing degraded — queue empty")
            return "\n".join(lines)
        if sub == "run":
            env.confirm_is_locked()
            kinds = [flags["kind"]] if flags.get("kind") else None
            doc = rpc.call_json(f"{base}/cluster/repair/run",
                                payload={"kinds": kinds},
                                timeout=600.0)
            lines = [f"ran {doc.get('ran', 0)} repairs"]
            for r in doc.get("results", []):
                lines.append(
                    f"  {r['kind']:9}  volume {r['volume']:6d}  "
                    f"{r.get('outcome', '?')}"
                    + (f"  ({r['error']})" if r.get("error") else ""))
            for r in doc.get("trimmed", []):
                lines.append(f"  dedupe     volume {r['volume']:6d}  "
                             f"trimmed surplus copy on {r['node']}")
            return "\n".join(lines)
        if sub in ("pause", "resume"):
            env.confirm_is_locked()
            doc = rpc.call_json(f"{base}/cluster/repair/{sub}",
                                payload={}, timeout=30.0)
            return ("autopilot paused" if doc.get("paused")
                    else "autopilot resumed")
        raise ShellError(f"unknown subcommand {sub!r} "
                         "(status|run|pause|resume)")


@register
class FilerShardsLs(Command):
    name = "filer.shards.ls"
    help = ("filer.shards.ls — the master's filer shard map: per-shard "
            "primary, fencing epoch, followers, and each registered "
            "filer's journal positions (metadata-HA plane; empty when "
            "the master runs without -filer.shards)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        try:
            doc = rpc.call(f"{env.master_url}/cluster/filer/shards",
                           timeout=10.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(
                f"cannot read the shard map: {e}") from None
        assert isinstance(doc, dict)
        if not doc.get("num_shards"):
            return ("metadata plane disarmed "
                    "(master started without -filer.shards)")
        lines = [f"{doc['num_shards']} shards, map version "
                 f"{doc.get('version', 0)}", "",
                 f"{'SHARD':>5}  {'EPOCH':>5}  {'PRIMARY':29}  FOLLOWERS"]
        for k in sorted((doc.get("shards") or {}), key=int):
            row = doc["shards"][k]
            lines.append(
                f"{int(k):5d}  {row.get('epoch', 0):5d}  "
                f"{row.get('primary') or '(none)':29}  "
                + (", ".join(row.get("followers", [])) or "-"))
        filers = doc.get("filers", [])
        if filers:
            lines.append("")
            lines.append(f"{'FILER':29}  {'ALIVE':5}  JOURNALS "
                         "(shard:last_seq/applied)")
            for f in filers:
                js = " ".join(
                    f"{k}:{v.get('last_seq', 0)}/"
                    f"{v.get('applied_seq', 0)}"
                    for k, v in sorted(f.get("shards", {}).items(),
                                       key=lambda kv: int(kv[0]))) \
                    or "-"
                lines.append(f"{f['url']:29}  "
                             f"{'yes' if f.get('alive') else 'NO':5}  "
                             f"{js}")
        return "\n".join(lines)


@register
class FilerShardsMove(Command):
    name = "filer.shards.move"
    help = ("filer.shards.move -shard N -to http://host:port — "
            "demote-first primary transfer: the old primary stops "
            "acking before the new one exists anywhere (mid-move the "
            "shard fails closed), then the epoch bumps and the target "
            "acquires; clients re-route on their next 409/map refresh")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        if "shard" not in flags or "to" not in flags:
            raise ShellError(
                "filer.shards.move -shard N -to url is required")
        shard = int(flags["shard"])
        to = flags["to"]
        to = to if "://" in to else f"http://{to}"
        try:
            out = rpc.call_json(
                f"{env.master_url}/cluster/filer/shards/move", "POST",
                {"shard": shard, "to": to}, timeout=30.0)
        except Exception as e:  # noqa: BLE001
            raise ShellError(f"move failed: {e}") from None
        if out.get("already"):
            return f"shard {shard} already primary on {to}"
        return (f"shard {shard} moved to {to} at epoch "
                f"{out.get('epoch', '?')} (old primary "
                f"{out.get('old_primary') or '(none)'} fenced)")
