"""Admin shell: command registry + maintenance/EC lifecycle commands.

Reference: weed/shell/ — the `weed shell` REPL with its `command` interface
(shell/commands.go:35-42) and the ec.*/volume.* maintenance command suite.
Commands here drive the cluster purely through the master/volume-server
RPC surfaces, exactly as the reference shell drives gRPC.
"""

from .commands import COMMANDS, Command, run_command  # noqa: F401
from .env import CommandEnv, ShellError  # noqa: F401

# Importing the command modules registers them.
from . import command_ec  # noqa: F401,E402
from . import command_fs  # noqa: F401,E402
from . import command_volume  # noqa: F401,E402
from . import command_misc  # noqa: F401,E402
from . import command_trace  # noqa: F401,E402
from . import command_fault  # noqa: F401,E402
from . import command_cluster  # noqa: F401,E402
from . import command_profile  # noqa: F401,E402
from . import command_mirror  # noqa: F401,E402
from . import command_lifecycle  # noqa: F401,E402
from . import command_tenant  # noqa: F401,E402
