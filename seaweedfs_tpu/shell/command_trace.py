"""trace.ls / trace.get — browse distributed request traces.

Traces are recorded per process into a bounded ring (trace/tracer.py)
and served by each server's `/debug/traces` (mounted when the server
was started with SEAWEEDFS_TPU_TRACES=1).  These commands aggregate
across every reachable server — master, all registered volume servers,
and the filer when configured — because in a multi-process deployment
each process only holds its own spans of a trace.
"""

from __future__ import annotations

from ..cluster import rpc
from .commands import Command, register
from .env import CommandEnv, ShellError


def _fetch(url: str, qs: str) -> dict | None:
    try:
        out = rpc.call(f"{url}/debug/traces{qs}", timeout=5.0)
        return out if isinstance(out, dict) else None
    except Exception:  # noqa: BLE001 — endpoint off / server gone
        return None


@register
class TraceLs(Command):
    name = "trace.ls"
    help = ("trace.ls [-server host:port] [-limit N] — list recent "
            "traces (needs servers started with SEAWEEDFS_TPU_TRACES=1)")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, _rest = self.parse_flags(args)
        limit = int(flags.get("limit", "50"))
        merged: dict[str, dict] = {}
        reached = 0
        for url in env.debug_servers(flags):
            out = _fetch(url, f"?limit={limit}")
            if out is None:
                continue
            reached += 1
            for s in out.get("traces", []):
                cur = merged.get(s["trace_id"])
                if cur is None:
                    merged[s["trace_id"]] = dict(s)
                elif (cur["spans"], cur["start"], cur["duration_ms"]) \
                        == (s["spans"], s["start"], s["duration_ms"]):
                    # Identical view = servers sharing one in-process
                    # buffer (test stacks): don't double-count.
                    continue
                else:  # the same trace seen from another process
                    cur["spans"] += s["spans"]
                    cur["duration_ms"] = max(cur["duration_ms"],
                                             s["duration_ms"])
                    cur["services"] = sorted(set(cur["services"])
                                             | set(s["services"]))
                    if s["start"] < cur["start"]:
                        cur["start"], cur["root"] = s["start"], s["root"]
        if not reached:
            raise ShellError(
                "no /debug/traces endpoint reachable — start servers "
                "with SEAWEEDFS_TPU_TRACES=1")
        rows = sorted(merged.values(), key=lambda s: -s["start"])[:limit]
        if not rows:
            return "no traces recorded"
        lines = [f"{'TRACE':32}  {'MS':>9}  {'SPANS':>5}  ROOT"]
        for s in rows:
            lines.append(
                f"{s['trace_id']:32}  {s['duration_ms']:9.2f}  "
                f"{s['spans']:5d}  {s['root']} "
                f"[{','.join(s['services'])}]")
        return "\n".join(lines)


@register
class TraceGet(Command):
    name = "trace.get"
    help = ("trace.get <trace_id> [-server host:port] — span tree of "
            "one trace, aggregated across all reachable servers")

    def do(self, args: list[str], env: CommandEnv) -> str:
        flags, rest = self.parse_flags(args)
        if not rest:
            raise ShellError("trace.get requires a trace id (trace.ls)")
        trace_id = rest[0]
        spans: dict[str, dict] = {}
        for url in env.debug_servers(flags):
            out = _fetch(url, f"?trace={trace_id}")
            if out is None:
                continue
            for s in out.get("spans", []):
                spans.setdefault(s["span_id"], s)
        if not spans:
            raise ShellError(f"trace {trace_id} not found on any server")
        children: dict[str, list[dict]] = {}
        roots: list[dict] = []
        for s in spans.values():
            if s["parent_id"] and s["parent_id"] in spans:
                children.setdefault(s["parent_id"], []).append(s)
            else:
                roots.append(s)  # true root, or an orphan whose parent
                #                  lives in an unreachable process
        lines = [f"trace {trace_id}: {len(spans)} spans"]

        def render(s: dict, depth: int) -> None:
            attrs = " ".join(f"{k}={v}" for k, v in
                             sorted(s["attrs"].items()))
            mark = "!" if s["status"] == "error" else ""
            lines.append(
                f"{'  ' * depth}{s['duration_ms']:9.2f}ms  "
                f"[{s['service']}] {s['name']}{mark}"
                + (f"  {attrs}" if attrs else ""))
            for c in sorted(children.get(s["span_id"], []),
                            key=lambda x: x["start"]):
                render(c, depth + 1)

        for root in sorted(roots, key=lambda s: s["start"]):
            render(root, 0)
        return "\n".join(lines)
